"""Lock discipline for the threaded serve stack, statically proven.

PR 8 made the stack genuinely multi-threaded: a dispatcher thread
coalescing eval batches under a ``Condition``, thread-per-session
players, admission locks, the watchdog/hang-protection workers, the
data-prefetch thread. The conventions that keep that correct — who
may touch ``ServePool._sessions``, in what order locks nest, what
must never run inside a critical section — live in comments and
would otherwise fail first in production, under load, as a deadlock
or a torn read. This family checks them at lint time against ONE
declared model, the same model the runtime harness
(:mod:`rocalphago_tpu.analysis.lockcheck`) checks at test time.

The declared model:

* **lock attributes** — ``self._lock = threading.Lock()`` (also
  ``RLock``/``Condition`` and the :mod:`..lockcheck` factories
  ``make_lock``/``make_rlock``/``make_condition``), or a module-level
  ``_lock = ...``. A lock's identity is ``Class.attr`` (or
  ``module.name`` for module-level locks) — the SAME labels the
  lockcheck wrappers carry at runtime, so the observed and static
  graphs reconcile.
* **guarded attributes** — a ``# guarded-by: self._lock`` comment on
  the attribute's defining assignment declares which lock protects
  it. ``__init__``/``__del__`` are construction/teardown and exempt.

Rules:

* ``unguarded-attr-access`` — a guarded attribute touched by a
  method without holding its declared lock;
* ``guarded-by-unknown-lock`` — the annotation names a lock the
  class/module never creates (typo guard: a misspelled annotation
  would silently guard nothing);
* ``lock-order-inversion`` — a cycle in the whole-project static
  lock-acquisition graph. Edges come from lexically nested ``with``
  extents AND from calls made while holding a lock, resolved by
  method name across modules (``self.admission.admit_rows(...)``
  under the evaluator's condition reaches the admission lock) with a
  transitive may-acquire fixpoint — the registry→metrics→trace style
  cross-module chains are one edge each. Test scaffolding is
  excluded (``tests/`` may seed inversions deliberately);
* ``blocking-call-under-lock`` — ``.join()``, ``Event.wait()``,
  blocking ``queue.get/put``, ``time.sleep``,
  ``.block_until_ready()`` and file writes inside a held-lock
  extent (a ``Condition.wait`` on the HELD lock is the sanctioned
  pattern and exempt: it releases while waiting);
* ``callback-under-lock`` — user code escaping a held critical
  section (a call through a function-valued parameter or a
  ``*_fn``/``*_cb``/``*_hook``/``callback`` attribute), the classic
  re-entrancy trap: the callback may try to take the same lock, or
  observe the structure mid-update;
* ``thread-no-join`` — a started thread whose owning scope (the
  class for ``self._thread``, the enclosing function for locals) has
  no reachable ``join()``: no bounded stop path, so ``close()``
  can't promise quiescence (the data-prefetch worker bug). Abandon-
  by-design threads are baselined with a justification.

Everything is stdlib ``ast`` over :mod:`..events`' evaluation-order
streams (with-extents included); no jax, inside the 30 s budget.
"""

from __future__ import annotations

import ast
import re

import builtins

from rocalphago_tpu.analysis.core import Finding, module_rule, project_rule
from rocalphago_tpu.analysis.events import scope_events
from rocalphago_tpu.analysis.jaxmodel import dotted, last_segment

#: constructors that create a lock (threading + the lockcheck factories)
LOCK_FACTORIES = ("Lock", "RLock", "Condition",
                  "make_lock", "make_rlock", "make_condition")

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

CALLBACK_RE = re.compile(r"(?:^|_)(?:fn|cb|hook|callback)$")

#: modules whose ``with`` scaffolding must not feed the project lock
#: graph (tests seed inversions deliberately; fixtures are strings)
GRAPH_EXCLUDE = ("tests/",)

#: names the unique-def call-resolution fallback must never claim:
#: ``seen.add(x)`` is a set method even if exactly one class defines
#: ``add``; ``set(x)`` is the builtin even if Gauge defines ``set``
_BUILTIN_NAMES = frozenset(dir(builtins))
_BUILTIN_METHODS = frozenset(
    n for t in (dict, list, set, frozenset, str, bytes, tuple)
    for n in dir(t)) | frozenset(
        ("close", "write", "read", "flush", "readline", "acquire",
         "release"))


def _norm_lock(name: str | None) -> str | None:
    """``self._lock`` → ``_lock``; bare names unchanged."""
    if name is None:
        return None
    return name[5:] if name.startswith("self.") else name


# ------------------------------------------------------------ module model


class ClassModel:
    def __init__(self, node: ast.ClassDef, module_rel: str):
        self.node = node
        self.name = node.name
        self.module = module_rel
        self.locks: dict[str, int] = {}        # attr -> lineno
        self.guarded: dict[str, tuple] = {}    # attr -> (lock, lineno)
        self.methods: list = []                # FunctionDef nodes
        self.attr_types: dict[str, str] = {}   # self.X -> ClassName

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


class ModuleModel:
    """Per-module lock/guard/thread model, cached on the ModuleInfo."""

    def __init__(self, mod):
        self.rel = mod.rel
        base = mod.rel.rsplit("/", 1)[-1]
        self.basename = base[:-3] if base.endswith(".py") else base
        self.classes: list[ClassModel] = []
        self.mod_locks: dict[str, int] = {}
        self.mod_guarded: dict[str, tuple] = {}
        self.functions: list = []              # module-level defs
        self._build(mod)

    def mod_lock_id(self, name: str) -> str:
        return f"{self.basename}.{name}"

    def _annotation(self, mod, lineno: int) -> str | None:
        m = GUARDED_RE.search(mod.line(lineno))
        return _norm_lock(m.group(1)) if m else None

    def _scan_assign(self, mod, st, cls: ClassModel | None) -> None:
        """One Assign/AnnAssign: lock construction or guarded attr."""
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        value = getattr(st, "value", None)
        is_lock = (isinstance(value, ast.Call)
                   and last_segment(dotted(value.func)) in LOCK_FACTORIES)
        guard = self._annotation(mod, st.lineno)
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and cls is not None \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                if is_lock:
                    cls.locks.setdefault(tgt.attr, st.lineno)
                elif guard:
                    cls.guarded.setdefault(tgt.attr, (guard, st.lineno))
            elif isinstance(tgt, ast.Name) and cls is None:
                if is_lock:
                    self.mod_locks.setdefault(tgt.id, st.lineno)
                elif guard:
                    self.mod_guarded.setdefault(tgt.id,
                                                (guard, st.lineno))

    def _build(self, mod) -> None:
        for st in mod.tree.body:
            if isinstance(st, (ast.Assign, ast.AnnAssign)):
                self._scan_assign(mod, st, None)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(st)
            elif isinstance(st, ast.ClassDef):
                cm = ClassModel(st, mod.rel)
                for sub in ast.walk(st):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        self._scan_assign(mod, sub, cm)
                for sub in st.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        cm.methods.append(sub)
                self.classes.append(cm)


def _model(mod) -> ModuleModel:
    cached = getattr(mod, "_conc_model", None)
    if cached is None:
        cached = mod._conc_model = ModuleModel(mod)
    return cached


def _held_walk(fndef, lock_names: set, visit) -> None:
    """Drive ``visit(node, held)`` over a function body with the set
    of held lock names (normalized: ``_lock``, not ``self._lock``)
    maintained across ``with`` extents. Nested defs/lambdas are
    separate runtime frames and are skipped (they do not hold the
    lock when later invoked)."""

    def walk(node, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            add = set()
            for item in node.items:
                walk(item.context_expr, held)
                name = _norm_lock(dotted(item.context_expr))
                if name in lock_names:
                    add.add(name)
                if item.optional_vars is not None:
                    walk(item.optional_vars, held)
            inner = held | frozenset(add)
            for st in node.body:
                walk(st, inner)
            return
        visit(node, held)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for st in fndef.body:
        walk(st, frozenset())


EXEMPT_METHODS = ("__init__", "__del__")


# ---------------------------------------------------------------- rule 1/2


@module_rule(
    "unguarded-attr-access",
    "a `# guarded-by:` attribute touched without holding its lock")
def unguarded_attr_access(mod, ctx):
    findings = []
    model = _model(mod)
    for cm in model.classes:
        if not cm.guarded:
            continue
        lock_names = set(cm.locks)
        for fndef in cm.methods:
            if fndef.name in EXEMPT_METHODS:
                continue

            def visit(node, held, _f=fndef):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in cm.guarded:
                    lock, _ = cm.guarded[node.attr]
                    if lock not in held:
                        findings.append(mod.finding(
                            "unguarded-attr-access", node,
                            f"'{_f.name}' touches 'self.{node.attr}' "
                            f"without holding 'self.{lock}' (declared "
                            f"guarded-by at line "
                            f"{cm.guarded[node.attr][1]})"))

            _held_walk(fndef, lock_names, visit)
    # module-level guarded names used by module functions
    if model.mod_guarded:
        lock_names = set(model.mod_locks)
        scopes = list(model.functions)
        for cm in model.classes:
            scopes.extend(cm.methods)
        for fndef in scopes:
            def visit(node, held, _f=fndef):
                if isinstance(node, ast.Name) \
                        and node.id in model.mod_guarded:
                    lock, ln = model.mod_guarded[node.id]
                    if lock not in held:
                        findings.append(mod.finding(
                            "unguarded-attr-access", node,
                            f"'{_f.name}' touches module global "
                            f"'{node.id}' without holding '{lock}' "
                            f"(declared guarded-by at line {ln})"))

            _held_walk(fndef, lock_names, visit)
    return findings


@module_rule(
    "guarded-by-unknown-lock",
    "a `# guarded-by:` annotation naming a lock that does not exist")
def guarded_by_unknown_lock(mod, ctx):
    findings = []
    model = _model(mod)
    for cm in model.classes:
        for attr, (lock, lineno) in cm.guarded.items():
            if lock not in cm.locks:
                findings.append(mod.finding(
                    "guarded-by-unknown-lock", lineno,
                    f"'{cm.name}.{attr}' is declared guarded by "
                    f"'{lock}' but {cm.name} creates no such lock — "
                    "typo, or the lock moved"))
    for name, (lock, lineno) in model.mod_guarded.items():
        if lock not in model.mod_locks:
            findings.append(mod.finding(
                "guarded-by-unknown-lock", lineno,
                f"module global '{name}' is declared guarded by "
                f"'{lock}' but this module creates no such lock"))
    return findings


# ---------------------------------------------------------------- rule 3/4

#: receivers whose ``.join`` is path/string joining, not thread join
_JOIN_EXEMPT_RECV = ("path", "sep", "linesep")

_FILE_RECV = ("f", "_f", "fh", "_fh", "file", "_file", "stream",
              "_stream")


def _blocking_reason(call: ast.Call, held: frozenset) -> str | None:
    """Why this call must not run under a lock (None = not blocking).
    ``held`` lets the sanctioned ``cond.wait()``-on-the-held-lock
    pattern through."""
    name = dotted(call.func)
    if name is None:
        return None
    seg = last_segment(name)
    recv = name[: -(len(seg) + 1)] if "." in name else ""
    recv_seg = last_segment(recv) if recv else ""
    if seg == "join":
        if not recv or recv_seg in _JOIN_EXEMPT_RECV:
            return None
        return f"'{name}()' joins (blocks until another thread exits)"
    if seg == "wait":
        if _norm_lock(recv) in held:
            return None      # Condition.wait on the held lock: legal
        return f"'{name}()' waits on an event/another thread"
    if seg in ("get", "put"):
        if "queue" not in (recv_seg or "").lower() and recv_seg != "q":
            return None
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        return f"'{name}()' is a blocking queue op"
    if seg == "sleep":
        return f"'{name}()' sleeps"
    if seg == "block_until_ready":
        return f"'{name}()' synchronizes with the device"
    if seg == "write" and recv_seg in _FILE_RECV:
        return f"'{name}()' is a file write (OS-paced I/O)"
    return None


@module_rule(
    "blocking-call-under-lock",
    "a blocking operation (join/wait/queue/sleep/device-sync/file "
    "write) inside a held-lock extent")
def blocking_call_under_lock(mod, ctx):
    findings = []
    model = _model(mod)
    scopes: list[tuple] = [(f, set(model.mod_locks))
                           for f in model.functions]
    for cm in model.classes:
        names = set(cm.locks) | set(model.mod_locks)
        scopes.extend((f, names) for f in cm.methods)
    for fndef, lock_names in scopes:
        def visit(node, held, _f=fndef):
            if not held or not isinstance(node, ast.Call):
                return
            reason = _blocking_reason(node, held)
            if reason:
                findings.append(mod.finding(
                    "blocking-call-under-lock", node,
                    f"{reason} while '{_f.name}' holds "
                    f"{sorted(held)} — every other thread needing "
                    "the lock stalls behind it; move it outside the "
                    "critical section"))

        _held_walk(fndef, lock_names, visit)
    return findings


@module_rule(
    "callback-under-lock",
    "user code (a function-valued parameter or *_fn/*_cb/*_hook "
    "attribute) invoked while holding a lock")
def callback_under_lock(mod, ctx):
    findings = []
    model = _model(mod)
    scopes: list[tuple] = [(f, set(model.mod_locks))
                           for f in model.functions]
    for cm in model.classes:
        names = set(cm.locks) | set(model.mod_locks)
        scopes.extend((f, names) for f in cm.methods)
    for fndef, lock_names in scopes:
        a = fndef.args
        params = {p.arg for p in (*a.posonlyargs, *a.args,
                                  *a.kwonlyargs)} - {"self", "cls"}

        def visit(node, held, _f=fndef, _params=params):
            if not held or not isinstance(node, ast.Call):
                return
            func = node.func
            hit = None
            if isinstance(func, ast.Name) and func.id in _params:
                hit = f"parameter '{func.id}'"
            elif isinstance(func, ast.Attribute) \
                    and CALLBACK_RE.search(func.attr):
                hit = f"callback attribute '{dotted(func)}'"
            if hit:
                findings.append(mod.finding(
                    "callback-under-lock", node,
                    f"{hit} invoked while '{_f.name}' holds "
                    f"{sorted(held)} — user code inside a critical "
                    "section can re-enter the lock or observe state "
                    "mid-update; call it after release"))

        _held_walk(fndef, lock_names, visit)
    return findings


# ------------------------------------------------------------------ rule 5


def _thread_bindings(fndef) -> list:
    """(binding kind, name, node) for every ``threading.Thread(...)``
    constructed in ``fndef`` (nested defs included — the worker
    pattern builds threads in closures). Binding: the Assign target
    (``self._thread`` → class scope, plain name → function scope);
    an unbound construction binds to the function scope."""
    out = []
    for node in ast.walk(fndef):
        if not (isinstance(node, ast.Call)
                and last_segment(dotted(node.func)) == "Thread"):
            continue
        out.append(node)
    return out


def _has_join(tree) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" \
                and last_segment(dotted(node.func.value) or "") \
                not in _JOIN_EXEMPT_RECV \
                and not isinstance(node.func.value, ast.Constant):
            return True
    return False


@module_rule(
    "thread-no-join",
    "a started thread with no reachable join (no bounded stop path)")
def thread_no_join(mod, ctx):
    """A thread assigned to ``self.X`` must be joined somewhere in
    its class (the ``close()``/``stop()`` contract); a local thread
    must be joined in its enclosing function. Daemon-ness is not an
    excuse: a daemon prefetch worker with no join means ``close()``
    returns while the worker still touches the dataset. Deliberate
    abandonment (hang protection discarding a wedged worker) is a
    baseline entry with a justification, not a pass."""
    findings = []
    model = _model(mod)

    def check(fndef, owner_tree, owner_desc):
        for call in _thread_bindings(fndef):
            # self-attribute binding → the CLASS is the owning scope
            tree = owner_tree
            where = owner_desc
            if not _has_join(tree):
                findings.append(mod.finding(
                    "thread-no-join", call,
                    f"thread constructed in '{fndef.name}' is never "
                    f"joined anywhere in {where} — no bounded "
                    "stop/close path; a caller cannot wait for "
                    "quiescence"))

    for fndef in model.functions:
        check(fndef, fndef, f"function '{fndef.name}'")
    for cm in model.classes:
        for fndef in cm.methods:
            # locals inside a method: the method scope may join (the
            # worker pattern); otherwise fall back to the class scope
            # (self._thread joined by close()/stop()).
            if _has_join(fndef):
                continue
            check(fndef, cm.node, f"class '{cm.name}'")
    return findings


# ----------------------------------------------------- acquisition graph


def _method_key(model: ModuleModel, cm: ClassModel | None,
                fndef) -> str:
    if cm is not None:
        return f"{cm.name}.{fndef.name}"
    return f"{model.basename}.{fndef.name}"


def _lock_ids_for(model: ModuleModel, cm: ClassModel | None,
                  names: tuple) -> list:
    """Lock identities acquired by one ``with`` statement's context
    names, resolved against the class then the module."""
    out = []
    for raw in names:
        n = _norm_lock(raw)
        if cm is not None and n in cm.locks:
            out.append(cm.lock_id(n))
        elif n in model.mod_locks:
            out.append(model.mod_lock_id(n))
    return out


def build_lock_graph(ctx) -> dict:
    """The whole-project static lock-acquisition graph.

    Returns ``{"locks": {id: (module, line)}, "edges": {(a, b):
    [(module, line, via), ...]}}`` where an edge ``a → b`` means
    "some code path acquires ``b`` while holding ``a``" — either a
    lexically nested ``with``, or a call made under ``a`` that (by
    the transitive may-acquire fixpoint, resolved by method name
    across modules) can reach ``b``. This is the graph the runtime
    harness reconciles its OBSERVED edges against: every observed
    edge must appear here, or the declared model is wrong.
    """
    cached = ctx.cache.get("lock_graph")
    if cached is not None:
        return cached
    locks: dict[str, tuple] = {}
    # method key ("Class.method" / "mod.func") -> scope info
    methods: dict[str, dict] = {}
    #: simple def name -> [method keys] and -> global def count; the
    #: unique-name fallback resolves a call only when the project
    #: defines that name EXACTLY once ("admit_rows"), never for
    #: builtin-colliding names ("close", "get") — a file handle's
    #: .close() must not alias some class's lock-taking close()
    name_index: dict[str, list] = {}
    def_count: dict[str, int] = {}
    class_names: dict[str, str] = {}     # ClassName -> "__init__" key

    models = []
    for mod in ctx.modules:
        if any(mod.rel.startswith(p) for p in GRAPH_EXCLUDE):
            continue
        model = _model(mod)
        models.append((mod, model))
        for cm in model.classes:
            for attr, ln in cm.locks.items():
                locks[cm.lock_id(attr)] = (mod.rel, ln)
            class_names.setdefault(cm.name, f"{cm.name}.__init__")
            # self.X = ClassName(...): the typed-receiver map
            for fndef in cm.methods:
                for sub in ast.walk(fndef):
                    if isinstance(sub, ast.Assign) \
                            and isinstance(sub.value, ast.Call):
                        tname = dotted(sub.value.func)
                        if tname is None or "." in tname:
                            continue
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                cm.attr_types.setdefault(tgt.attr,
                                                         tname)
        for name, ln in model.mod_locks.items():
            locks[model.mod_lock_id(name)] = (mod.rel, ln)

    for mod, model in models:
        scopes = [(None, f) for f in model.functions]
        for cm in model.classes:
            scopes.extend((cm, f) for f in cm.methods)
        own_funcs = {f.name for f in model.functions}
        for cm, fndef in scopes:
            key = _method_key(model, cm, fndef)
            ev = scope_events(fndef)
            extents = []
            for names, start, end, node in ev.withs:
                for lid in _lock_ids_for(model, cm, names):
                    extents.append((lid, start, end, node))
            info = {"direct": {e[0] for e in extents},
                    "extents": extents, "module": mod.rel, "ev": ev,
                    "class": cm, "own_funcs": own_funcs,
                    "basename": model.basename}
            methods[key] = info
            name_index.setdefault(fndef.name, []).append(key)
            def_count[fndef.name] = def_count.get(fndef.name, 0) + 1

    def resolve(call: ast.Call, info) -> list:
        """Method keys a call site may reach: typed receiver first
        (``self.m``, ``self.X.m`` via the attr-type map), then
        same-module defs/constructors, then the unique-name
        fallback. Unresolvable calls contribute no edge — a missed
        edge is a model gap the runtime reconciliation surfaces,
        while a fabricated edge is a false deadlock report."""
        name = dotted(call.func)
        if not name:
            return []
        seg = last_segment(name)
        cm = info["class"]
        if "." in name:
            recv = name[: -(len(seg) + 1)]
            if recv == "self" and cm is not None:
                k = f"{cm.name}.{seg}"
                if k in methods:
                    return [k]
            if recv.startswith("self.") and "." not in recv[5:] \
                    and cm is not None:
                tname = cm.attr_types.get(recv[5:])
                if tname:
                    k = f"{tname}.{seg}"
                    return [k] if k in methods else []
            if seg in _BUILTIN_METHODS:
                return []
        else:
            if seg in info["own_funcs"]:
                return [f"{info['basename']}.{seg}"]
            if seg in class_names:
                k = class_names[seg]
                return [k] if k in methods else []
            if seg in _BUILTIN_NAMES:
                return []
        if def_count.get(seg) == 1:
            return list(name_index[seg])
        return []

    for key, info in methods.items():
        info["calls"] = set()
        for e in info["ev"].events:
            if e.kind == "call":
                info["calls"].update(resolve(e.call, info))

    # transitive may-acquire fixpoint over the resolved call graph
    may = {k: set(v["direct"]) for k, v in methods.items()}
    changed = True
    while changed:
        changed = False
        for k, info in methods.items():
            for k2 in info["calls"]:
                extra = may[k2] - may[k]
                if extra:
                    may[k] |= extra
                    changed = True

    edges: dict[tuple, list] = {}

    def add_edge(a, b, module, line, via):
        if a == b:
            return
        edges.setdefault((a, b), []).append((module, line, via))

    for k, info in methods.items():
        ev = info["ev"]
        for lid, start, end, node in info["extents"]:
            # nested with extents: outer -> inner
            for lid2, s2, e2, n2 in info["extents"]:
                if lid2 != lid and start <= s2 and e2 <= end \
                        and (s2, e2) != (start, end):
                    add_edge(lid, lid2, info["module"], n2.lineno,
                             f"nested with in {k}")
            # calls under the lock: edge to everything they may acquire
            for i in range(start, end):
                e = ev.events[i]
                if e.kind != "call":
                    continue
                for k2 in resolve(e.call, info):
                    for lid2 in may[k2]:
                        add_edge(lid, lid2, info["module"],
                                 e.call.lineno, f"{k} calls {k2}")
    out = {"locks": locks, "edges": edges}
    ctx.cache["lock_graph"] = out
    return out


@project_rule(
    "lock-order-inversion",
    "a cycle in the static lock-acquisition graph (deadlock under "
    "the right interleaving)")
def lock_order_inversion(ctx):
    graph = build_lock_graph(ctx)
    edges = graph["edges"]
    adj: dict[str, set] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    # Tarjan-free SCC via iterative DFS twice (Kosaraju) — graphs
    # here are tiny (tens of locks)
    order, seen = [], set()

    def dfs(start, graph_adj, visitor):
        stack = [(start, iter(graph_adj.get(start, ())))]
        seen.add(start)
        while stack:
            node, it = stack[-1]
            for nxt in it:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, iter(graph_adj.get(nxt, ()))))
                    break
            else:
                stack.pop()
                visitor(node)

    nodes = set(adj) | {b for bs in adj.values() for b in bs}
    for n in sorted(nodes):
        if n not in seen:
            dfs(n, adj, order.append)
    radj: dict[str, set] = {}
    for a, bs in adj.items():
        for b in bs:
            radj.setdefault(b, set()).add(a)
    seen.clear()
    comp: dict[str, int] = {}
    cid = 0
    for n in reversed(order):
        if n not in seen:
            members: list = []
            dfs(n, radj, members.append)
            for m in members:
                comp[m] = cid
            cid += 1
    findings = []
    for (a, b), sites in sorted(edges.items()):
        if comp.get(a) is not None and comp.get(a) == comp.get(b):
            module, line, via = sites[0]
            cycle = sorted(x for x in comp if comp[x] == comp[a])
            findings.append(Finding(
                path=module, line=line, rule="lock-order-inversion",
                message=f"acquiring '{b}' while holding '{a}' "
                        f"({via}) is part of an acquisition cycle "
                        f"{{{', '.join(cycle)}}} — two threads "
                        "taking the locks in opposite orders "
                        "deadlock; pick one global order",
                snippet=f"edge:{a}->{b}"))
    return findings
