"""Inventory drift: code vs documented observability/resilience
contracts.

The metric names, span names, fault-barrier names and
``ROCALPHAGO_*`` env knobs are *interfaces*: operators scrape them,
fault plans target them, docs promise them. They are also just
strings, so nothing stops a rename in code from silently orphaning
the documented name (or a new metric from shipping undocumented).
This family extracts every such name statically and diffs it against
the documented inventories:

* ``undocumented-metric`` / ``stale-metric-doc`` — registry
  counters/gauges/histograms vs the metric table in
  docs/OBSERVABILITY.md;
* ``undocumented-span`` — ``trace.span("…")`` names vs
  docs/OBSERVABILITY.md (prose/backtick mention suffices; spans have
  no stale check because the doc groups them as prose);
* ``undocumented-barrier`` / ``stale-barrier-doc`` — fault-barrier
  names vs the two barrier tables in docs/RESILIENCE.md;
* ``knob-doc-drift`` — env knobs vs the generated docs/KNOBS.md
  (regenerate with ``python scripts/lint.py --write-knobs``);
* ``report-unknown-metric`` — metric names *consumed* by
  scripts/obs_report.py that no code path produces (the renderer
  silently showing empty sections is exactly the rot this catches).

F-string names become ``*`` glob patterns (``encode_incr_{f}_total``
→ ``encode_incr_*_total``) and match the documented glob; documented
placeholders (``serve.<rung>``) glob the same way.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import re

from rocalphago_tpu.analysis.core import Finding, project_rule
from rocalphago_tpu.analysis.jaxmodel import dotted, last_segment

#: modules whose registry/trace calls DEFINE the api, not metrics
#: (obs/jaxobs.py is a genuine producer — jax_compiles_total — and
#: so is analysis/lockcheck.py — lock_wait_seconds — so only the
#: registry/trace definition modules and the rule modules, whose
#: docstrings/messages quote metric idioms, are excluded)
PRODUCER_EXCLUDE = ("rocalphago_tpu/obs/registry.py",
                    "rocalphago_tpu/obs/trace.py",
                    "rocalphago_tpu/analysis/rules/",
                    "rocalphago_tpu/analysis/core.py",
                    "tests/", "scripts/obs_report.py")
BARRIER_EXCLUDE = ("rocalphago_tpu/runtime/faults.py",
                   "rocalphago_tpu/analysis/", "tests/")
KNOB_RE = re.compile(r"^ROCALPHAGO_[A-Z0-9_]+$")
METRIC_SUFFIX = re.compile(
    r"_(total|seconds|us|per_s|per_min|occupancy|gap_s|margin_s|"
    r"plies)$")


@dataclasses.dataclass
class Entry:
    name: str          # may contain '*' (from f-strings)
    module: str
    line: int
    kind: str = ""
    labels: tuple = ()


@dataclasses.dataclass
class Knob:
    name: str
    module: str = ""      # owning (first defining/reading) module
    default: str = ""     # literal default at the environ.get site
    readers: tuple = ()


def _joined_pattern(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("*")
    return "".join(parts)


def _str_or_pattern(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return _joined_pattern(node)
    return None


def _excluded(rel: str, prefixes) -> bool:
    return any(rel == p or rel.startswith(p) for p in prefixes)


# ------------------------------------------------------------ extraction

def _extract(ctx) -> dict:
    cached = ctx.cache.get("inventory")
    if cached is not None:
        return cached
    metrics: list = []
    spans: list = []
    barriers: list = []
    knob_map: dict = {}

    owner_rank: dict = {}

    def _rank(module, defining):
        # package modules own their knobs; benches/scripts/tests are
        # readers. Within a tier a defining `X_ENV = "…"` assign
        # beats a bare read.
        tier = (0 if module.startswith("rocalphago_tpu/")
                else 2 if module.startswith("tests/") else 1)
        return (tier, 0 if defining else 1)

    def note_knob(name, module, line, default=None, defining=False):
        k = knob_map.setdefault(name, Knob(name=name))
        readers = set(k.readers)
        readers.add(module)
        k.readers = tuple(sorted(readers))
        rank = _rank(module, defining)
        if not k.module or rank < owner_rank[name]:
            k.module = module
            owner_rank[name] = rank
        if default is not None and not k.default:
            k.default = default

    for mod in ctx.modules:
        rel = mod.rel
        # module-level "NAME_ENV = 'ROCALPHAGO_X'" aliases (defining)
        aliases: dict = {}
        for st in mod.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name) \
                    and isinstance(st.value, ast.Constant) \
                    and isinstance(st.value.value, str) \
                    and KNOB_RE.match(st.value.value):
                aliases[st.targets[0].id] = st.value.value
                note_knob(st.value.value, rel, st.lineno,
                          defining=True)

        def knob_of(node):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and KNOB_RE.match(node.value):
                return node.value
            if isinstance(node, ast.Name):
                return aliases.get(node.id)
            return None

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                seg = last_segment(name)
                # ---- metrics / spans / barriers (producers)
                if seg in ("counter", "gauge", "histogram") \
                        and name and "." in name \
                        and not _excluded(rel, PRODUCER_EXCLUDE) \
                        and node.args:
                    m = _str_or_pattern(node.args[0])
                    if m:
                        metrics.append(Entry(
                            name=m, module=rel, line=node.lineno,
                            kind=seg,
                            labels=tuple(sorted(
                                k.arg for k in node.keywords
                                if k.arg))))
                elif seg == "span" and not _excluded(
                        rel, PRODUCER_EXCLUDE) and node.args:
                    s = _str_or_pattern(node.args[0])
                    if s:
                        spans.append(Entry(name=s, module=rel,
                                           line=node.lineno))
                elif seg and seg.endswith("barrier") \
                        and not _excluded(rel, BARRIER_EXCLUDE) \
                        and node.args:
                    b = _str_or_pattern(node.args[0])
                    if b:
                        barriers.append(Entry(name=b, module=rel,
                                              line=node.lineno))
                # ---- env knobs (environ access forms)
                if name and (name.endswith("environ.get")
                             or name.endswith(".getenv")
                             or name == "getenv"
                             or name.endswith("environ.setdefault")
                             or name.endswith("environ.pop")):
                    if node.args:
                        kn = knob_of(node.args[0])
                        if kn:
                            default = None
                            if len(node.args) > 1 and isinstance(
                                    node.args[1], ast.Constant):
                                default = repr(node.args[1].value)
                            note_knob(kn, rel, node.lineno,
                                      default=default)
            elif isinstance(node, ast.Subscript):
                base = dotted(node.value)
                if base and base.endswith("environ"):
                    kn = knob_of(node.slice)
                    if kn:
                        note_knob(kn, rel, node.lineno)
            elif isinstance(node, ast.Compare) \
                    and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)):
                right = dotted(node.comparators[0])
                if right and right.endswith("environ"):
                    kn = knob_of(node.left)
                    if kn:
                        note_knob(kn, rel, node.lineno)

    out = {"metrics": metrics, "spans": spans, "barriers": barriers,
           "knobs": dict(sorted(knob_map.items()))}
    ctx.cache["inventory"] = out
    return out


# ------------------------------------------------------------ doc parsing

def _backtick_tokens(text: str) -> list:
    return re.findall(r"`([^`\n]+)`", text)


def _table_first_cells(text: str, header_word: str) -> list:
    """(lineno, [backtick tokens]) for the first column of every
    markdown table whose header's first cell contains
    ``header_word``."""
    rows = []
    lines = text.splitlines()
    in_table = False
    for i, line in enumerate(lines, start=1):
        s = line.strip()
        if not s.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in s.strip("|").split("|")]
        if not cells:
            continue
        if header_word in cells[0].lower() and "`" not in cells[0]:
            in_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue
        if in_table:
            toks = re.findall(r"`([^`]+)`", cells[0])
            if toks:
                rows.append((i, toks))
    return rows


def _norm(name: str) -> str:
    """Strip the ``{label=}`` suffix and turn ``<placeholder>`` into a
    glob, so documented and extracted names compare."""
    name = re.sub(r"\{[^}]*\}", "", name).strip()
    name = re.sub(r"<[^>]*>", "*", name)
    return name


def _match(a: str, b: str) -> bool:
    a, b = _norm(a), _norm(b)
    return a == b or fnmatch.fnmatchcase(a, b) \
        or fnmatch.fnmatchcase(b, a)


def _doc_line_of(text: str, token: str) -> int:
    for i, line in enumerate(text.splitlines(), start=1):
        if token in line:
            return i
    return 1


# ------------------------------------------------------------ the rules

@project_rule(
    "undocumented-metric",
    "a registry metric produced in code but absent from the "
    "OBSERVABILITY.md inventory")
def undocumented_metric(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_observability)
    if doc is None:
        return []
    tokens = [_norm(t) for t in _backtick_tokens(doc)]
    findings = []
    seen = set()
    for m in inv["metrics"]:
        base = _norm(m.name)
        if base in seen:
            continue
        seen.add(base)
        if not any(_match(base, t) for t in tokens):
            findings.append(Finding(
                path=m.module, line=m.line, rule="undocumented-metric",
                message=f"metric '{m.name}' ({m.kind}) is not in "
                        f"{ctx.config.docs_observability} — add it to "
                        "the metric inventory table",
                snippet=f"metric:{base}"))
    return findings


@project_rule(
    "stale-metric-doc",
    "a metric documented in OBSERVABILITY.md's table that no code "
    "produces")
def stale_metric_doc(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_observability)
    if doc is None:
        return []
    produced = [_norm(m.name) for m in inv["metrics"]]
    findings = []
    for lineno, toks in _table_first_cells(doc, "metric"):
        for t in toks:
            base = _norm(t)
            # non-name tokens in the cell (e.g. annotations) — skip
            if not re.match(r"^[a-z][a-z0-9_*]+$", base):
                continue
            if not any(_match(base, p) for p in produced):
                findings.append(Finding(
                    path=ctx.config.docs_observability, line=lineno,
                    rule="stale-metric-doc",
                    message=f"documented metric '{t}' is produced by "
                            "no code path — remove the row or restore "
                            "the metric",
                    snippet=f"doc-metric:{base}"))
    return findings


@project_rule(
    "undocumented-span",
    "a trace.span name not mentioned in OBSERVABILITY.md")
def undocumented_span(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_observability)
    if doc is None:
        return []
    tokens = []
    for t in _backtick_tokens(doc):
        for part in t.split("/"):
            tokens.append(_norm(part))
    findings = []
    seen = set()
    for s in inv["spans"]:
        base = _norm(s.name)
        if base in seen:
            continue
        seen.add(base)
        if not any(_match(base, t) for t in tokens):
            findings.append(Finding(
                path=s.module, line=s.line, rule="undocumented-span",
                message=f"span '{s.name}' is not mentioned in "
                        f"{ctx.config.docs_observability} — document "
                        "it in the span-coverage paragraph",
                snippet=f"span:{base}"))
    return findings


@project_rule(
    "undocumented-barrier",
    "a fault-barrier name absent from RESILIENCE.md's barrier tables")
def undocumented_barrier(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_resilience)
    if doc is None:
        return []
    documented = []
    for _ln, toks in _table_first_cells(doc, "barrier"):
        documented.extend(_norm(t) for t in toks)
    findings = []
    seen = set()
    for b in inv["barriers"]:
        base = _norm(b.name)
        if base in seen:
            continue
        seen.add(base)
        if not any(_match(base, t) for t in documented):
            findings.append(Finding(
                path=b.module, line=b.line,
                rule="undocumented-barrier",
                message=f"fault barrier '{b.name}' is not in the "
                        f"{ctx.config.docs_resilience} barrier tables"
                        " — fault plans can't target what operators "
                        "can't see",
                snippet=f"barrier:{base}"))
    return findings


@project_rule(
    "stale-barrier-doc",
    "a barrier documented in RESILIENCE.md that no code declares")
def stale_barrier_doc(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_resilience)
    if doc is None:
        return []
    declared = [_norm(b.name) for b in inv["barriers"]]
    findings = []
    for lineno, toks in _table_first_cells(doc, "barrier"):
        for t in toks:
            base = _norm(t)
            if not re.match(r"^[a-z][a-z0-9_.]+$", base):
                continue
            if not any(_match(base, d) for d in declared):
                findings.append(Finding(
                    path=ctx.config.docs_resilience, line=lineno,
                    rule="stale-barrier-doc",
                    message=f"documented barrier '{t}' is declared "
                            "nowhere in code — remove the row or "
                            "restore the barrier",
                    snippet=f"doc-barrier:{base}"))
    return findings


@project_rule(
    "knob-doc-drift",
    "ROCALPHAGO_* env knobs out of sync with the generated "
    "docs/KNOBS.md")
def knob_doc_drift(ctx):
    inv = _extract(ctx)
    doc = ctx.read_doc(ctx.config.docs_knobs)
    findings = []
    documented = set()
    if doc is not None:
        for _ln, toks in _table_first_cells(doc, "knob"):
            documented.update(_norm(t) for t in toks)
    for name, k in inv["knobs"].items():
        if name not in documented:
            findings.append(Finding(
                path=k.module or "pyproject.toml", line=1,
                rule="knob-doc-drift",
                message=f"env knob '{name}' is not documented in "
                        f"{ctx.config.docs_knobs} — run `python "
                        "scripts/lint.py --write-knobs`",
                snippet=f"knob:{name}"))
    for name in sorted(documented):
        if name not in inv["knobs"]:
            findings.append(Finding(
                path=ctx.config.docs_knobs,
                line=_doc_line_of(doc or "", name),
                rule="knob-doc-drift",
                message=f"documented knob '{name}' is read nowhere "
                        "in code — stale name? run `python "
                        "scripts/lint.py --write-knobs`",
                snippet=f"doc-knob:{name}"))
    return findings


@project_rule(
    "report-unknown-metric",
    "obs_report consumes a metric name no code produces")
def report_unknown_metric(ctx):
    inv = _extract(ctx)
    produced = [_norm(m.name) for m in inv["metrics"]]
    findings = []
    report_mods = [m for m in ctx.modules
                   if m.rel in ctx.config.report_modules]
    for mod in report_mods:
        seen = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(dotted(node.func))
            if seg not in ("startswith", "get"):
                continue
            for a in node.args[:1]:
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, str)):
                    continue
                base = _norm(a.value)
                if base in seen or not METRIC_SUFFIX.search(base):
                    continue
                seen.add(base)
                # prefix consumption (startswith) matches any
                # produced metric that begins with the base
                ok = any(_match(base, p) or p.startswith(base)
                         for p in produced)
                if not ok:
                    findings.append(mod.finding(
                        "report-unknown-metric", node,
                        f"obs_report reads metric '{base}' which no "
                        "code path produces — its section will "
                        "render empty forever"))
        findings = [f for f in findings]
    return findings


def _probe_drift(ctx, *, rule: str, doc_rel: str, block_key: str,
                 module_rel: str, class_name: str,
                 consumer: str) -> list:
    """Shared engine of the three probe-drift rules: the fenced JSON
    block in ``doc_rel`` containing ``block_key`` is the documented
    schema; the dict literal ``class_name.stats`` returns in
    ``module_rel`` is the producer. Both flatten to dotted key paths
    and diff BOTH directions — the same pattern as the metric/
    barrier tables. ``consumer`` names who keys on the block (for
    the finding message)."""
    import json as _json

    doc = ctx.read_doc(doc_rel)
    if doc is None:
        return []

    def flatten_json(d, prefix=""):
        out = set()
        for k, v in d.items():
            out.add(prefix + k)
            if isinstance(v, dict):
                out |= flatten_json(v, prefix + k + ".")
        return out

    documented = None
    for block in re.findall(r"```json\s*\n(.*?)```", doc, re.S):
        if f'"{block_key}"' not in block:
            continue
        try:
            data = _json.loads(block)
        except ValueError:
            continue
        probe = data.get(block_key)
        if isinstance(probe, dict):
            documented = flatten_json(probe)
            break
    if documented is None:
        return []

    def flatten_dict_node(node, prefix=""):
        out = {}
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                path = prefix + k.value
                out[path] = k.lineno
                if isinstance(v, ast.Dict):
                    out.update(flatten_dict_node(v, path + "."))
        return out

    produced = None
    mod = next((m for m in ctx.modules if m.rel == module_rel), None)
    if mod is not None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) \
                    and node.name == class_name:
                for fn in node.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) \
                            and fn.name == "stats":
                        for sub in ast.walk(fn):
                            if isinstance(sub, ast.Return) \
                                    and isinstance(sub.value, ast.Dict):
                                produced = flatten_dict_node(sub.value)
    if produced is None:
        return []

    findings = []
    for key, line in sorted(produced.items()):
        if key not in documented:
            findings.append(Finding(
                path=mod.rel, line=line, rule=rule,
                message=f"{block_key}-probe field '{key}' is emitted "
                        f"by {class_name}.stats but missing from the "
                        f"schema in {doc_rel} — {consumer} key on "
                        "that block; document it",
                snippet=f"probe:{key}"))
    for key in sorted(documented - set(produced)):
        findings.append(Finding(
            path=doc_rel,
            line=_doc_line_of(doc, key.rsplit(".", 1)[-1]),
            rule=rule,
            message=f"documented {block_key}-probe field '{key}' is "
                    f"emitted by no code path — a {consumer} reader "
                    "sees nothing; update the schema or restore the "
                    "field",
            snippet=f"doc-probe:{key}"))
    return findings


@project_rule(
    "serve-probe-drift",
    "the documented serve health-probe block schema vs the fields "
    "ServePool.stats actually emits")
def serve_probe_drift(ctx):
    """The ``"serve"`` block in ``rocalphago-health`` /
    ``rocalphago-stats`` is the LB health-check schema
    (docs/SERVING.md's fenced JSON example). Its producer is the
    dict literal ``ServePool.stats`` returns
    (``config.serve_probe_module``)."""
    return _probe_drift(
        ctx, rule="serve-probe-drift",
        doc_rel=ctx.config.docs_serving, block_key="serve",
        module_rel=ctx.config.serve_probe_module,
        class_name="ServePool", consumer="load balancers")


@project_rule(
    "gateway-probe-drift",
    "the documented gateway health-probe block schema vs the fields "
    "GatewayServer.stats actually emits")
def gateway_probe_drift(ctx):
    """The ``"gateway"`` block in the gateway's ``/healthz`` is the
    LB health-check schema for the network front end
    (docs/GATEWAY.md's fenced JSON example). Its producer is the dict
    literal ``GatewayServer.stats`` returns
    (``config.gateway_probe_module``); same both-direction diff as
    ``serve-probe-drift``."""
    return _probe_drift(
        ctx, rule="gateway-probe-drift",
        doc_rel=ctx.config.docs_gateway, block_key="gateway",
        module_rel=ctx.config.gateway_probe_module,
        class_name="GatewayServer", consumer="load balancers")


@project_rule(
    "rollout-probe-drift",
    "the documented router/canary probe block schemas vs the fields "
    "RolloutRouter.stats / CanaryController.stats actually emit")
def rollout_probe_drift(ctx):
    """Two producers, one rule: the ``"router"`` block the router's
    ``/healthz`` serves (producer: ``RolloutRouter.stats``,
    ``config.router_probe_module``) and the ``"canary"`` block the
    canary controller probes emit (producer:
    ``CanaryController.stats``, ``config.canary_probe_module``) —
    both diffed both ways against docs/ROLLOUT.md's fenced JSON
    examples, like the other probe rules. The router's dynamic
    per-replica map is documented as ``{}`` (only literal keys
    count)."""
    return _probe_drift(
        ctx, rule="rollout-probe-drift",
        doc_rel=ctx.config.docs_rollout, block_key="router",
        module_rel=ctx.config.router_probe_module,
        class_name="RolloutRouter",
        consumer="fleet balancers") + _probe_drift(
        ctx, rule="rollout-probe-drift",
        doc_rel=ctx.config.docs_rollout, block_key="canary",
        module_rel=ctx.config.canary_probe_module,
        class_name="CanaryController",
        consumer="rollout dashboards")


@project_rule(
    "replaynet-probe-drift",
    "the documented replaynet stats-probe block schema vs the fields "
    "ReplayService.stats actually emits")
def replaynet_probe_drift(ctx):
    """The ``"replaynet"`` block a ``stats`` frame returns is the
    schema the soak harness green-gates on and dashboards scrape
    (docs/REPLAYNET.md's fenced JSON example). Its producer is the
    dict literal ``ReplayService.stats`` returns
    (``config.replaynet_probe_module``); same both-direction diff
    as the other probe rules."""
    return _probe_drift(
        ctx, rule="replaynet-probe-drift",
        doc_rel=ctx.config.docs_replaynet, block_key="replaynet",
        module_rel=ctx.config.replaynet_probe_module,
        class_name="ReplayService", consumer="soak harnesses")


# --------------------------------------------------- KNOBS.md generator

KNOBS_HEADER = """\
# KNOBS — every `ROCALPHAGO_*` environment variable

<!-- GENERATED by `python scripts/lint.py --write-knobs` from the
     jaxlint env-knob extractor; hand edits to the table are
     overwritten. The `knob-doc-drift` lint rule fails when this
     file and the source disagree. -->

One row per knob the source actually reads: the owning module (the
definition/primary read site), the literal default at the
`environ.get` site (`—` when the knob is presence/flag-style or the
default is computed), and every other module that reads it. Semantics
live with the owning module's docstrings and the subsystem docs
(docs/PERFORMANCE.md, docs/RESILIENCE.md, docs/OBSERVABILITY.md).

| knob | owning module | default | also read in |
|---|---|---|---|
"""


def render_knobs_doc(ctx) -> str:
    inv = _extract(ctx)
    rows = []
    for name, k in sorted(inv["knobs"].items()):
        others = [r for r in k.readers if r != k.module]
        rows.append(
            f"| `{name}` | `{k.module}` | "
            f"{('`' + k.default + '`') if k.default else '—'} | "
            f"{', '.join('`' + o + '`' for o in others) or '—'} |")
    return KNOBS_HEADER + "\n".join(rows) + "\n"
