"""jaxlint output: text for humans, JSON for tooling.

The text reporter groups by file and marks grandfathered findings
with ``(baselined)`` so a full run still shows the debt without
failing on it; the JSON reporter is one stable object (findings +
partition counts) for CI artifacts and the tests.
"""

from __future__ import annotations

import json

from rocalphago_tpu.analysis.core import Finding


def render_text(new: list[Finding], baselined: list[Finding],
                stale_entries: list[dict], verbose: bool = False) -> str:
    out = []
    flagged = {id(f) for f in new}
    for f in sorted(new + baselined):
        tag = "" if id(f) in flagged else "  (baselined)"
        if id(f) in flagged or verbose:
            out.append(f.render() + tag)
    for e in stale_entries:
        out.append(f"{e.get('path', '?')}: [baseline-stale] baselined "
                   f"finding no longer occurs: [{e.get('rule')}] "
                   f"{e.get('snippet', '')!r} — remove it (or run "
                   "--update-baseline)")
    n_stale = len(stale_entries)
    out.append(f"jaxlint: {len(new)} new finding(s), "
               f"{len(baselined)} baselined, {n_stale} stale baseline "
               "entr" + ("y" if n_stale == 1 else "ies"))
    return "\n".join(out)


def render_json(new: list[Finding], baselined: list[Finding],
                stale_entries: list[dict]) -> str:
    return json.dumps({
        "new": [f.to_dict() for f in sorted(new)],
        "baselined": [f.to_dict() for f in sorted(baselined)],
        "stale_baseline_entries": stale_entries,
        "counts": {"new": len(new), "baselined": len(baselined),
                   "stale": len(stale_entries)},
    }, indent=1, sort_keys=False)
