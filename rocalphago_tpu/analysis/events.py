"""Evaluation-order event streams per function scope.

The donation-reuse and PRNG-discipline rules are *ordering* rules:
"after this call, the next touch of ``x`` decides". Python's AST
walk order is not evaluation order (``carry = f(carry)`` evaluates
the RHS — including the argument read — before the store), so this
module flattens each scope into a list of ``read`` / ``write`` /
``call`` events in evaluation order, with loop extents recorded so a
rule can reason about "the next iteration touches it again", and
``with`` extents (context names + event ranges) so the concurrency
family can reason about "this call happens while that lock is held".

Approximations (deliberate, baseline-absorbable): ``if``/``else``
arms are concatenated linearly; ``try`` flows linearly; nested
function bodies are separate scopes (a closure read is not an event
in the enclosing scope); only ``Name`` targets produce ``write``
events (attribute/subscript stores read their base instead).
"""

from __future__ import annotations

import ast
import dataclasses


@dataclasses.dataclass
class Event:
    kind: str                 # "read" | "write" | "call"
    name: str | None          # read/write target
    node: ast.AST             # anchor for findings
    call: ast.Call | None = None   # for kind == "call"
    src: str | None = None    # write: dotted callee of a direct-call RHS


@dataclasses.dataclass
class ScopeEvents:
    scope: ast.AST            # FunctionDef or Module
    events: list
    loops: list               # (start_idx, end_idx) per loop, any order
    #: (ctx_names tuple, start_idx, end_idx, With node) per ``with``
    #: statement — ctx_names are the dotted context expressions
    #: (``self._lock``; a Call context contributes its callee name).
    #: The concurrency family reads lock-held extents off these.
    withs: list = dataclasses.field(default_factory=list)

    def enclosing_withs(self, i: int):
        """Every with-extent containing event index ``i``, outermost
        first (list of (ctx_names, start, end, node))."""
        hits = [w for w in self.withs if w[1] <= i < w[2]]
        hits.sort(key=lambda w: w[1])
        return hits

    def enclosing_loop(self, i: int):
        """Innermost loop range containing event index ``i``."""
        best = None
        for s, e in self.loops:
            if s <= i < e and (best is None or (e - s) < (best[1] - best[0])):
                best = (s, e)
        return best


class _Walker:
    def __init__(self):
        self.events: list = []
        self.loops: list = []
        self.withs: list = []

    # -- expressions (reads, calls) ----------------------------------
    def expr(self, node) -> None:
        if node is None:
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self.events.append(Event("read", node.id, node))
            return
        if isinstance(node, ast.Call):
            self.expr(node.func)
            for a in node.args:
                self.expr(a.value if isinstance(a, ast.Starred) else a)
            for k in node.keywords:
                self.expr(k.value)
            self.events.append(Event("call", None, node, call=node))
            return
        if isinstance(node, (ast.Lambda,)):
            return  # separate scope
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                self.expr(gen.iter)  # iterables evaluate in this scope
            return  # element exprs run in the comprehension scope
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    # -- statements ---------------------------------------------------
    def write_target(self, tgt, src: str | None) -> None:
        if isinstance(tgt, ast.Name):
            self.events.append(Event("write", tgt.id, tgt, src=src))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self.write_target(e, src)
        elif isinstance(tgt, ast.Starred):
            self.write_target(tgt.value, src)
        else:  # attribute/subscript store: base object is read
            self.expr(getattr(tgt, "value", None))
            self.expr(getattr(tgt, "slice", None))

    def stmts(self, body) -> None:
        for st in body:
            self.stmt(st)

    def stmt(self, st) -> None:
        if isinstance(st, ast.Assign):
            self.expr(st.value)
            src = dotted_callee(st.value)
            for tgt in st.targets:
                self.write_target(tgt, src)
        elif isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                self.events.append(Event("read", st.target.id, st.target))
            self.expr(st.value)
            self.write_target(st.target, None)
        elif isinstance(st, ast.AnnAssign):
            self.expr(st.value)
            if st.value is not None:
                self.write_target(st.target, dotted_callee(st.value))
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.expr(st.iter)
            start = len(self.events)
            self.write_target(st.target, None)
            self.stmts(st.body)
            self.loops.append((start, len(self.events)))
            self.stmts(st.orelse)
        elif isinstance(st, ast.While):
            start = len(self.events)
            self.expr(st.test)
            self.stmts(st.body)
            self.loops.append((start, len(self.events)))
            self.stmts(st.orelse)
        elif isinstance(st, ast.If):
            self.expr(st.test)
            self.stmts(st.body)
            self.stmts(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            ctx_names = []
            for item in st.items:
                self.expr(item.context_expr)
                name = _ctx_name(item.context_expr)
                if name:
                    ctx_names.append(name)
                if item.optional_vars is not None:
                    self.write_target(item.optional_vars, None)
            start = len(self.events)
            self.stmts(st.body)
            self.withs.append((tuple(ctx_names), start,
                               len(self.events), st))
        elif isinstance(st, ast.Try):
            self.stmts(st.body)
            for h in st.handlers:
                self.stmts(h.body)
            self.stmts(st.orelse)
            self.stmts(st.finalbody)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope
        elif isinstance(st, (ast.Return, ast.Expr, ast.Raise,
                             ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(st):
                self.expr(child)
        elif isinstance(st, (ast.Import, ast.ImportFrom, ast.Pass,
                             ast.Break, ast.Continue, ast.Global,
                             ast.Nonlocal)):
            return
        else:
            for child in ast.iter_child_nodes(st):
                self.expr(child)


def dotted_callee(value) -> str | None:
    from rocalphago_tpu.analysis.jaxmodel import dotted
    if isinstance(value, ast.Call):
        return dotted(value.func)
    return None


def _ctx_name(expr) -> str | None:
    """Dotted name of a ``with`` context expression: ``self._lock``
    directly, or the callee of a Call context (``span("x")`` →
    ``span``)."""
    from rocalphago_tpu.analysis.jaxmodel import dotted
    name = dotted(expr)
    if name is not None:
        return name
    if isinstance(expr, ast.Call):
        return dotted(expr.func)
    return None


def scope_events(scope) -> ScopeEvents:
    """Flatten one scope (FunctionDef body or Module body) into
    evaluation-order events."""
    w = _Walker()
    w.stmts(scope.body)
    return ScopeEvents(scope=scope, events=w.events, loops=w.loops,
                       withs=w.withs)


def iter_scopes(tree):
    """Module scope plus every function def (nested included — each
    analyzed as its own scope)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
