"""jaxlint core: findings, module model, rule registry, driver.

A *rule* is a function registered under a stable kebab-case id.
Module rules run once per parsed file; project rules run once over
the whole file set (the inventory-drift family needs cross-file
state — every metric produced anywhere vs one documented table).

Suppression syntax (same-line comment, documented in
docs/STATIC_ANALYSIS.md):

* ``# jaxlint: disable=rule-id`` — suppress that rule on this line
  (comma-separate several ids);
* ``# jaxlint: disable`` — suppress every rule on this line;
* ``# jaxlint: skip-file`` — anywhere in the file, drops the whole
  file from analysis (reserved for vendored/generated code).

Suppressions anchor on the line a finding is REPORTED at (the
statement's first line for multi-line statements).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

# --------------------------------------------------------------- findings

SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*(disable|skip-file)\b(?:\s*=\s*([A-Za-z0-9_,\- ]+))?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit. ``snippet`` (the stripped source line) joins the
    fingerprint so baseline entries survive line-number drift."""

    path: str          # repo-relative posix path
    line: int
    rule: str
    message: str
    snippet: str = ""

    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.snippet}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "snippet": self.snippet}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ----------------------------------------------------------- module model

class ModuleInfo:
    """One parsed source file: AST + per-line suppression map."""

    def __init__(self, rel: str, source: str, path: str | None = None):
        self.rel = rel.replace(os.sep, "/")
        self.path = path or rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)  # SyntaxError handled by driver
        self.suppressions: dict[int, set[str]] = {}
        self.skip_file = False
        for i, text in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            if m.group(1) == "skip-file":
                self.skip_file = True
            elif m.group(2):
                ids = {t.strip() for t in m.group(2).split(",") if t.strip()}
                self.suppressions.setdefault(i, set()).update(ids)
            else:
                self.suppressions.setdefault(i, set()).add("*")

    def line(self, n: int) -> str:
        if 1 <= n <= len(self.lines):
            return self.lines[n - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", None) or int(node)
        return Finding(path=self.rel, line=line, rule=rule,
                       message=message, snippet=self.line(line))

    def suppressed(self, f: Finding) -> bool:
        ids = self.suppressions.get(f.line)
        return bool(ids) and ("*" in ids or f.rule in ids)


# ----------------------------------------------------------- rule registry

#: rule id -> (callable, one-line summary). Module rules take
#: ``(module, ctx)``; project rules take ``(ctx)`` and read
#: ``ctx.modules``.
MODULE_RULES: dict = {}
PROJECT_RULES: dict = {}
#: rule id -> family name (the defining rules/ module: "donation",
#: "concurrency", ...) — lets the CLI's --rules accept a whole family
RULE_FAMILIES: dict = {}


def _family_of(fn) -> str:
    return fn.__module__.rsplit(".", 1)[-1]


def module_rule(rule_id: str, summary: str):
    def deco(fn):
        assert rule_id not in MODULE_RULES and rule_id not in PROJECT_RULES
        MODULE_RULES[rule_id] = (fn, summary)
        RULE_FAMILIES[rule_id] = _family_of(fn)
        fn.rule_id = rule_id
        return fn
    return deco


def project_rule(rule_id: str, summary: str):
    def deco(fn):
        assert rule_id not in MODULE_RULES and rule_id not in PROJECT_RULES
        PROJECT_RULES[rule_id] = (fn, summary)
        RULE_FAMILIES[rule_id] = _family_of(fn)
        fn.rule_id = rule_id
        return fn
    return deco


def expand_rule_names(names) -> set[str]:
    """Resolve a mix of rule ids and family names ("concurrency",
    "donation", …) to rule ids; unknown tokens pass through so the
    CLI can report them."""
    _load_rules()
    out: set[str] = set()
    families: dict[str, set] = {}
    for rid, fam in RULE_FAMILIES.items():
        families.setdefault(fam, set()).add(rid)
    for name in names:
        if name in families:
            out |= families[name]
        else:
            out.add(name)
    return out


def _load_rules() -> None:
    """Import the rule modules (registration is an import side
    effect); idempotent."""
    from rocalphago_tpu.analysis import rules  # noqa: F401


def all_rule_ids() -> list[str]:
    _load_rules()
    return sorted(list(MODULE_RULES) + list(PROJECT_RULES))


def rule_catalog() -> dict[str, str]:
    """id -> one-line summary, for ``lint.py --list-rules`` and the
    doc table."""
    _load_rules()
    cat = {rid: s for rid, (_, s) in MODULE_RULES.items()}
    cat.update({rid: s for rid, (_, s) in PROJECT_RULES.items()})
    return dict(sorted(cat.items()))


# ----------------------------------------------------------------- driver

class LintContext:
    """Shared state for one lint run: config, the parsed modules, and
    a scratch cache for cross-module indexes (donation registry, jit
    map) built lazily by the rule modules."""

    def __init__(self, root: str, config, modules: list[ModuleInfo]):
        self.root = root
        self.config = config
        self.modules = modules
        self.cache: dict = {}

    def read_doc(self, rel: str) -> str | None:
        """Repo doc contents (None when absent); inventory rules diff
        against these."""
        p = os.path.join(self.root, rel)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


def discover_files(root: str, config) -> list[str]:
    """Repo-relative paths of the python files under
    ``config.include`` minus ``config.exclude`` prefixes."""
    out: list[str] = []
    for entry in config.include:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            out.append(entry)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    excl = tuple(config.exclude)
    return [p for p in sorted(set(out))
            if not any(p == e or p.startswith(e.rstrip("/") + "/")
                       for e in excl)]


def parse_modules(root: str, rels: list[str]):
    """-> (modules, parse_findings). A file that does not parse is a
    finding, not a crash — the lint must degrade per-file."""
    modules, findings = [], []
    for rel in rels:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as f:
                src = f.read()
            modules.append(ModuleInfo(rel, src, path=full))
        except SyntaxError as e:
            findings.append(Finding(
                path=rel, line=int(e.lineno or 1), rule="parse-error",
                message=f"file does not parse: {e.msg}"))
        except OSError as e:
            findings.append(Finding(
                path=rel, line=1, rule="parse-error",
                message=f"unreadable: {e}"))
    return modules, findings


def _enabled(rule_id: str, config, only) -> bool:
    if only is not None and rule_id not in only:
        return False
    return rule_id not in set(config.disable)


def run_lint(root: str, config, only: set[str] | None = None
             ) -> list[Finding]:
    """Full run: discover → parse → rules → suppression filter.
    Returns ALL findings (baselining is the caller's concern — see
    :mod:`.baseline`), sorted by path/line/rule."""
    _load_rules()
    rels = discover_files(root, config)
    modules, findings = parse_modules(root, rels)
    modules = [m for m in modules if not m.skip_file]
    ctx = LintContext(root, config, modules)
    for mod in modules:
        for rule_id, (fn, _) in MODULE_RULES.items():
            if _enabled(rule_id, config, only):
                findings.extend(f for f in fn(mod, ctx)
                                if not mod.suppressed(f))
    by_rel = {m.rel: m for m in modules}
    for rule_id, (fn, _) in PROJECT_RULES.items():
        if _enabled(rule_id, config, only):
            for f in fn(ctx):
                mod = by_rel.get(f.path)
                if mod is None or not mod.suppressed(f):
                    findings.append(f)
    return sorted(findings)


def lint_source(source: str, rel: str = "<fixture>.py",
                rules: set[str] | None = None, config=None,
                root: str = ".", docs: dict[str, str] | None = None
                ) -> list[Finding]:
    """Lint one in-memory source string — the fixture-test entry
    point. ``docs`` maps repo-relative doc paths to contents for the
    inventory rules; the default (no docs) makes the doc-sync rules
    no-ops rather than diffing a fixture against the real repo docs."""
    from rocalphago_tpu.analysis.config import LintConfig
    _load_rules()
    config = config or LintConfig()
    mod = ModuleInfo(rel, source)
    if mod.skip_file:
        return []
    ctx = LintContext(root, config, [mod])
    ctx.read_doc = lambda rel_, _d=(docs or {}): _d.get(rel_)  # type: ignore
    findings: list[Finding] = []
    for rule_id, (fn, _) in MODULE_RULES.items():
        if _enabled(rule_id, config, rules):
            findings.extend(f for f in fn(mod, ctx)
                            if not mod.suppressed(f))
    for rule_id, (fn, _) in PROJECT_RULES.items():
        if _enabled(rule_id, config, rules):
            findings.extend(f for f in fn(ctx)
                            if f.path != mod.rel or not mod.suppressed(f))
    return sorted(findings)
