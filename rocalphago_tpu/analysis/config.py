"""jaxlint configuration: the ``[tool.jaxlint]`` block in
pyproject.toml.

Recognized keys (all optional — defaults lint the whole repo):

* ``include`` — list of repo-relative files/dirs to lint;
* ``exclude`` — list of repo-relative prefixes to drop;
* ``disable`` — list of rule ids switched off globally;
* ``baseline`` — path of the committed baseline file;
* ``docs.observability`` / ``docs.resilience`` / ``docs.knobs`` —
  where the inventory rules find their documented tables;
* ``report_modules`` — files whose metric-name *consumers* are
  checked against the produced set (obs_report drift).

Python 3.10 has no ``tomllib``, so a minimal single-table parser
handles exactly the value shapes above (strings, string lists,
booleans); ``tomllib`` is used when available.
"""

from __future__ import annotations

import dataclasses
import os
import re

DEFAULT_INCLUDE = ("rocalphago_tpu", "scripts", "benchmarks", "tests",
                   "bench.py")
DEFAULT_EXCLUDE = ()


@dataclasses.dataclass
class LintConfig:
    include: tuple = DEFAULT_INCLUDE
    exclude: tuple = DEFAULT_EXCLUDE
    disable: tuple = ()
    baseline: str = ".jaxlint-baseline.json"
    docs_observability: str = "docs/OBSERVABILITY.md"
    docs_resilience: str = "docs/RESILIENCE.md"
    docs_knobs: str = "docs/KNOBS.md"
    docs_serving: str = "docs/SERVING.md"
    docs_gateway: str = "docs/GATEWAY.md"
    docs_replaynet: str = "docs/REPLAYNET.md"
    docs_rollout: str = "docs/ROLLOUT.md"
    report_modules: tuple = ("scripts/obs_report.py",)
    #: module whose ``ServePool.stats`` dict is the serve-probe
    #: block producer (diffed against docs_serving's JSON schema)
    serve_probe_module: str = "rocalphago_tpu/serve/sessions.py"
    #: module whose ``GatewayServer.stats`` dict is the gateway-probe
    #: block producer (diffed against docs_gateway's JSON schema)
    gateway_probe_module: str = "rocalphago_tpu/gateway/server.py"
    #: module whose ``ReplayService.stats`` dict is the replaynet
    #: probe producer (diffed against docs_replaynet's JSON schema)
    replaynet_probe_module: str = "rocalphago_tpu/replaynet/server.py"
    #: module whose ``RolloutRouter.stats`` dict is the router probe
    #: producer (diffed against docs_rollout's JSON schema)
    router_probe_module: str = "rocalphago_tpu/rollout/router.py"
    #: module whose ``CanaryController.stats`` dict is the canary
    #: probe producer (diffed against docs_rollout's JSON schema)
    canary_probe_module: str = "rocalphago_tpu/rollout/canary.py"


_KEY_MAP = {
    "include": "include", "exclude": "exclude", "disable": "disable",
    "baseline": "baseline",
    "docs.observability": "docs_observability",
    "docs.resilience": "docs_resilience",
    "docs.knobs": "docs_knobs",
    "docs.serving": "docs_serving",
    "docs.gateway": "docs_gateway",
    "docs.replaynet": "docs_replaynet",
    "docs.rollout": "docs_rollout",
    "report_modules": "report_modules",
    "serve_probe_module": "serve_probe_module",
    "gateway_probe_module": "gateway_probe_module",
    "replaynet_probe_module": "replaynet_probe_module",
    "router_probe_module": "router_probe_module",
    "canary_probe_module": "canary_probe_module",
}


def _mini_toml_table(text: str, table: str) -> dict:
    """Parse one ``[table]`` of simple ``key = value`` lines; value
    shapes: basic string, list of basic strings, true/false."""
    out: dict = {}
    lines = text.splitlines()
    in_table = False
    buf = None  # (key, accumulated) while a list spans lines
    for raw in lines:
        line = raw.strip()
        if line.startswith("["):
            in_table = line == f"[{table}]"
            buf = None
            continue
        if not in_table or not line or line.startswith("#"):
            continue
        if buf is not None:
            buf = (buf[0], buf[1] + " " + line)
            if "]" in line:
                out[buf[0]] = buf[1]
                buf = None
            continue
        m = re.match(r'(?:"([^"]+)"|([A-Za-z0-9_.\-]+))\s*=\s*(.*)$', line)
        if not m:
            continue
        key = m.group(1) or m.group(2)
        val = m.group(3).strip()
        if val.startswith("[") and "]" not in val:
            buf = (key, val)
            continue
        out[key] = val
    parsed = {}
    for key, val in out.items():
        val = val.split("#")[0].strip() if not val.startswith("[") \
            else val
        if val.startswith("["):
            inner = val[val.index("[") + 1:val.rindex("]")]
            parsed[key] = [s for s in re.findall(r'"([^"]*)"', inner)]
        elif val.startswith('"'):
            parsed[key] = val.strip('"')
        elif val in ("true", "false"):
            parsed[key] = val == "true"
        else:
            parsed[key] = val
    return parsed


def _read_jaxlint_table(pyproject_path: str) -> dict:
    try:
        with open(pyproject_path, "rb") as f:
            data = f.read()
    except OSError:
        return {}
    try:
        import tomllib  # Python >= 3.11
        return (tomllib.loads(data.decode("utf-8"))
                .get("tool", {}).get("jaxlint", {}))
    except ImportError:
        return _mini_toml_table(data.decode("utf-8"), "tool.jaxlint")


def load_config(root: str) -> LintConfig:
    """Config from ``<root>/pyproject.toml``; defaults when the block
    (or the file) is absent."""
    table = _read_jaxlint_table(os.path.join(root, "pyproject.toml"))
    cfg = LintConfig()
    for toml_key, attr in _KEY_MAP.items():
        if toml_key in table:
            val = table[toml_key]
            if isinstance(getattr(cfg, attr), tuple):
                val = tuple(val) if isinstance(val, list) else (val,)
            setattr(cfg, attr, val)
    return cfg
