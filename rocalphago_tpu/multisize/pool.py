"""One FCN checkpoint serving every board size: the multi-size pool.

The fully-convolutional heads (``models/value.py`` ``head="fcn"``,
``models/nn_util.py::PointHead``) make the param pytree board-size-
free, so ONE set of weights applies at 9×9, 13×13 and 19×19 unchanged
— but the device search is still one compiled program per board size
(static shapes: slabs, planes, action spaces all carry H×W).
:class:`MultiSizePool` owns that split: the weights are shared BY
REFERENCE across a ladder of per-size :class:`~rocalphago_tpu.serve.
sessions.ServePool`\\ s (each with its own compiled searcher +
:class:`~rocalphago_tpu.serve.evaluator.BatchingEvaluator`), and
sessions route by requested size. Opening a game at a new size is a
dict lookup, not a model rebuild — the GTP ``boardsize`` command on a
multi-size engine re-routes the session instead of erroring.

Per-size facades come from :meth:`~rocalphago_tpu.models.nn_util.
NeuralNetBase.at_board`, which shares the caller's params (no copy);
size-locked legacy heads (``dense``/``bias``) are refused at
construction with a pointer to docs/MULTISIZE.md.

Observability: each member pool labels its admission metrics with its
size (``serve_sessions_live{board=}``, ``serve_sheds_total{board=}``)
and :meth:`MultiSizePool.stats` publishes one ``ServePool.stats()``
row per active size under ``boards`` — the probe block a multi-size
balancer keys on (schema: docs/MULTISIZE.md; the single-pool
``serve`` schema in docs/SERVING.md is unchanged).
"""

from __future__ import annotations

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.serve.sessions import ServePool, ServeSession

#: the ladder a multi-size deployment serves by default
DEFAULT_SIZES = (9, 13, 19)


class MultiSizePool:
    """A ladder of per-size :class:`ServePool`\\ s over ONE shared
    FCN param pytree.

    Parameters
    ----------
    value_net, policy_net : size-generic nets (``size_generic()``
        True — FCN heads); their params are shared by reference with
        every per-size facade.
    sizes : board sizes to serve (default ``(9, 13, 19)``); more can
        join later via :meth:`add_size`.
    default_size : the size :meth:`open_session` uses when none is
        requested (default: the nets' native board if it is in
        ``sizes``, else the largest size).
    pool_kwargs : everything else (``n_sim``, ``batch_sizes``,
        ``slo_s``, ``metrics`` …) is forwarded to every member
        :class:`ServePool` unchanged.
    """

    def __init__(self, value_net, policy_net, sizes=DEFAULT_SIZES,
                 default_size: int | None = None, **pool_kwargs):
        for net in (policy_net, value_net):
            if not net.size_generic():
                raise ValueError(
                    f"{type(net).__name__} has a size-locked head "
                    f"({net.module.head!r}): a multi-size pool needs "
                    "FCN heads (head='fcn'; docs/MULTISIZE.md)")
        self.policy = policy_net
        self.value = value_net
        self._pool_kwargs = dict(pool_kwargs)
        self._pool_kwargs["label_board"] = True
        # ONE transposition cache across the whole ladder (cache keys
        # carry the board size, so members cannot cross-hit): built
        # here when the env switch is on so every member shares it
        # rather than each building its own
        from rocalphago_tpu.serve import evalcache
        if self._pool_kwargs.get("eval_cache") is None \
                and evalcache.cache_enabled():
            self._pool_kwargs["eval_cache"] = evalcache.EvalCache()
        self.eval_cache = self._pool_kwargs.get("eval_cache")
        self.warmed = False
        self._lock = lockcheck.make_lock("MultiSizePool._lock")
        self._pools: dict = {}            # guarded-by: self._lock
        sizes = tuple(sorted(set(int(s) for s in sizes)))
        if not sizes:
            raise ValueError("a multi-size pool needs at least one size")
        for s in sizes:
            self._build_pool(s)
        if default_size is None:
            default_size = (policy_net.board
                            if policy_net.board in sizes else sizes[-1])
        self.default_size = int(default_size)
        self.pool_for(self.default_size)   # default must be active

    # ------------------------------------------------------- routing

    def _build_pool(self, size: int) -> ServePool:
        # at_board facades share the caller's params BY REFERENCE —
        # the whole ladder serves one checkpoint, and a weight swap
        # on the source nets is one swap, not one per size
        policy = (self.policy if size == self.policy.board
                  else self.policy.at_board(size))
        value = (self.value if size == self.value.board
                 else self.value.at_board(size))
        pool = ServePool(value, policy, **self._pool_kwargs)
        with self._lock:
            self._pools[size] = pool
        return pool

    @property
    def sizes(self) -> tuple:
        """Active sizes, ascending."""
        with self._lock:
            return tuple(sorted(self._pools))

    def pool_for(self, size: int) -> ServePool:
        """The member pool serving ``size`` (KeyError when the size
        is not active — :meth:`add_size` activates one)."""
        with self._lock:
            pool = self._pools.get(int(size))
        if pool is None:
            raise KeyError(
                f"board size {size} not active (serving "
                f"{self.sizes}); MultiSizePool.add_size({size}) "
                "activates it")
        return pool

    def add_size(self, size: int) -> ServePool:
        """Activate a new size (idempotent): builds its pool — the
        searcher/evaluator compile lazily on first traffic, or
        eagerly via :meth:`warm`."""
        size = int(size)
        with self._lock:
            pool = self._pools.get(size)
        return pool if pool is not None else self._build_pool(size)

    # ------------------------------------------------------ sessions

    def open_session(self, size: int | None = None,
                     **kwargs) -> ServeSession:
        """Admit one game at ``size`` (default ``default_size``);
        kwargs (``resilient``, ``komi`` …) go to
        :meth:`ServePool.open_session`."""
        return self.pool_for(
            self.default_size if size is None else size
        ).open_session(**kwargs)

    def driver(self, sessions):
        """Fleet drive over ``sessions`` — which must all live in the
        SAME member pool (the lockstep drive stacks tree slabs on one
        batch axis; mixed H×W cannot stack)."""
        boards = {s.raw.board for s in sessions}
        if len(boards) != 1:
            raise ValueError(
                f"fleet driver needs one board size, got {sorted(boards)}")
        return self.pool_for(boards.pop()).driver(sessions)

    # -------------------------------------------------------- rollout

    @property
    def params_version(self) -> int:
        """The ladder's converged version (the default pool's — every
        fan-out below applies one version number to all sizes)."""
        return self.pool_for(self.default_size).params_version

    def _fanout(self, op, version: int | None = None) -> int:
        # one version number across the whole ladder: the first pool
        # allocates (when version is None), the rest reuse it
        v = version
        for s in self.sizes:
            v = op(self.pool_for(s), v)
        return v

    def set_params(self, params_p=None, params_v=None,
                   version: int | None = None) -> int:
        """Hot-swap every member pool to ``(params_p, params_v)`` (or
        promote a staged ``version``) — one checkpoint, one version
        number, every size; the source nets' params follow so a later
        :meth:`add_size` facade shares the new weights."""
        v = self._fanout(
            lambda pool, ver: pool.set_params(params_p, params_v,
                                              version=ver),
            version)
        pp, pv = self.pool_for(self.default_size) \
            .evaluator.version_params(v)
        self.policy.params = pp
        self.value.params = pv
        return v

    def stage_params(self, params_p, params_v,
                     version: int | None = None) -> int:
        """Stage a candidate on every member pool (canary arm)."""
        return self._fanout(
            lambda pool, ver: pool.stage_params(params_p, params_v,
                                                version=ver),
            version)

    def promote_version(self, version: int) -> int:
        """Promote a staged version on every member pool."""
        v = int(version)
        for s in self.sizes:
            self.pool_for(s).promote_version(v)
        pp, pv = self.pool_for(self.default_size) \
            .evaluator.version_params(v)
        self.policy.params = pp
        self.value.params = pv
        return v

    def discard_version(self, version: int) -> None:
        """Retire a staged version on every member pool."""
        for s in self.sizes:
            self.pool_for(s).discard_version(version)

    # -------------------------------------------------------- warmup

    def warm(self, sizes=None) -> None:
        """Compile every (or the given) member pool ahead of traffic."""
        for s in (self.sizes if sizes is None else sizes):
            self.pool_for(s).warm()
        self.warmed = True

    # ----------------------------------------------------- lifecycle

    def close(self) -> None:
        with self._lock:
            pools = list(self._pools.values())
        for pool in pools:
            pool.close()

    def __enter__(self) -> "MultiSizePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The multi-size probe block (schema: docs/MULTISIZE.md):
        one ``ServePool.stats()`` row per active size plus the
        routing facts a balancer needs."""
        with self._lock:
            pools = dict(self._pools)
        boards = {str(s): pools[s].stats() for s in sorted(pools)}
        return {
            "multisize": True,
            "default_board": self.default_size,
            "params_version": self.params_version,
            "sessions_live": sum(
                b["sessions"]["live"] for b in boards.values()),
            "boards": boards,
        }
