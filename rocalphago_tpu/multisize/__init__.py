"""Multi-size serving: one net, every board.

The FCN policy/value heads make one param pytree apply at any board
size; this package turns that into a serving property.
:class:`~rocalphago_tpu.multisize.pool.MultiSizePool` shares the
weights by reference across one compiled
:class:`~rocalphago_tpu.serve.sessions.ServePool` per active size and
routes sessions by requested size — ``boardsize`` on a multi-size GTP
engine (``--serve-sizes``) re-routes the session instead of
rebuilding the engine. Design + probe schema + measured transfer:
docs/MULTISIZE.md. The training-side counterpart (progressive-size
curriculum over the same checkpoint) is
``rocalphago_tpu/training/curriculum.py``.
"""

from rocalphago_tpu.multisize.pool import (  # noqa: F401
    DEFAULT_SIZES,
    MultiSizePool,
)
