"""Shared small utilities (coordinate flattening, dihedral symmetries).

Parity: the reference's ``AlphaGo/util.py`` (``flatten_idx`` /
``unflatten_idx``; SGF helpers live in :mod:`rocalphago_tpu.data.sgf`).
"""

from rocalphago_tpu.utils.coords import (  # noqa: F401
    flatten_idx,
    unflatten_idx,
)
