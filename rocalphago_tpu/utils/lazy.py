"""Shared PEP 562 lazy re-export helper for package ``__init__``s.

Eager submodule imports in a package ``__init__`` make
``python -m package.submodule`` warn (the module is already in
``sys.modules`` before runpy executes it) and pull every submodule's
dependencies into any one CLI's start. Usage::

    _EXPORTS = {"Thing": "package.submodule", ...}
    __getattr__, __dir__, __all__ = make_lazy(__name__, _EXPORTS)
"""

from __future__ import annotations

import importlib


def make_lazy(package: str, exports: dict):
    """Return ``(__getattr__, __dir__, __all__)`` resolving each name
    in ``exports`` from its submodule on first attribute access."""

    def __getattr__(name: str):
        module = exports.get(name)
        if module is None:
            raise AttributeError(
                f"module {package!r} has no attribute {name!r}")
        return getattr(importlib.import_module(module), name)

    def __dir__():
        return sorted(exports)

    return __getattr__, __dir__, list(exports)
