"""Board-coordinate helpers (reference: ``AlphaGo/util.py``).

Convention: a point is ``(x, y)`` with ``x`` the row index into the
board array; the flat action space is ``x * size + y`` with the extra
index ``size * size`` meaning pass (device-side engines use the flat
form exclusively — fixed shapes, no tuples).
"""

from __future__ import annotations


def flatten_idx(position, size: int) -> int:
    x, y = position
    return x * size + y


def unflatten_idx(idx: int, size: int):
    return divmod(idx, size)


def pass_idx(size: int) -> int:
    return size * size
