"""On-device dihedral (D4) augmentation.

Parity: the reference SL trainer's ``BOARD_TRANSFORMATIONS`` — 8 board
symmetries applied randomly per sample on the *host* with
``np.rot90/fliplr`` (SURVEY.md §2 "SL trainer"). Here the transform is
a jitted gather on device: one random int per sample picks the group
element, applied to both the NHWC plane stack and the flat action
index, so augmentation rides along inside the compiled train step at
zero host cost.

Group element ``t`` in 0..7 = ``rot90^(t % 4)`` then horizontal flip if
``t >= 4``; ``inverse_transform`` provides the inverse permutation for
symmetry-averaged evaluation (used by search).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def transform_planes(x: jax.Array, t: jax.Array) -> jax.Array:
    """Apply group element ``t`` (int scalar) to one ``[s, s, F]`` (or
    ``[s, s]``) array. Branchless: composed from flips/transposes picked
    by ``lax.switch``."""
    return jax.lax.switch(t, [
        lambda a: a,
        lambda a: jnp.rot90(a, 1),
        lambda a: jnp.rot90(a, 2),
        lambda a: jnp.rot90(a, 3),
        lambda a: jnp.flip(a, axis=1),
        lambda a: jnp.rot90(jnp.flip(a, axis=1), 1),
        lambda a: jnp.rot90(jnp.flip(a, axis=1), 2),
        lambda a: jnp.rot90(jnp.flip(a, axis=1), 3),
    ], x)


def transform_action(action: jax.Array, t: jax.Array, size: int
                     ) -> jax.Array:
    """Apply group element ``t`` to a flat board action (pass = ``size²``
    maps to itself)."""
    n = size * size
    grid = jnp.arange(n, dtype=action.dtype).reshape(size, size)
    # forward-transform the *index grid*: entry (r, c) of the transformed
    # grid names the source point that lands at (r, c); we need the
    # inverse map (where does `action` land), so scatter instead:
    moved = transform_planes(grid, t).reshape(n)      # moved[dst] = src
    dest = jnp.zeros((n,), action.dtype).at[moved].set(
        jnp.arange(n, dtype=action.dtype))            # dest[src] = dst
    return jnp.where(action >= n, action, dest[jnp.minimum(action, n - 1)])


def inverse_transform_planes(x: jax.Array, t: jax.Array) -> jax.Array:
    """Inverse group element (t<4 → rot90^(4-t); t>=4 is an involution
    composed as flip∘rot, whose inverse is rot^{-1}∘flip = itself for
    these generators)."""
    return jax.lax.switch(t, [
        lambda a: a,
        lambda a: jnp.rot90(a, 3),
        lambda a: jnp.rot90(a, 2),
        lambda a: jnp.rot90(a, 1),
        lambda a: jnp.flip(a, axis=1),
        lambda a: jnp.flip(jnp.rot90(a, 3), axis=1),
        lambda a: jnp.flip(jnp.rot90(a, 2), axis=1),
        lambda a: jnp.flip(jnp.rot90(a, 1), axis=1),
    ], x)


def random_transform_batch(rng: jax.Array, planes: jax.Array,
                           actions: jax.Array, size: int):
    """Random per-sample symmetry for a training batch
    (``planes [B,s,s,F]``, ``actions [B]``)."""
    t = jax.random.randint(rng, (planes.shape[0],), 0, 8)
    planes = jax.vmap(transform_planes)(planes, t)
    actions = jax.vmap(
        lambda a, ti: transform_action(a, ti, size))(actions, t)
    return planes, actions
