"""AlphaZero-style training iteration over the on-device search.

Beyond the reference's scope (its RL trainer REINFORCEs the raw
policy against a past self; ``AlphaGo/training/
reinforcement_policy_trainer.py``, SURVEY.md §3.2): this closes the
modern loop the device search makes possible — self-play games where
EVERY move comes from the batched on-device MCTS
(:func:`search.device_mcts.make_mcts_selfplay`), then one update that
trains the policy toward the search's visit distributions and the
value net toward the game outcomes:

    loss = CE(policy(s_t), π_t) + MSE(value(s_t), z_t)

with π_t the root visit distribution at ply t and z_t the final
outcome from ply t's player-to-move perspective.

TPU-native structure (same watchdog discipline as the chunked RL
iteration): the game phase is the chunk-driven search self-play; the
training phase REPLAYS the recorded actions through the engine in
compiled segments, accumulating both nets' gradients in a
params-shaped carry — constant memory in game length, no
``[T, B, 19, 19, F]`` plane materialization; only the visit targets
``[T, B, A]`` are kept (a few MB). One optimizer step per net per
iteration.

Policy targets and the pass action: the policy net's head covers the
N board points (pass is an agent-layer decision, reference parity —
``models/policy.py``), while the search's visit distribution includes
pass. Pass gets visits only when nothing sensible exists (its prior
is 0 otherwise), and those plies carry no board signal — so each
ply's target is the board slice of π renormalized, and plies whose
board mass is zero (forced passes, finished games) get weight 0.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from rocalphago_tpu.data.replay import ZeroGames
from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.features import pyfeatures
from rocalphago_tpu.features.planes import batched_encoder, needs_member
from rocalphago_tpu.features.pyfeatures import output_planes
from rocalphago_tpu.io.checkpoint import pack_rng, unpack_rng
from rocalphago_tpu.obs import jaxobs, trace
from rocalphago_tpu.ops.labels import terminal_labels
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.runtime.pipeline import ChunkPipeline
from rocalphago_tpu.search.device_mcts import make_mcts_selfplay
from rocalphago_tpu.search.selfplay import sensible_mask


class ZeroState(NamedTuple):
    policy_params: dict
    value_params: dict
    opt_policy: tuple
    opt_value: tuple
    iteration: jax.Array   # int32 []
    rng: jax.Array         # uint32 key data


def next_keys(rng_bits):
    """Step the zero rng chain one iteration: ``(rng_bits) ->
    (next_rng_bits, game_key)``.

    EXACTLY the split ``iteration`` performs: the game key sequence
    depends only on the seed rng, never on game content or params —
    which is what lets a detached self-play actor walk the chain
    locally and reproduce the synchronous loop's games bit-for-bit
    (docs/SCALE.md)."""
    key, game_key = jax.random.split(unpack_rng(rng_bits))
    return pack_rng(key), game_key


def make_zero_iteration(cfg: jaxgo.GoConfig, policy_features: tuple,
                        value_features: tuple, policy_apply: Callable,
                        value_apply: Callable, tx_policy, tx_value,
                        batch: int, move_limit: int, n_sim: int,
                        max_nodes: int | None = None,
                        temperature: float = 1.0,
                        sim_chunk: int = 8, replay_chunk: int = 10,
                        gumbel: bool = False, m_root: int = 16,
                        gumbel_sample: bool = False,
                        dirichlet_alpha: float = 0.0,
                        noise_frac: float = 0.25, mesh=None,
                        cap_p: float | None = None,
                        cap_cheap: int | None = None,
                        cap_per_row: bool = False,
                        forced_k: float = 0.0,
                        aux_weight: float | None = None,
                        value_apply_aux: Callable | None = None):
    """``(ZeroState) -> (ZeroState, metrics)`` — one full iteration:
    search self-play, replay-gradient accumulation for both nets, one
    optimizer step each. Host-driven (chunk-compiled throughout); the
    search phase and every replay segment stay under the TPU worker
    watchdog.

    Self-play-economics knobs (KataGo; docs/PERFORMANCE.md "Self-play
    economics"; all default OFF and the OFF path is pinned
    bit-identical): ``cap_p``/``cap_cheap``/``cap_per_row`` and
    ``forced_k`` pass through to :func:`make_mcts_selfplay` (env
    defaults ``ROCALPHAGO_CAP_P``/``ROCALPHAGO_CAP_CHEAP`` resolve
    HERE so the recorder and the loss masking agree on whether cap
    randomization is live). With cap randomization on, only
    full-searched plies carry policy-loss weight — cheap plies still
    train the value (and aux) heads, which is the economics bet: a
    cheap search is a fine move-picker and a fine value label, just
    not a policy target.

    ``aux_weight`` (> 0, env default ``ROCALPHAGO_AUX_WEIGHT``)
    enables the auxiliary ownership/score regression against the
    engine's terminal labels, weighted into the value-net loss;
    requires ``value_apply_aux`` (an apply returning
    ``(value, {"ownership", "score"})`` — build the net with
    ``aux_heads=("ownership", "score")``, see ``models/value.py``).
    Aux terms are masked exactly like the value loss (live plies of
    FINISHED games: a move-capped game's terminal labels describe a
    half-played board).
    """
    n = cfg.num_points
    if cap_p is None:
        cap_p = float(os.environ.get("ROCALPHAGO_CAP_P", "") or 0.0)
    if cap_cheap is None:
        cap_cheap = int(os.environ.get("ROCALPHAGO_CAP_CHEAP", "")
                        or max(1, n_sim // 4))
    cheap = max(1, min(int(cap_cheap), n_sim))
    econ = cap_p > 0 and cheap < n_sim
    if aux_weight is None:
        aux_weight = float(
            os.environ.get("ROCALPHAGO_AUX_WEIGHT", "") or 0.0)
    aux = aux_weight > 0
    if aux and value_apply_aux is None:
        raise ValueError(
            "aux_weight > 0 needs value_apply_aux — an apply "
            "returning (value, aux dict); build the value net with "
            "aux_heads=('ownership', 'score')")
    selfplay = make_mcts_selfplay(
        cfg, policy_features, value_features, policy_apply,
        value_apply, batch, move_limit, n_sim, max_nodes,
        temperature=temperature, sim_chunk=sim_chunk,
        record_visits=True, gumbel=gumbel, m_root=m_root,
        gumbel_sample=gumbel_sample,
        dirichlet_alpha=dirichlet_alpha, noise_frac=noise_frac,
        mesh=mesh, cap_p=cap_p, cap_cheap=cheap,
        cap_per_row=cap_per_row, forced_k=forced_k)
    vlabels = jax.jit(jax.vmap(
        functools.partial(terminal_labels, cfg))) if aux else None

    n_policy_planes = output_planes(policy_features)
    vgd = jax.vmap(lambda s: jaxgo.group_data(
        cfg, s.board, with_member=needs_member(value_features),
        with_zxor=cfg.enforce_superko, labels=s.labels))
    venc = batched_encoder(cfg, value_features)
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(jaxgo.step, cfg))

    def ply(policy_params, value_params, winners, finished,
            aux_labels, carry, xs):
        states, grads_p, grads_v, stats = carry
        if econ:
            actions_t, live_t, visits_t, full_t = xs
        else:
            actions_t, live_t, visits_t = xs
            full_t = None
        if mesh is not None:
            # anchor the replayed game batch on the data axis (same
            # pattern as the RL iteration); the batch-summed losses
            # and grads then all-reduce via XLA-inserted collectives
            states = lax.with_sharding_constraint(
                states, meshlib.data_sharding(mesh))

        gd = vgd(states)
        planes = venc(states, gd)
        sens = vsens(states, gd)
        # search-policy target: board slice of the per-ply target
        # distribution (root visit counts, or π' under gumbel),
        # renormalized (see module docstring). Visit counts are
        # integers so mass>0 implies mass>=1; π' is a probability
        # vector whose board mass can be any positive fraction —
        # normalize by the actual mass and skip plies where almost
        # everything sat on pass
        board_counts = visits_t[:, :n].astype(jnp.float32)
        mass = board_counts.sum(axis=-1)
        pi = board_counts / jnp.maximum(mass, 1e-6)[:, None]
        w = live_t * (mass > 1e-3)                   # f32-able [B]
        wf = w.astype(jnp.float32)
        if full_t is not None:
            # playout-cap randomization: cheap-searched plies carry
            # no policy target (their visit distribution is too
            # shallow to teach), but still replay into the value/aux
            # losses below
            wf = wf * full_t
        # outcome from ply t's player-to-move perspective
        z = (winners * states.turn).astype(jnp.float32)
        turn_f = states.turn.astype(jnp.float32)

        def loss_fn(pp, vp):
            # nested layout: the policy reads the prefix slice of the
            # value planes (one encode serves both nets, as in search)
            logits = policy_apply(pp, planes[..., :n_policy_planes])
            neg = jnp.finfo(logits.dtype).min
            logp = jax.nn.log_softmax(
                jnp.where(sens, logits, neg), axis=-1)
            ce = -(pi * logp).sum(axis=-1)
            if aux_labels is None:
                v = value_apply(vp, planes)
            else:
                v, aux_out = value_apply_aux(vp, planes)
            mse = (v - z) ** 2
            lp = (wf * ce).sum() / batch
            # value targets only from games that actually ENDED (two
            # passes): a move-capped game's area score labels a
            # half-played board (the round-4 run trained 267
            # iterations of value net exclusively on such labels —
            # VERDICT r4 weak #2). Policy targets stay per-ply (the
            # visit distribution is valid however the game ends).
            livef = live_t.astype(jnp.float32) * finished
            lv = (livef * mse).sum() / batch
            # win-prediction accuracy (VERDICT r3 #7): the learning
            # signal the paper reports — live non-draw plies where
            # the value head's SIGN matches the game's outcome
            decided = livef * (z != 0)
            correct = (decided * ((v > 0) == (z > 0))).sum()
            aux_stats = ()
            total = lp + lv
            if aux_labels is not None:
                # terminal ownership/score, rotated to the player to
                # move like z (the labels are black-positive) and
                # masked exactly like the value loss — a half-played
                # board's "terminal" labels teach nothing
                own_l, score_l = aux_labels
                own_t = own_l.astype(jnp.float32) * turn_f[:, None]
                l_own = (livef * ((aux_out["ownership"] - own_t) ** 2
                                  ).mean(axis=-1)).sum() / batch
                sc_t = score_l * turn_f
                l_sc = (livef * ((aux_out["score"] - sc_t) ** 2
                                 )).sum() / batch
                total = total + aux_weight * (l_own + l_sc)
                aux_stats = (l_own, l_sc)
            return total, (lp, lv, correct, decided.sum(),
                           livef.sum()) + aux_stats

        (gp, gv), st = jax.grad(
            loss_fn, argnums=(0, 1), has_aux=True)(
                policy_params, value_params)
        grads_p = jax.tree.map(jnp.add, grads_p, gp)
        grads_v = jax.tree.map(jnp.add, grads_v, gv)
        stats = tuple(s + d for s, d in zip(stats, st))
        # share the ply's one group analysis with the rules step
        return (vstep(states, actions_t, gd), grads_p, grads_v, stats)

    # Explicit in/out shardings (not just internal constraints) when a
    # mesh is supplied: params/opt-state/grads replicated, the game
    # batch sharded on `data` (batch-leading for [B]/GoState leaves,
    # axis 1 for the time-major [T, B, ...] histories). Shardings are
    # pytree prefixes, so one NamedSharding covers a whole subtree.
    # This is what lets the detached learner compile ONE program whose
    # inputs arrive from the replay buffer (host numpy) and land
    # directly in the right placement — and it makes the collective
    # layout part of the program's signature instead of an inference.
    if mesh is None:
        _replay_jit = functools.partial(jax.jit, donate_argnums=(4,))
        _update_jit = jax.jit
    else:
        _rep = meshlib.replicated(mesh)
        _dat = meshlib.data_sharding(mesh)
        _tmaj = meshlib.axis_sharding(mesh, 1)
        _carry_sh = (_dat, _rep, _rep, _rep)
        _replay_jit = functools.partial(
            jax.jit, donate_argnums=(4,),
            in_shardings=(_rep, _rep, _dat, _dat, _carry_sh,
                          _tmaj, _tmaj, _tmaj, _tmaj, _dat),
            out_shardings=_carry_sh)
        _update_jit = functools.partial(
            jax.jit,
            in_shardings=(_rep, _rep, _rep, _rep, _dat, _dat, _dat,
                          _rep),
            out_shardings=(_rep, _rep))

    @jaxobs.track("zero.replay_segment")
    @_replay_jit
    def replay_segment(policy_params, value_params, winners, finished,
                       carry, actions, live, visits, full, aux_labels):
        # segment length rides the xs shapes (one compile per distinct
        # segment length — the fixed chunk plus at most one remainder).
        # The carry (replay states + BOTH nets' grad accumulators) is
        # DONATED: it is loop-internal (built fresh per iteration, so
        # the iteration-level retry wrapper stays valid) and donating
        # it keeps pipelined dispatch from doubling the params-shaped
        # accumulators. ``full``/``aux_labels`` are None with the
        # economics flags off — empty pytrees that leave the traced
        # program (and the donation indices) exactly as before.
        def body(c, xs):
            return ply(policy_params, value_params, winners, finished,
                       aux_labels, c, xs), None

        xs = ((actions, live, visits) if full is None
              else (actions, live, visits, full))
        carry, _ = lax.scan(body, carry, xs)
        return carry

    replay_segment.donates_buffers = True

    @jaxobs.track("zero.apply_updates")
    @_update_jit
    def apply_updates(state: ZeroState, grads_p, grads_v, stats,
                      winners, finished, num_moves, key):
        up, opt_p = tx_policy.update(grads_p, state.opt_policy,
                                     state.policy_params)
        uv, opt_v = tx_value.update(grads_v, state.opt_value,
                                    state.value_params)
        metrics = {
            "policy_loss": stats[0],
            "value_loss": stats[1],
            # normalized value diagnostics: mean squared error per
            # live ply (comparable across batch/move-limit configs —
            # AlphaGo paper baseline 0.226/0.234; draws count in the
            # MSE but not the accuracy) and win-prediction sign
            # accuracy over decided plies (0.5 = uninformative)
            "value_mse": stats[1] * batch / jnp.maximum(stats[4], 1.0),
            "value_acc": stats[2] / jnp.maximum(stats[3], 1.0),
            "black_win_rate": (winners > 0).mean(),
            "draw_rate": (winners == 0).mean(),
            "mean_moves": num_moves.astype(jnp.float32).mean(),
            # fraction of games that ended by two passes within the
            # move limit; a low value means the move limit is starving
            # the value net (its loss is masked to finished games)
            "finished_rate": finished.mean(),
        }
        if aux:
            metrics["aux_loss_ownership"] = stats[5]
            metrics["aux_loss_score"] = stats[6]
        return ZeroState(
            optax.apply_updates(state.policy_params, up),
            optax.apply_updates(state.value_params, uv),
            opt_p, opt_v, state.iteration + 1, pack_rng(key)), metrics

    def play(policy_params, value_params, game_key) -> ZeroGames:
        """The ACTOR half: search self-play only — no optimizer
        state, no gradients. Returns the raw game record the replay
        buffer stores; any params snapshot can play (the gated
        best pair, a stale actor copy) without touching the learner.

        The self-play span is honest host wall time (the chunk loop
        syncs per done-poll — see docs/OBSERVABILITY.md)."""
        with trace.span("zero.selfplay", plies=move_limit):
            out = selfplay(policy_params, value_params, game_key)
            if econ:
                final, actions, live, visits, full = out
            else:
                (final, actions, live, visits), full = out, None
            winners = jax.vmap(
                functools.partial(jaxgo.winner, cfg))(final)
            ownership = score = None
            if aux:
                # terminal aux labels off the final position (the
                # loss masks to finished games, so labels from
                # move-capped boards are recorded but never weighted)
                ownership, score = vlabels(final)
        return ZeroGames(actions, live, visits, winners, final.done,
                         full, ownership, score)

    def learn(state: ZeroState, games: ZeroGames):
        """The LEARNER half: replay-gradient accumulation + one
        optimizer step per net, from a recorded :class:`ZeroGames`
        (device arrays or host numpy — the buffer round-trip is
        bit-exact because the record keeps raw recorder dtypes).

        Steps ``state.rng`` exactly as the synchronous iteration
        does (re-deriving the same split ``play``'s caller used), so
        ``learn(state, play(..., game_key))`` ==
        ``iteration(state)`` bit-for-bit."""
        key = unpack_rng(state.rng)
        key, _ = jax.random.split(key)   # the slot play's key used

        actions = jnp.asarray(games.actions)
        live = jnp.asarray(games.live)
        visits = jnp.asarray(games.visits)
        winners = jnp.asarray(games.winners)
        wf = winners.astype(jnp.float32)
        finished = jnp.asarray(games.finished).astype(jnp.float32)
        live_f = live.astype(jnp.float32)
        num_moves = live.sum(axis=0, dtype=jnp.int32)
        full_f = None
        if econ:
            # a v1/flags-off record fed to an economics learner has
            # no mask: every ply was a full search
            full_f = (jnp.ones_like(live_f) if games.full is None
                      else jnp.asarray(games.full).astype(jnp.float32))
        aux_labels = None
        if aux:
            if games.ownership is None or games.score is None:
                raise ValueError(
                    "aux_weight > 0 but the game record carries no "
                    "ownership/score labels — the actor must play "
                    "with aux labelling on (schema v2)")
            aux_labels = (jnp.asarray(games.ownership),
                          jnp.asarray(games.score))

        states = jaxgo.new_states(cfg, batch)
        if mesh is not None:
            # commit every game array to the placement the jitted
            # programs declare (device_put reshards legally even for
            # committed arrays; letting jit see a mismatched
            # committed sharding would error instead)
            states = meshlib.shard_batch(mesh, states)
            winners, wf, finished, num_moves = (
                jax.device_put(x, _dat)
                for x in (winners, wf, finished, num_moves))
            actions, live_f, visits = (
                jax.device_put(x, _tmaj)
                for x in (actions, live_f, visits))
            if full_f is not None:
                full_f = jax.device_put(full_f, _tmaj)
            if aux_labels is not None:
                aux_labels = jax.device_put(aux_labels, _dat)
        grads_p = jax.tree.map(jnp.zeros_like, state.policy_params)
        grads_v = jax.tree.map(jnp.zeros_like, state.value_params)
        # DISTINCT zero arrays, not one repeated: the replay
        # segment donates the carry, and XLA rejects donating the
        # same buffer twice (5 stats; +2 aux-loss slots when on)
        stats = tuple(jnp.float32(0) for _ in range(7 if aux else 5))
        plies = actions.shape[0]
        carry = (states, grads_p, grads_v, stats)
        # pipelined dispatch (runtime.pipeline): the pipeline paces
        # the host to `depth` in-flight segments (device never idle,
        # host never queueing unboundedly) and records the dispatch
        # gap/occupancy telemetry
        pipe = ChunkPipeline(runner="zero.replay")
        with trace.span("zero.replay", plies=plies):
            for offset in range(0, plies, replay_chunk):
                sl = slice(offset, offset + replay_chunk)
                carry = replay_segment(
                    state.policy_params, state.value_params, wf,
                    finished, carry, actions[sl], live_f[sl],
                    visits[sl],
                    None if full_f is None else full_f[sl],
                    aux_labels)
                # fresh handle (the next segment donates the carry,
                # deleting its leaves out from under a retire)
                pipe.push(carry[3][0] + 0.0)
            pipe.finish()
        _, grads_p, grads_v, stats = carry

        with trace.span("zero.update"):
            return apply_updates(state, grads_p, grads_v, stats,
                                 winners, finished, num_moves, key)

    def iteration(state: ZeroState, sp_policy_params=None,
                  sp_value_params=None):
        """One iteration. ``sp_*_params`` override which nets PLAY the
        self-play games (the gated "best"/incumbent pair — AlphaGo's
        evaluator discipline: the data generator only changes when a
        candidate demonstrably beats it); gradients always update
        ``state``'s candidate nets. Default: state's own nets play
        (ungated self-play).

        Composed as ``learn(state, play(...))`` — the synchronous
        path and the actor/learner split (docs/SCALE.md) run the
        same two halves, so the A/B stays bit-exact for free."""
        _, game_key = jax.random.split(unpack_rng(state.rng))
        games = play(
            state.policy_params if sp_policy_params is None
            else sp_policy_params,
            state.value_params if sp_value_params is None
            else sp_value_params, game_key)
        return learn(state, games)

    # the halves ARE the public actor/learner API (training/actor.py
    # and training/learner.py consume them); expose on the composed fn
    iteration.play = play
    iteration.learn = learn
    iteration.batch = batch
    return iteration


def init_zero_state(policy_params, value_params, tx_policy, tx_value,
                    seed: int = 0) -> ZeroState:
    return ZeroState(policy_params, value_params,
                     tx_policy.init(policy_params),
                     tx_value.init(value_params),
                     jnp.int32(0), pack_rng(jax.random.key(seed)))


class ZeroGate:
    """Evaluator gating + best-pair pool for the zero loop.

    Round-4 measured WHY this exists: ungated zero self-play cycles —
    iteration 260 of the 267-iteration 9×9 run LOSES to iteration 80
    raw 25–75 (``results/zero_scale_r4/strength_*.jsonl``; VERDICT r4
    missing #5). The fix is the reference pipeline's own discipline
    (AlphaGo's evaluator; the same past-self mechanism as
    :class:`rocalphago_tpu.training.rl.OpponentPool`): self-play data
    comes from the gated "best" pair, and a training candidate is
    promoted to best only after beating the incumbent in an N-game
    raw-policy match. Promoted pairs snapshot to ``out_dir/pool`` so
    a resumed run keeps its incumbent and a strength ladder can be
    replayed offline.

    Matches are raw-policy (no search): cheap — a gate costs about
    one search-free self-play batch — and it targets exactly the
    regression round 4 measured, which was in *raw* strength (the
    search-backed 260-vs-80 match was level at 4–4). Promotion is
    statistically honest (:meth:`decide`): besides the point-estimate
    ``threshold``, the candidate's decided-game win rate must carry a
    Wilson 95% lower bound ≥ 0.5 — marginal 64-game results
    (0.56–0.62, most of round 5's recorded promotions) no longer
    promote on noise.

    Multi-host: ``pool_dir`` must live on a filesystem shared by all
    processes (the same requirement ``rl.OpponentPool`` documents).
    Snapshots are written by the coordinator only (``write``); every
    process replays identical match programs with identical keys, so
    gate/promotion decisions agree — but resume and ladder sampling
    READ the pool listing, which must therefore be the same
    everywhere.
    """

    def __init__(self, cfg: jaxgo.GoConfig, features: tuple,
                 policy_apply: Callable, pool_dir: str, games: int,
                 threshold: float, temperature: float,
                 move_limit: int, chunk: int = 20, write: bool = True):
        from rocalphago_tpu.search.selfplay import make_selfplay_chunked

        if games % 2:
            raise ValueError(f"gate games must be even, got {games}")
        self.pool_dir = pool_dir
        self.games = games
        self.threshold = threshold
        self.write = write
        self._runner = make_selfplay_chunked(
            cfg, features, policy_apply, policy_apply, games,
            max_moves=move_limit, chunk=chunk,
            temperature=temperature)

    def match(self, params_a, params_b, key) -> dict:
        """N games of A vs B (colors split half/half by the runner);
        returns A's win rate over decided games plus the tally."""
        import numpy as np

        res = self._runner(params_a, params_b, key,
                           stop_when_done=True)
        w = np.asarray(jax.device_get(res.winners))
        half = self.games // 2
        wins_a = int((w[:half] > 0).sum() + (w[half:] < 0).sum())
        draws = int((w == 0).sum())
        decided = self.games - draws
        return {"wins_a": wins_a, "wins_b": decided - wins_a,
                "draws": draws,
                "win_rate_a": wins_a / max(decided, 1)}

    def decide(self, result: dict) -> tuple:
        """``(promoted, wilson_lb)`` from a :meth:`match` result —
        the statistically honest promotion rule (VERDICT r5 #4): the
        candidate needs BOTH the point-estimate threshold AND a
        Wilson 95% lower bound ≥ 0.5 on its decided-game win rate.
        At the default 64-game budget the bound refuses exactly the
        coin-flip promotions round 5 recorded (a 0.59 observed rate
        has lb ≈ 0.47; clearing 0.5 needs ~0.625+). Gate events log
        the bound so every promotion carries its confidence."""
        from rocalphago_tpu.interface.elo import wilson_lower_bound

        decided = result["wins_a"] + result["wins_b"]
        lb = wilson_lower_bound(result["wins_a"], decided)
        return (result["win_rate_a"] >= self.threshold
                and lb >= 0.5), lb

    # ---- best-pair snapshots ------------------------------------

    def _paths(self, iteration: int) -> tuple:
        import os

        return tuple(os.path.join(
            self.pool_dir, f"best.{iteration:05d}.{kind}.msgpack")
            for kind in ("policy", "value"))

    def snapshots(self) -> list:
        """Sorted ``(iteration, policy_path, value_path)`` triples."""
        import glob
        import os
        import re

        out = []
        for p in sorted(glob.glob(os.path.join(
                self.pool_dir, "best.*.policy.msgpack"))):
            m = re.search(r"best\.(\d+)\.policy\.msgpack$", p)
            v = p.replace(".policy.", ".value.")
            if m and os.path.exists(v):
                out.append((int(m.group(1)), p, v))
        return out

    def promote(self, policy_params, value_params,
                iteration: int) -> None:
        if not self.write:
            return
        from flax import serialization

        from rocalphago_tpu.runtime import atomic, faults, retries

        # atomic per-file writes + policy-before-value order: a crash
        # mid-promotion leaves either a complete pair or a policy file
        # whose missing value sibling keeps it OUT of snapshots() —
        # never a torn incumbent. Transient write failures (flaky
        # shared filesystem) retry with backoff; the promotion is
        # idempotent (same params → same bytes).
        @retries.retry(max_attempts=3, base_delay=0.2)
        def write_pair():
            for path, params in zip(self._paths(iteration),
                                    (policy_params, value_params)):
                faults.barrier("zero.promote", iteration)
                atomic.atomic_write_bytes(
                    path, serialization.to_bytes(
                        jax.device_get(params)))

        write_pair()
        # pointer AFTER the pair: a rollout watcher reading the spill
        # always finds the files it names (docs/ROLLOUT.md)
        from rocalphago_tpu.training.actor import write_spill

        ppath, vpath = self._paths(iteration)
        write_spill(self.pool_dir, version=iteration,
                    policy_path=ppath, value_path=vpath)

    def load(self, entry, policy_template, value_template) -> tuple:
        from flax import serialization

        _, ppath, vpath = entry
        out = []
        for path, template in ((ppath, policy_template),
                               (vpath, value_template)):
            with open(path, "rb") as f:
                out.append(serialization.from_bytes(
                    template, f.read()))
        return tuple(out)

    def sample(self, seed: int, iteration: int):
        """Stateless uniform draw over the pool for ladder matches
        (same (seed, iteration) discipline as ``OpponentPool``). The
        LATEST snapshot — the current incumbent — is excluded: a
        ladder probe exists to compare the incumbent against its
        *past* selves, and best-vs-best is 64 games of noise. Returns
        ``None`` until the pool has a past entry."""
        import numpy as np

        snaps = self.snapshots()[:-1]
        if not snaps:
            return None
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, iteration]))
        return snaps[rng.integers(len(snaps))]


def run_training(argv=None) -> dict:
    """CLI: ``python -m rocalphago_tpu.training.zero policy.json
    value.json out_dir [...]`` — the sibling trainers' operational
    surface (argparse, Orbax checkpoint/resume, JSONL metrics +
    metadata.json, per-save model.json exports loadable by
    GTP/tournament).

    Multi-chip/multi-host wired like the sibling trainers:
    ``distributed_init`` (no-op single-process), a ``(data, model)``
    mesh with the game batch sharded over ``data`` (the search shards
    by root placement; the replay's batch-summed grads all-reduce via
    XLA collectives), replicated net/optimizer state, and
    coordinator-only artifact writes (Orbax saves participate on
    every process)."""
    import argparse
    import dataclasses
    import json
    import os
    import sys
    import time

    from rocalphago_tpu.io.checkpoint import (
        MetadataWriter,
        TrainCheckpointer,
    )
    from rocalphago_tpu.io.metrics import MetricsLogger
    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.obs import registry as obs_registry
    from rocalphago_tpu.runtime import faults, retries
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache
    from rocalphago_tpu.runtime.watchdog import Watchdog

    enable_compile_cache()      # before any compile (env-tunable)
    ap = argparse.ArgumentParser(
        description="AlphaZero-style training: device-MCTS self-play "
                    "+ visit-distribution policy targets")
    ap.add_argument("policy_json")
    ap.add_argument("value_json")
    ap.add_argument("out_dir")
    ap.add_argument("--learning-rate", type=float, default=0.001)
    ap.add_argument("--game-batch", type=int, default=8)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--move-limit", type=int, default=500)
    ap.add_argument("--sims", type=int, default=64)
    ap.add_argument("--max-nodes", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--sim-chunk", type=int, default=8)
    ap.add_argument("--replay-chunk", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gumbel", action="store_true",
                    help="Gumbel root search self-play with improved-"
                         "policy (π') targets instead of PUCT + "
                         "visit counts. Plays each ply's halving "
                         "winner (--temperature does not apply); "
                         "NOTE the halving schedule visits every "
                         "candidate at least once per phase, so at "
                         "small --sims the real per-ply simulation "
                         "count is max(sims, schedule total) — "
                         "lower --m-root accordingly")
    ap.add_argument("--m-root", type=int, default=16,
                    help="gumbel root candidate count (top-k of the "
                         "gumbel-perturbed logits)")
    ap.add_argument("--gumbel-sample-moves", action="store_true",
                    help="with --gumbel: SAMPLE each move from the "
                         "improved policy (temperature applies) "
                         "instead of playing the halving winner — "
                         "decouples the pi' target from the play "
                         "distribution (VERDICT r4 #9 experiment)")
    ap.add_argument("--dirichlet-alpha", type=float, default=0.0,
                    help="AlphaZero root-noise Dir(α) for PUCT "
                         "self-play (0 = off; paper: 0.03 on 19x19; "
                         "incompatible with --gumbel)")
    ap.add_argument("--noise-frac", type=float, default=0.25,
                    help="root-noise mix fraction ε")
    ap.add_argument("--cap-p", type=float, default=None,
                    help="playout-cap randomization: probability a "
                         "ply gets the FULL --sims search (cheap cap "
                         "otherwise; only full plies emit policy "
                         "targets). Default $ROCALPHAGO_CAP_P or 0 "
                         "= off")
    ap.add_argument("--cap-cheap", type=int, default=None,
                    help="cheap-search sim cap (default "
                         "$ROCALPHAGO_CAP_CHEAP or --sims // 4)")
    ap.add_argument("--cap-per-row", action="store_true",
                    help="draw the cap per GAME instead of per ply-"
                         "batch (iid rows; masked-slab budgets — see "
                         "docs/PERFORMANCE.md before using: lockstep "
                         "batches only save wall-clock with the "
                         "shared draw)")
    ap.add_argument("--forced-k", type=float, default=0.0,
                    help="forced-playout coefficient k at the PUCT "
                         "root (KataGo sqrt(k*P*n) visit floors; "
                         "recorded policy targets have the forced "
                         "visits pruned back out; 0 = off, "
                         "incompatible with --gumbel)")
    ap.add_argument("--aux-weight", type=float, default=None,
                    help="weight of the auxiliary ownership/score "
                         "losses (value net needs aux_heads; default "
                         "$ROCALPHAGO_AUX_WEIGHT or 0 = off)")
    ap.add_argument("--num-devices", type=int, default=None,
                    help="mesh width (default: every device whose "
                         "count divides --game-batch)")
    ap.add_argument("--komi", type=float, default=None,
                    help="area-scoring komi (default: the board "
                         "size's standard — 7.5 at 13x13+, 7.0 below;"
                         " engine.jaxgo.default_komi)")
    ap.add_argument("--no-gating", action="store_true",
                    help="train WITHOUT the evaluator gate (round-4 "
                         "evidence says this cycles: iter-260 lost "
                         "25-75 raw to iter-80)")
    ap.add_argument("--gate-every", type=int, default=0,
                    help="iterations between candidate-vs-best gate "
                         "matches (0 = --save-every)")
    ap.add_argument("--gate-games", type=int, default=64,
                    help="games per gate match (raw policy, colors "
                         "split)")
    ap.add_argument("--gate-threshold", type=float, default=0.55,
                    help="decided-game win rate the candidate needs "
                         "to be promoted to self-play duty (a Wilson "
                         "95%% lower bound >= 0.5 is additionally "
                         "required — marginal wins don't promote)")
    ap.add_argument("--gate-temperature", type=float, default=1.0,
                    help="sampling temperature for gate/ladder match "
                         "play")
    ap.add_argument("--actor-learner", action="store_true",
                    help="decouple self-play from the update "
                         "(docs/SCALE.md): in-process actor threads "
                         "stream finished games into a bounded "
                         "replay buffer, and the learner consumes "
                         "them at its own cadence. With --actors 1 "
                         "the run is BIT-IDENTICAL to the "
                         "synchronous loop (lockstep pacing); more "
                         "actors free-run against the freshest "
                         "published params")
    ap.add_argument("--actors", type=int, default=1,
                    help="self-play actor threads (--actor-learner)")
    ap.add_argument("--replay-connect", default=None,
                    metavar="HOST:PORT",
                    help="consume games from a networked replay "
                         "service (docs/REPLAYNET.md) instead of "
                         "in-process actors: implies "
                         "--actor-learner with zero local actor "
                         "threads — self-play comes from actor "
                         "PROCESSES (rocalphago_tpu.replaynet"
                         ".actor) shipping to the service")
    ap.add_argument("--replay-capacity", type=int, default=None,
                    help="replay buffer capacity in game batches "
                         "(default $ROCALPHAGO_REPLAY_CAPACITY or 8)")
    ap.add_argument("--replay-sample", action="store_true",
                    help="learner draws prioritized-recency samples "
                         "instead of FIFO batches (breaks the "
                         "bit-exact A/B; actors evict instead of "
                         "pacing)")
    ap.add_argument("--iteration-deadline", type=float, default=0.0,
                    help="watchdog: seconds one iteration may take "
                         "before a 'stall' event is logged and the "
                         "run aborts with the last completed "
                         "checkpoint (0 = off); resume picks up at "
                         "the aborted iteration")
    ap.add_argument("--profile-dir", default=None,
                    help="capture a jax.profiler trace of the run "
                         "into this directory (also via "
                         "ROCALPHAGO_JAX_PROFILE; default off)")
    a = ap.parse_args(argv)
    if a.gumbel and a.dirichlet_alpha > 0:
        raise SystemExit("--dirichlet-alpha is PUCT-mode root noise; "
                         "--gumbel explores via the gumbel draw")
    if a.gumbel_sample_moves and not a.gumbel:
        raise SystemExit("--gumbel-sample-moves requires --gumbel")
    if a.gumbel and a.forced_k:
        raise SystemExit("--forced-k is a PUCT-root knob; gumbel "
                         "search visits candidates by schedule")
    aux_weight = (a.aux_weight if a.aux_weight is not None else
                  float(os.environ.get("ROCALPHAGO_AUX_WEIGHT", "")
                        or 0.0))
    if a.gumbel and a.temperature != 1.0 and not a.gumbel_sample_moves:
        print("zero: --temperature is ignored with --gumbel (the "
              "per-ply gumbel draw is the exploration; with "
              "--gumbel-sample-moves it applies to the pi' draw)",
              file=sys.stderr)

    policy = NeuralNetBase.load_model(a.policy_json)
    value = NeuralNetBase.load_model(a.value_json)
    if policy.board != value.board:
        raise SystemExit(
            f"policy is {policy.board}x{policy.board} but value is "
            f"{value.board}x{value.board} — the nets must share a "
            "board size")
    # ladder-free configuration (docs/PERFORMANCE.md "Ladder-free
    # encode"): the feature list lives in the NET SPECS — the env knob
    # shapes new specs at models/specs.py, not a trained net's input
    # layer. Surface the mismatch loudly instead of silently paying
    # the ladder tax the operator thought they turned off.
    ladder_free = not any(f in pyfeatures.LADDER_FEATURES
                          for f in (policy.feature_list
                                    + value.feature_list))
    if not pyfeatures.ladder_planes_enabled() and not ladder_free:
        print("zero: ROCALPHAGO_LADDER_PLANES=off has no effect on "
              "nets whose saved specs include the ladder planes — "
              "rebuild the specs under the knob "
              "(python -m rocalphago_tpu.models.specs ...) to get "
              "the ladder-free encode", file=sys.stderr)
    # scoring komi: per-board-size default (VERDICT r4 weak #2 — the
    # nets' own GoConfig carries the 19x19 value whatever the board)
    game_cfg = dataclasses.replace(
        policy.cfg, komi=a.komi if a.komi is not None
        else jaxgo.default_komi(policy.board))
    a.komi = game_cfg.komi      # metadata records the resolved value
    # multi-host/multi-chip bring-up, same wiring as the sibling
    # trainers: DCN init (no-op single-process), a (data, model)
    # mesh, the game batch sharded over data, state replicated,
    # artifact writes on the coordinator only
    meshlib.distributed_init()
    requested = a.num_devices or len(jax.devices())
    # the game batch shards over the data axis — use the largest
    # device count that divides it (a 2-game smoke run on an 8-device
    # mesh must not die on divisibility)
    n_dev = requested
    while a.game_batch % n_dev:
        n_dev -= 1
    if n_dev < requested:
        print(f"zero: using {n_dev}/{requested} devices "
              f"(--game-batch {a.game_batch} must divide evenly; "
              "raise it to use the full mesh)", file=sys.stderr)
    mesh = meshlib.make_mesh(n_dev)
    coord = meshlib.is_coordinator()

    value_apply_aux = None
    if aux_weight > 0:
        if not getattr(value.module, "aux_heads", ()):
            raise SystemExit(
                "--aux-weight needs a value net built with "
                "aux_heads=('ownership', 'score') — rebuild the "
                "value spec (models/value.py) or graft heads onto "
                "the checkpoint with models.value.with_aux_heads")
        value_apply_aux = functools.partial(value.module.apply,
                                            with_aux=True)

    tx_p = optax.sgd(a.learning_rate)
    tx_v = optax.sgd(a.learning_rate)
    iteration = make_zero_iteration(
        game_cfg, policy.feature_list, value.feature_list,
        policy.module.apply, value.module.apply, tx_p, tx_v,
        batch=a.game_batch, move_limit=a.move_limit, n_sim=a.sims,
        max_nodes=a.max_nodes or None,   # 0 = auto (CLI convention)
        temperature=a.temperature, sim_chunk=a.sim_chunk,
        replay_chunk=a.replay_chunk, gumbel=a.gumbel,
        m_root=a.m_root, gumbel_sample=a.gumbel_sample_moves,
        dirichlet_alpha=a.dirichlet_alpha,
        noise_frac=a.noise_frac, mesh=mesh,
        cap_p=a.cap_p, cap_cheap=a.cap_cheap,
        cap_per_row=a.cap_per_row, forced_k=a.forced_k,
        aux_weight=aux_weight, value_apply_aux=value_apply_aux)
    state = meshlib.replicate(mesh, init_zero_state(
        policy.params, value.params, tx_p, tx_v, seed=a.seed))

    os.makedirs(a.out_dir, exist_ok=True)
    ckpt = TrainCheckpointer(os.path.join(a.out_dir, "checkpoints"))
    metrics = MetricsLogger(
        os.path.join(a.out_dir, "metrics.jsonl") if coord else None,
        echo=coord)
    # observability: spans/compile events share the metrics stream;
    # opt-in profiler capture (--profile-dir / env) brackets the run
    trace.configure(metrics)
    jaxobs.maybe_start_profiler(a.profile_dir)
    meta = MetadataWriter(
        os.path.join(a.out_dir, "metadata.json"),
        header={"cmd": " ".join(sys.argv), "config": vars(a),
                "ladder_free": ladder_free},
        enabled=coord)
    start = 0
    restored, _ = ckpt.restore(jax.device_get(state))
    if restored is not None:
        # re-replicate over the mesh (rl.py does the same): the
        # restore yields host arrays, but the iteration's sharding
        # contract is replicated state next to data-sharded batches
        state = meshlib.replicate(mesh, ZeroState(*restored))
        start = int(state.iteration)
        metrics.log("resume", iteration=start)
    final = {}

    # evaluator gating (VERDICT r4 missing #5): self-play data comes
    # from the gated BEST pair; the trained candidate must beat it in
    # a raw match to take over self-play duty
    gate = None
    best_p = best_v = None
    gate_every = a.gate_every or a.save_every
    if not a.no_gating:
        gate = ZeroGate(
            game_cfg, policy.feature_list, policy.module.apply,
            os.path.join(a.out_dir, "pool"), games=a.gate_games,
            threshold=a.gate_threshold,
            temperature=a.gate_temperature, move_limit=a.move_limit,
            write=coord)
        # only snapshots at-or-before the restored checkpoint count:
        # a crash between a promotion and its checkpoint save leaves a
        # "future" pool entry, and resuming with it as incumbent would
        # diverge from the uninterrupted run (the re-run iteration
        # re-promotes deterministically, overwriting it with identical
        # bytes)
        snaps = [s for s in gate.snapshots() if s[0] <= start]
        if restored is not None and snaps:
            # a resumed run keeps its incumbent (the candidate in the
            # checkpoint may be mid-losing-streak)
            bp, bv = gate.load(snaps[-1], jax.device_get(
                state.policy_params), jax.device_get(
                state.value_params))
            best_p = meshlib.replicate(mesh, bp)
            best_v = meshlib.replicate(mesh, bv)
            metrics.log("gate_resume", incumbent=snaps[-1][0])
        else:
            best_p, best_v = state.policy_params, state.value_params
            if not snaps:
                gate.promote(best_p, best_v, start)
    gate_root = jax.random.key(a.seed ^ 0x9A7E)

    def export(it):
        if not coord:
            return
        for net, params, name in ((policy, state.policy_params,
                                   "policy"),
                                  (value, state.value_params,
                                   "value")):
            net.params = jax.device_get(params)
            weights = os.path.join(
                a.out_dir, f"{name}.{it:05d}.flax.msgpack")
            net.save_model(
                os.path.join(a.out_dir, f"{name}.json"), weights)

    # transient device/XLA failures re-dispatch the whole iteration:
    # it is functional (state in, new state out), so a retry
    # recomputes the identical result from the same state. The
    # iteration's chunk programs donate their loop-internal carries,
    # but those are rebuilt from `state` — which is never donated —
    # on every invocation, so iteration-level retry stays valid
    # (retries.retry refuses to wrap the donating chunk programs
    # themselves; see runtime/retries.py)
    run_iteration = retries.retry(
        max_attempts=3, base_delay=1.0, logger=metrics.log)(iteration)

    # watchdog: a wedged device program (round-2 tunnel postmortem)
    # must not hang a nohup run forever — log a stall and abort with
    # the last COMPLETED iteration durably checkpointed; resume picks
    # up exactly there
    last_done = {"state": None, "step": -1}

    def _stall_abort():
        st = last_done["state"]
        if st is not None and last_done["step"] != ckpt.latest_step():
            ckpt.save(last_done["step"], st, wait=True)

    watchdog = None
    if a.iteration_deadline > 0:
        watchdog = Watchdog(a.iteration_deadline, metrics=metrics,
                            abort_fn=_stall_abort, name="zero").start()

    # actor/learner composition (docs/SCALE.md): actors walk the SAME
    # rng chain the synchronous loop would (next_keys depends only on
    # the seed rng, never on game content), play against the published
    # best pair, and stream host copies into the buffer; the learner
    # half consumes at its own cadence. Lockstep (1 actor, FIFO) is
    # bit-identical to the synchronous path — the A/B the acceptance
    # test pins.
    rig = None
    sup = None
    publisher = None
    if a.actor_learner or a.replay_connect:
        from rocalphago_tpu.data.replay import ReplayBuffer
        from rocalphago_tpu.runtime import supervisor as superv
        from rocalphago_tpu.training.actor import (
            DispatchGang,
            ParamsPublisher,
            SelfplayActor,
        )
        from rocalphago_tpu.training.learner import ZeroLearner

        lockstep = (a.actors == 1 and not a.replay_sample
                    and not a.replay_connect)
    if a.replay_connect:
        # the wire rig: the learner consumes a remote replay service
        # over RemoteReplayBuffer (FIFO over the wire; reconnect with
        # backoff inside the client); actor processes ship to the
        # service, so there is no in-process publisher — actors pin
        # their own params version
        from rocalphago_tpu.replaynet.client import (
            RemoteReplayBuffer,
            ReplayClient,
        )

        rhost, _, rport = a.replay_connect.rpartition(":")
        buffer = RemoteReplayBuffer(
            ReplayClient(rhost or "127.0.0.1", int(rport)))
        gang = DispatchGang()
        sup = superv.Supervisor(metrics=metrics)
        learner = ZeroLearner(iteration.learn, buffer, gang=gang,
                              sample=a.replay_sample, metrics=metrics)
        sup.install_sigterm()
        sup.start()
        rig = (buffer, publisher, sup, learner)
        metrics.log("actor_learner", actors=0, lockstep=False,
                    remote=a.replay_connect, sample=a.replay_sample,
                    supervised=True)
    elif a.actor_learner:
        buffer = ReplayBuffer(
            capacity=a.replay_capacity,
            spill_dir=(os.path.join(a.out_dir, "replay")
                       if coord else None))
        # spill left by a drained/killed predecessor: the lockstep
        # actor replays its games bit-identically from the
        # checkpointed rng chain, so restoring leftovers would
        # double-insert them — discard; free-run has no replay to
        # lean on, so it restores what survived
        if coord:
            n_spill = (buffer.discard_spill() if lockstep
                       else buffer.restore())
            if n_spill:
                metrics.log("replay_spill_discarded" if lockstep
                            else "replay_restored", entries=n_spill)
        publisher = ParamsPublisher()
        # one gang shared by every device-section owner: concurrent
        # play/learn SPMD programs over the same mesh can deadlock at
        # their collective rendezvous (training.actor.DispatchGang)
        gang = DispatchGang()
        sup = superv.Supervisor(metrics=metrics)
        base_rng = state.rng

        def _actor_factory(i):
            def make(attempt, beat):
                # free-run restarts branch a FRESH key per attempt —
                # the in-flight game is discarded, never replayed;
                # lockstep never reaches attempt > 0 (the handle is
                # restartable=False)
                if lockstep:
                    rng = base_rng
                else:
                    key = jax.random.fold_in(unpack_rng(base_rng),
                                             i + 1)
                    if attempt:
                        key = jax.random.fold_in(key, attempt)
                    rng = pack_rng(key)
                return SelfplayActor(
                    iteration.play, publisher, buffer, rng,
                    name=f"a{i}", lockstep=lockstep,
                    start_index=start,
                    games=((a.iterations - start) if lockstep
                           else None),
                    pace=not a.replay_sample, gang=gang,
                    metrics=metrics, on_progress=beat)
            return make

        for i in range(a.actors):
            sup.add(_actor_factory(i), name=f"actor:{i}",
                    restartable=not lockstep)
        learner = ZeroLearner(iteration.learn, buffer, gang=gang,
                              sample=a.replay_sample, metrics=metrics)
        publisher.publish(
            best_p if best_p is not None else state.policy_params,
            best_v if best_v is not None else state.value_params,
            version=start)
        # SIGTERM (the preemption notice) → graceful drain: exit at
        # the next iteration boundary with a committed checkpoint
        sup.install_sigterm()
        sup.start()
        rig = (buffer, publisher, sup, learner)
        metrics.log("actor_learner", actors=a.actors,
                    lockstep=lockstep, capacity=buffer.capacity,
                    sample=a.replay_sample, supervised=True)

    def _learner_iteration(state, it):
        # finite waits so a dead fleet surfaces as an error instead
        # of an indefinite hang (the watchdog would fire anyway, but
        # with less to say). A learner death FAILS OVER (free-run
        # only): restore the last committed checkpoint and re-step
        # until iteration it+1 is consumed again — the consumed-but-
        # unlearned entry is simply re-learned from older state.
        # Lockstep refuses the ride: its FIFO entries are gone once
        # taken, so a failover could not replay them bit-identically.
        fell_back = False
        while True:
            try:
                out = learner.step(state, timeout=5.0)
            except Exception as e:
                if lockstep:
                    raise
                restored2, _ = ckpt.restore(jax.device_get(state))
                if restored2 is not None:
                    state = meshlib.replicate(mesh,
                                              ZeroState(*restored2))
                step_now = int(state.iteration)
                metrics.log("learner_failover",
                            error=f"{type(e).__name__}: {e}",
                            restored_step=step_now, target=it + 1)
                obs_registry.counter(
                    "supervisor_restarts_total", worker="learner",
                    reason=("transient" if retries.is_transient(e)
                            else "error")).inc()
                fell_back = True
                continue
            if out is None:
                parked = sup.parked()
                if parked:
                    raise RuntimeError(
                        f"self-play worker {parked[0].name} parked; "
                        "learner starved") from parked[0].error
                if buffer.closed:
                    raise RuntimeError("replay buffer closed mid-run")
                continue
            state, m, _ = out
            if not fell_back or int(state.iteration) >= it + 1:
                return state, m

    drained = False
    try:
        for it in range(start, a.iterations):
            if sup is not None and sup.draining:
                # preemption drain: stop at the iteration boundary —
                # everything up to `it` is complete and (below) gets
                # committed, so a resumed run replays from exactly
                # here, byte-identical to never having been drained
                metrics.log("drain", phase="loop_exit", iteration=it,
                            reason=sup.drain_reason)
                drained = True
                break
            with trace.span("zero.iteration", iteration=it):
                faults.barrier("zero.pre_iteration", it)
                t0 = time.time()
                if rig is None:
                    state, m = run_iteration(state, best_p, best_v)
                    # the fetch below syncs the iteration's device
                    # programs, so zero.iteration is real end-to-end
                    # wall time and the replay spans' async remainder
                    # lands inside this span, not outside it
                    m = {k: float(jax.device_get(v))
                         for k, v in m.items()}
                else:
                    # actors produced the games; learn + fetch only
                    # (the fetch inside learner.step is the sync)
                    state, m = _learner_iteration(state, it)
                if watchdog is not None:
                    watchdog.beat()
                    last_done["state"] = jax.device_get(state)
                    last_done["step"] = it + 1
                faults.barrier("zero.post_iteration", it)
                if "aux_loss_ownership" in m:
                    # per-head gauges mirror the metrics stream so
                    # obs_report can trend the aux losses next to the
                    # economics counters
                    obs_registry.gauge(
                        "aux_loss", head="ownership").set(
                            m["aux_loss_ownership"])
                    obs_registry.gauge("aux_loss", head="score").set(
                        m["aux_loss_score"])
                entry = {"iteration": it, **m,
                         "games_per_min": a.game_batch * 60.0
                         / max(time.time() - t0, 1e-9)}
                metrics.log("iteration", **entry)
                meta.record_epoch(entry)
                final = entry
                if gate and ((it + 1) % gate_every == 0
                             or it + 1 == a.iterations):
                    with trace.span("zero.gate", iteration=it):
                        gkey, lkey = jax.random.split(
                            jax.random.fold_in(gate_root, it))
                        r = gate.match(state.policy_params, best_p, gkey)
                        promoted, wilson_lb = gate.decide(r)
                        if promoted:
                            best_p, best_v = (state.policy_params,
                                              state.value_params)
                            gate.promote(best_p, best_v, it + 1)
                        metrics.log("gate", iteration=it,
                                    promoted=promoted,
                                    wilson_lb=round(wilson_lb, 4), **r)
                        # ladder probe: the (possibly new) incumbent vs a
                        # sampled past best — the monotonicity evidence
                        # round 4 lacked
                        snap = gate.sample(a.seed, it)
                        if snap is not None:
                            lp, _ = gate.load(snap, jax.device_get(
                                state.policy_params), jax.device_get(
                                state.value_params))
                            lr = gate.match(
                                best_p, meshlib.replicate(mesh, lp), lkey)
                            metrics.log("ladder", iteration=it,
                                        opponent=snap[0], **lr)
                        faults.barrier("zero.post_gate", it)
                if rig is not None and publisher is not None:
                    # version it+1 = exactly the pair the synchronous
                    # loop would hand iteration it+1 (post-gate best,
                    # or the fresh candidate without gating)
                    publisher.publish(
                        best_p if best_p is not None
                        else state.policy_params,
                        best_v if best_v is not None
                        else state.value_params, version=it + 1)
                if (it + 1) % a.save_every == 0 or it + 1 == a.iterations:
                    # exports BEFORE the checkpoint save: everything
                    # written before the save that commits step it+1 is
                    # reproduced by a resume from the previous
                    # checkpoint, so a crash at any point leaves
                    # artifacts a resume makes identical to the
                    # uninterrupted run (the save is the commit point)
                    with trace.span("zero.export", iteration=it):
                        export(it + 1)
                        faults.barrier("zero.post_export", it)
                    with trace.span("zero.save", iteration=it):
                        faults.barrier("zero.pre_save", it)
                        ckpt.save(it + 1, jax.device_get(state))
                        if faults.active():
                            # barriers are DETERMINISTIC points: under an
                            # active fault plan the async save commits
                            # before post_save, so crash@pre_save/
                            # post_save cleanly separate uncommitted from
                            # committed (a real crash can land anywhere —
                            # the chaos sweep covers that too)
                            ckpt.wait()
                        faults.barrier("zero.post_save", it)
    finally:
        if rig is not None:
            buffer.close()          # unblocks paced/waiting actors
            sup.stop()              # joins monitor, stops workers
            metrics.log(
                "actor_learner_done",
                learner_idle_frac=round(learner.idle_frac, 4),
                learner_steps=learner.steps,
                restarts=sum(h.restarts for h in sup.handles()),
                games_played=sum(
                    h.worker.games_played for h in sup.handles()
                    if h.worker is not None))
    if drained:
        # commit the drain point: the last completed iteration's
        # state, saved through the normal checkpointer (no export —
        # exports happen at save boundaries, which the resumed run
        # reproduces identically). Exit 0 follows: a drain is a
        # success, not a failure.
        step_now = int(state.iteration)
        if step_now != ckpt.latest_step():
            ckpt.save(step_now, jax.device_get(state))
        metrics.log("drain", phase="checkpoint", step=step_now,
                    reason=sup.drain_reason)
    ckpt.wait()
    if watchdog is not None:
        watchdog.stop()
    # the run's counter/histogram state, queryable by obs_report
    obs_registry.log_to(metrics)
    jaxobs.stop_profiler()
    print(json.dumps(final))
    return final


if __name__ == "__main__":
    run_training()
