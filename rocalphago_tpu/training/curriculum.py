"""Progressive-size zero curriculum: one FCN checkpoint, trained
small-to-large.

The FCN heads (``models/value.py`` ``head="fcn"``, ``models/nn_util.
py::PointHead``) make the param pytree board-size-free, so the nets a
9×9 zero run produces APPLY at 13×13 unchanged — this driver turns
that into a training schedule: run the full zero loop
(:func:`rocalphago_tpu.training.zero.run_training` — self-play,
replay, gating, checkpoints, actor/learner, all of it) at each board
size in turn, handing the finished params to the next stage through
:meth:`~rocalphago_tpu.models.nn_util.NeuralNetBase.at_board`.
Optimizer state does NOT carry across stages (each stage's loss
landscape is a different board; a fresh optimizer per stage is the
conservative choice) — only the params do.

Layout: ``out_dir/stageNN_bSS/`` is a complete, self-contained
``training.zero`` out_dir (resumable, gated, exportable); the
curriculum's own stream is ``out_dir/metrics.jsonl`` (``span`` records
for ``curriculum.stage`` plus ``curriculum_stage`` /
``curriculum_transfer`` events) and ``out_dir/curriculum.json`` holds
the final summary. Unrecognized CLI flags forward to EVERY stage's
``run_training`` verbatim (``--sims``, ``--game-batch``,
``--actor-learner``, ``--gate-*`` …).

The payoff question — does the small-board curriculum actually
transfer? — is answered in-run: ``--transfer-games N`` plays the
final stage's policy against a FRESH net of the same architecture at
the final board size, raw-policy stochastic sampling, and gates the
claim on a Wilson 95% lower bound ≥ 0.5 over decided games (the same
statistical honesty as :class:`~rocalphago_tpu.training.zero.
ZeroGate.decide`). docs/MULTISIZE.md records measured results.

Usage::

    python -m rocalphago_tpu.training.curriculum \\
        policy.json value.json out_dir --stages 9:30,13:20,19:10 \\
        --sims 64 --game-batch 8 --transfer-games 64
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys
import time

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.obs import trace


def parse_stages(spec: str) -> list:
    """``"9:30,13:20,19:10"`` → ``[(9, 30), (13, 20), (19, 10)]``
    (board size : zero iterations per stage)."""
    stages = []
    for part in spec.split(","):
        m = re.fullmatch(r"\s*(\d+)\s*:\s*(\d+)\s*", part)
        if not m:
            raise ValueError(
                f"bad stage {part!r} in --stages {spec!r} "
                "(want SIZE:ITERATIONS, e.g. 9:30,13:20)")
        board, iters = int(m.group(1)), int(m.group(2))
        if board < 2 or iters < 1:
            raise ValueError(
                f"bad stage {part!r}: board >= 2, iterations >= 1")
        stages.append((board, iters))
    if not stages:
        raise ValueError("--stages needs at least one SIZE:ITERATIONS")
    return stages


def stage_inputs(policy_json: str, value_json: str, board: int,
                 out_dir: str) -> tuple:
    """Re-board the previous stage's exported nets to ``board`` and
    save them as this stage's input specs. ``at_board`` refuses
    size-locked (dense/bias head) checkpoints with a pointer to
    docs/MULTISIZE.md — a curriculum needs FCN heads end to end."""
    from rocalphago_tpu.models.nn_util import NeuralNetBase

    os.makedirs(out_dir, exist_ok=True)
    out = []
    for path, name in ((policy_json, "policy"), (value_json, "value")):
        net = NeuralNetBase.load_model(path)
        net = net.at_board(board)       # no-op at the native size
        spec = os.path.join(out_dir, f"{name}.json")
        net.save_model(spec,
                       os.path.join(out_dir, f"{name}.flax.msgpack"))
        out.append(spec)
    return tuple(out)


def transfer_match(policy_json: str, board: int, games: int,
                   temperature: float, move_limit: int,
                   seed: int) -> dict:
    """Transferred-vs-fresh at the target size, Wilson-gated: the
    curriculum's final policy (re-boarded to ``board`` if needed)
    against a freshly-initialized net of the SAME architecture, via
    :meth:`ZeroGate.match`'s raw-policy runner. ``transfer`` in the
    returned dict is True only when the transferred net's decided-game
    win rate carries a Wilson 95% lower bound ≥ 0.5 — the curriculum
    must BEAT fresh init with confidence, not merely edge it."""
    import jax

    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.training.zero import ZeroGate

    net = NeuralNetBase.load_model(policy_json).at_board(board)
    fresh = type(net)(net.feature_list, board=board, seed=seed,
                      **net.spec_kwargs)
    cfg = dataclasses.replace(net.cfg,
                              komi=jaxgo.default_komi(board))
    gate = ZeroGate(cfg, net.feature_list, net.module.apply,
                    pool_dir="", games=games, threshold=0.5,
                    temperature=temperature, move_limit=move_limit,
                    write=False)
    result = gate.match(net.params, fresh.params,
                        jax.random.key(seed ^ 0x7A45))
    transfer, lb = gate.decide(result)
    return {"board": board, "games": games,
            "transfer": bool(transfer),
            "wilson_lb": round(float(lb), 4), **result}


def run_curriculum(argv=None) -> dict:
    """CLI driver; returns the summary dict ``curriculum.json``
    records. Stage training flags pass through: anything this parser
    does not own forwards to every stage's ``run_training`` verbatim
    (the per-stage ``--iterations`` and ``--seed`` are appended LAST,
    so the curriculum's values win)."""
    from rocalphago_tpu.io.metrics import MetricsLogger
    from rocalphago_tpu.training.zero import run_training

    ap = argparse.ArgumentParser(
        description="Progressive-size zero curriculum over one FCN "
                    "checkpoint (unknown flags forward to every "
                    "stage's training.zero run)")
    ap.add_argument("policy_json")
    ap.add_argument("value_json")
    ap.add_argument("out_dir")
    ap.add_argument("--stages", required=True,
                    help="comma list of SIZE:ITERATIONS, ascending "
                         "by convention (e.g. 9:30,13:20,19:10)")
    ap.add_argument("--seed", type=int, default=0,
                    help="base rng seed; stage i trains with seed+i "
                         "so stages are decorrelated")
    ap.add_argument("--transfer-games", type=int, default=0,
                    help="after the last stage: play the curriculum "
                         "policy vs a fresh-init net at the final "
                         "board, N games raw-policy, Wilson-gated "
                         "(0 = skip)")
    ap.add_argument("--transfer-temperature", type=float, default=1.0)
    ap.add_argument("--transfer-move-limit", type=int, default=240)
    a, passthrough = ap.parse_known_args(argv)
    stages = parse_stages(a.stages)

    os.makedirs(a.out_dir, exist_ok=True)
    metrics = MetricsLogger(os.path.join(a.out_dir, "metrics.jsonl"))
    metrics.log("curriculum_start",
                stages=[list(s) for s in stages],
                cmd=" ".join(sys.argv))
    trace.configure(metrics)

    prev_policy, prev_value = a.policy_json, a.value_json
    stage_rows = []
    summary: dict = {}
    try:
        for i, (board, iters) in enumerate(stages):
            stage_dir = os.path.join(a.out_dir,
                                     f"stage{i:02d}_b{board}")
            p_in, v_in = stage_inputs(
                prev_policy, prev_value, board,
                os.path.join(stage_dir, "init"))
            t0 = time.time()
            with trace.span("curriculum.stage", stage=i, board=board,
                            iterations=iters):
                final = run_training(
                    [p_in, v_in, stage_dir, *passthrough,
                     "--iterations", str(iters),
                     "--seed", str(a.seed + i)])
                # run_training pointed the global trace sink at ITS
                # stage logger (and closed nothing — the logger stays
                # open for the stage's own spans); reclaim the sink
                # BEFORE the with-block exits so the stage span lands
                # in the curriculum stream, not the stage's
                trace.configure(metrics)
            row = {"stage": i, "board": board, "iterations": iters,
                   "duration_s": round(time.time() - t0, 3),
                   "out_dir": stage_dir, **final}
            metrics.log("curriculum_stage", **row)
            stage_rows.append(row)
            prev_policy = os.path.join(stage_dir, "policy.json")
            prev_value = os.path.join(stage_dir, "value.json")

        summary = {"stages": stage_rows,
                   "final_policy": prev_policy,
                   "final_value": prev_value}
        if a.transfer_games > 0:
            board = stages[-1][0]
            with trace.span("curriculum.transfer", board=board,
                            games=a.transfer_games):
                tr = transfer_match(
                    prev_policy, board, a.transfer_games,
                    a.transfer_temperature, a.transfer_move_limit,
                    a.seed + len(stages))
            metrics.log("curriculum_transfer", **tr)
            summary["transfer"] = tr
        with open(os.path.join(a.out_dir, "curriculum.json"),
                  "w") as f:
            json.dump(summary, f, indent=2)
    finally:
        metrics.close()
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    run_curriculum()
