"""REINFORCE policy training over on-device self-play.

Parity: ``AlphaGo/training/reinforcement_policy_trainer.py::run_training``
(lockstep game batches learner-vs-sampled-past-self, per-game gradient
of the log-likelihood of played moves scaled by the ±1 outcome, an
opponent pool of past checkpoints sampled uniformly, ``--game-batch 20
--policy-temp --move-limit 500 --save-every``, ``metadata.json`` resume;
SURVEY.md §2 "RL policy trainer", §3.2).

TPU-native design — the reference's two host hot loops (Python
``do_move`` and per-state featurization, SURVEY.md §3.2) are gone:

* games are played by :func:`rocalphago_tpu.search.selfplay.play_games`
  — the whole encode → forward → sample → rules-step loop is one
  ``lax.scan`` on device;
* the REINFORCE gradient needs the states the learner saw, which the
  game scan does not materialize (storing ``[T, B, 19, 19, 48]`` planes
  would blow HBM). Instead the iteration *replays* the recorded actions
  through the engine in a second scan, accumulating a per-ply policy
  gradient into a params-shaped carry — constant memory in game length,
  and only the learner's half-batch is re-forwarded per ply;
* no custom sign-flipped SGD (the reference's Keras hack): the ±z
  weight is just a per-sample coefficient on the log-likelihood loss,
  and plain ``optax.sgd`` applies the one accumulated update;
* the games batch axis carries a ``data``-mesh sharding constraint, so
  on a multi-chip mesh XLA shards the whole game scan and all-reduces
  the gradient over ICI.
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import glob
import os
import sys
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.io.checkpoint import (
    MetadataWriter,
    TrainCheckpointer,
    pack_rng,
    unpack_rng,
)
from rocalphago_tpu.io.metrics import MetricsLogger
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.obs import jaxobs, trace
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime.pipeline import ChunkPipeline
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.runtime import faults, retries
from rocalphago_tpu.search.selfplay import (
    make_selfplay_chunked,
    play_games,
    sensible_mask,
)
from rocalphago_tpu.features.planes import batched_encoder


@dataclasses.dataclass
class RLConfig:
    """Flat, JSON-serializable stage config (SURVEY.md §5 "Config")."""

    model_json: str = ""
    out_dir: str = ""
    learning_rate: float = 0.001
    game_batch: int = 20          # reference default; TPU runs use 128+
    iterations: int = 100
    save_every: int = 10
    policy_temp: float = 0.67
    move_limit: int = 500
    seed: int = 0
    num_devices: int | None = None
    chunk: int = 0    # >0: plies per compiled segment (watchdog-safe
    #                   chunked iteration; 0 = one monolithic program)
    komi: float | None = None   # None = board size's standard
    #                   (engine.jaxgo.default_komi; VERDICT r4 weak 2)


class RLState(NamedTuple):
    params: dict
    opt_state: tuple
    iteration: jax.Array  # int32 []
    rng: jax.Array        # uint32 key data


def _make_replay_ply(cfg: jaxgo.GoConfig, features: tuple, apply_fn,
                     batch: int, temperature: float):
    """Shared REINFORCE replay body: one ply of re-stepping the
    recorded game while accumulating the z-weighted policy gradient
    into a params-shaped carry. Used by both the monolithic iteration
    (one scan) and the chunked iteration (host-driven segments)."""
    n = cfg.num_points
    half = batch // 2
    enc = batched_encoder(cfg, features)
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(jaxgo.step, cfg))

    def ply(params, z, carry, xs):
        states, grads = carry
        t, actions_t, live_t = xs
        # the learner moves games [0:half] on even plies and games
        # [half:batch] on odd plies (selfplay color split)
        start = jnp.where((t % 2) == 0, 0, half)
        take = lambda a: lax.dynamic_slice_in_dim(a, start, half)  # noqa: E731
        half_states = jax.tree.map(take, states)
        planes = enc(half_states)
        sens = vsens(half_states)
        acts = take(actions_t)
        w = (take(z) * take(live_t)
             * (acts < n).astype(jnp.float32))

        def loss_fn(p):
            logits = apply_fn(p, planes)
            neg = jnp.finfo(logits.dtype).min
            masked = jnp.where(sens, logits / temperature, neg)
            logp = jax.nn.log_softmax(masked, axis=-1)
            lp = jnp.take_along_axis(
                logp, jnp.minimum(acts, n - 1)[:, None], axis=1)[:, 0]
            return -(w * lp).sum() / batch

        grads = jax.tree.map(jnp.add, grads, jax.grad(loss_fn)(params))
        return (vstep(states, actions_t), grads)

    return ply


def _learner_z(winners: jax.Array, half: int) -> jax.Array:
    """Outcome from the LEARNER's perspective: the learner (net A) is
    Black in games [0:half], White in the rest."""
    w = winners.astype(jnp.float32)
    return jnp.concatenate([w[:half], -w[half:]])


def _update_and_metrics(tx, state: RLState, grads, z, num_moves, key):
    """Shared SGD apply + metrics assembly for both iterations."""
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    # win rate over DECIDED games (draws excluded, reported
    # separately) — counting draws as losses biases the learner
    # win-rate low on integer-komi configs
    wins = (z > 0).sum()
    decided = (z != 0).sum()
    metrics = {
        "win_rate": jnp.where(decided > 0,
                              wins / jnp.maximum(decided, 1), 0.5),
        "draw_rate": (z == 0).mean(),
        "mean_moves": num_moves.astype(jnp.float32).mean(),
    }
    new = RLState(params, opt_state, state.iteration + 1,
                  pack_rng(key))
    return new, metrics


def make_rl_iteration(cfg: jaxgo.GoConfig, features: tuple, apply_fn,
                      tx, batch: int, move_limit: int,
                      temperature: float, mesh=None):
    """Pure ``(RLState, opp_params) -> (RLState, metrics)`` — one full
    REINFORCE iteration: play a game batch, accumulate the z-weighted
    policy gradient by replay, apply one SGD update."""
    if batch % 2:
        raise ValueError(f"game_batch must be even, got {batch}")
    half = batch // 2
    replay_ply = _make_replay_ply(cfg, features, apply_fn, batch,
                                  temperature)

    def iteration(state: RLState, opp_params):
        key = unpack_rng(state.rng)
        key, game_key = jax.random.split(key)
        params = state.params

        result = play_games(cfg, features, apply_fn, params, apply_fn,
                            opp_params, game_key, batch, move_limit,
                            temperature)
        z = _learner_z(result.winners, half)

        states0 = jaxgo.new_states(cfg, batch)
        if mesh is not None:
            states0 = lax.with_sharding_constraint(
                states0, meshlib.data_sharding(mesh))
        zero = jax.tree.map(jnp.zeros_like, params)
        (_, grads), _ = lax.scan(
            lambda c, xs: (replay_ply(params, z, c, xs), None),
            (states0, zero),
            (jnp.arange(result.actions.shape[0]), result.actions,
             result.live.astype(jnp.float32)))

        return _update_and_metrics(tx, state, grads, z,
                                   result.num_moves, key)

    return iteration


def make_rl_iteration_chunked(cfg: jaxgo.GoConfig, features: tuple,
                              apply_fn, tx, batch: int, move_limit: int,
                              temperature: float, chunk: int,
                              mesh=None):
    """Chunked ``(RLState, opp_params) -> (RLState, metrics)`` — the
    same REINFORCE iteration as :func:`make_rl_iteration`, but no
    single device program runs longer than one ``chunk``-ply segment.

    Why: the attached TPU tunnel's worker kills device programs past
    ~40s of execution, and the monolithic iteration (a full
    ``move_limit``-ply game scan PLUS an equally long replay scan with
    backward passes, in ONE program) is far past that for real
    configs — it was the one component benchmark that crashed the
    worker in round 2 (BENCH_RESULTS.md "worker-crash status"). Here
    the game phase reuses :func:`make_selfplay_chunked` (host-driven
    segments, device-resident states) and the replay+gradient phase is
    its own segmented scan with the (states, grads) carry device-
    resident between segments. The math is IDENTICAL to the monolithic
    iteration — same per-ply op order, same gradient accumulation
    order, same rng split chain — verified bit-identical in
    ``tests/test_rl_trainer.py``.
    """
    if batch % 2:
        raise ValueError(f"game_batch must be even, got {batch}")
    half = batch // 2
    runner = make_selfplay_chunked(
        cfg, features, apply_fn, apply_fn, batch, move_limit,
        chunk=chunk, temperature=temperature, mesh=mesh)
    replay_ply = _make_replay_ply(cfg, features, apply_fn, batch,
                                  temperature)

    @jaxobs.track("rl.replay_segment")
    @functools.partial(jax.jit, static_argnames=("length",),
                       donate_argnums=(2, 3))
    def replay_segment(params, z, states, grads, actions, live,
                       offset, length):
        # states + grad accumulator are DONATED: both are
        # loop-internal (built fresh each iteration, so the
        # iteration-level retry wrapper stays valid) and donation
        # keeps pipelined dispatch from doubling the params-shaped
        # accumulator
        (states, grads), _ = lax.scan(
            lambda c, xs: (replay_ply(params, z, c, xs), None),
            (states, grads),
            (offset + jnp.arange(length), actions, live))
        return states, grads

    replay_segment.donates_buffers = True

    update = jax.jit(functools.partial(_update_and_metrics, tx))

    def iteration(state: RLState, opp_params):
        key = unpack_rng(state.rng)
        key, game_key = jax.random.split(key)
        params = state.params

        # phase spans (see training.zero.iteration for the async-
        # dispatch caveat: the caller's metrics fetch is the sync)
        with trace.span("rl.play"):
            result = runner(params, opp_params, game_key)
        z = _learner_z(result.winners, half)

        states = jaxgo.new_states(cfg, batch)
        if mesh is not None:
            states = meshlib.shard_batch(mesh, states)
        grads = jax.tree.map(jnp.zeros_like, params)
        live = result.live.astype(jnp.float32)
        plies = result.actions.shape[0]
        # pipelined dispatch (runtime.pipeline): paces the host to
        # `depth` in-flight segments and records gap/occupancy
        pipe = ChunkPipeline(runner="rl.replay")
        with trace.span("rl.replay", plies=plies):
            for offset in range(0, plies, chunk):
                length = min(chunk, plies - offset)
                states, grads = replay_segment(
                    params, z, states, grads,
                    result.actions[offset:offset + length],
                    live[offset:offset + length],
                    jnp.int32(offset), length)
                # fresh scalar handle — the next segment donates
                # `states`, so no leaf of it may be the handle
                pipe.push(states.turn.sum())
            pipe.finish()

        with trace.span("rl.update"):
            return update(state, grads, z, result.num_moves, key)

    return iteration


class OpponentPool:
    """Directory of past learner snapshots, sampled uniformly each
    iteration (reference opponent-pool semantics)."""

    def __init__(self, directory: str, net: NeuralNetBase,
                 write: bool = True):
        self.directory = directory
        self.net = net
        self.write = write
        os.makedirs(directory, exist_ok=True)
        if write and not self.snapshots():
            self.add(net.params, 0)

    def snapshots(self) -> list:
        return sorted(glob.glob(
            os.path.join(self.directory, "opponent.*.flax.msgpack")))

    def add(self, params, iteration: int) -> None:
        if not self.write:
            return
        self.net.params = jax.device_get(params)
        self.net.save_weights(os.path.join(
            self.directory, f"opponent.{iteration:05d}.flax.msgpack"))

    def sample(self, seed, iteration: int,
               save_every: int | None = None):
        """Uniform draw over the current pool, seeded by (seed,
        iteration) — stateless, so an interrupted-and-resumed run makes
        the same choices as an uninterrupted one with no RNG replay.
        ``self.net.params`` is used only as a read-only deserialization
        template (never mutated — no scratch-slot reentrancy hazard).

        With ``save_every`` the candidate set is RECONSTRUCTED from the
        save schedule (snapshots land at iterations 0, save_every,
        2·save_every, …) instead of listing the directory — every host
        of a multi-host run computes the identical choice even when
        shared-filesystem listings lag the coordinator's writes; the
        read then waits briefly for the chosen file to become visible.
        Without it (single-process default) the directory listing is
        the candidate set."""
        from flax import serialization

        rng = np.random.default_rng(
            np.random.SeedSequence([seed, iteration]))
        if save_every:
            iters = [0] + [k * save_every for k in
                           range(1, iteration // save_every + 1)]
            pick = iters[rng.integers(len(iters))]
            path = os.path.join(
                self.directory, f"opponent.{pick:05d}.flax.msgpack")
            deadline = time.time() + (30.0 if jax.process_count() > 1
                                      else 0.0)
            while not os.path.exists(path):
                if time.time() >= deadline:
                    raise FileNotFoundError(
                        f"opponent snapshot {path} not visible. "
                        "Multi-host: the coordinator writes snapshots; "
                        "a shared filesystem is required. Resumed run: "
                        "--save-every must match the value the out_dir "
                        "was populated with (the candidate set is "
                        "reconstructed from the save schedule, not the "
                        "directory listing, so every host agrees)")
                time.sleep(0.5)
        else:
            paths = self.snapshots()
            if not paths:
                raise FileNotFoundError(
                    f"no opponent snapshots in {self.directory}")
            path = paths[rng.integers(len(paths))]
        with open(path, "rb") as f:
            params = serialization.from_bytes(self.net.params, f.read())
        return params, os.path.basename(path)


class RLTrainer:
    """Wires learner + opponent pool + mesh into the iteration loop."""

    def __init__(self, cfg: RLConfig, net: NeuralNetBase | None = None):
        self.cfg = cfg
        self.net = net or NeuralNetBase.load_model(cfg.model_json)
        self.mesh = meshlib.make_mesh(cfg.num_devices)
        os.makedirs(cfg.out_dir, exist_ok=True)

        tx = optax.sgd(cfg.learning_rate)
        rep = meshlib.replicated(self.mesh)
        # scoring komi: per-board-size default unless overridden (the
        # net spec's GoConfig always carries the 19x19 value)
        game_cfg = dataclasses.replace(
            self.net.cfg, komi=cfg.komi if cfg.komi is not None
            else jaxgo.default_komi(self.net.cfg.size))
        cfg.komi = game_cfg.komi    # metadata records the resolved value
        if cfg.chunk:
            # host-driven segmented iteration (not itself jittable —
            # its internal segment programs are the jit units)
            self._iteration = make_rl_iteration_chunked(
                game_cfg, self.net.feature_list,
                self.net.module.apply, tx, cfg.game_batch,
                cfg.move_limit, cfg.policy_temp, chunk=cfg.chunk,
                mesh=self.mesh)
        else:
            iteration = make_rl_iteration(
                game_cfg, self.net.feature_list,
                self.net.module.apply, tx, cfg.game_batch,
                cfg.move_limit, cfg.policy_temp, mesh=self.mesh)
            self._iteration = jax.jit(iteration, donate_argnums=(0,),
                                      out_shardings=(rep, rep))

        self.state = meshlib.replicate(self.mesh, RLState(
            params=self.net.params,
            opt_state=tx.init(self.net.params),
            iteration=jnp.int32(0),
            rng=pack_rng(jax.random.key(cfg.seed))))
        # multi-host: artifact files are coordinator-only; Orbax saves
        # stay all-process (SURVEY.md §2b "Multi-host")
        self.coord = meshlib.is_coordinator()
        self.pool = OpponentPool(
            os.path.join(cfg.out_dir, "opponents"), self.net,
            write=self.coord)
        self.ckpt = TrainCheckpointer(
            os.path.join(cfg.out_dir, "checkpoints"))
        self.metrics = MetricsLogger(
            os.path.join(cfg.out_dir, "metrics.jsonl")
            if self.coord else None, echo=self.coord)
        # spans/compile events share the metrics stream (obs.trace)
        trace.configure(self.metrics)
        self.start_iteration = 0
        self._maybe_resume()

    def _maybe_resume(self):
        restored, _ = self.ckpt.restore(jax.device_get(self.state))
        if restored is None:
            return
        self.state = meshlib.replicate(self.mesh, RLState(*restored))
        self.start_iteration = int(restored.iteration)
        self.metrics.log("resume", iteration=self.start_iteration)

    def run(self) -> dict:
        cfg = self.cfg
        meta = MetadataWriter(
            os.path.join(cfg.out_dir, "metadata.json"),
            header={"cmd": " ".join(sys.argv),
                    "config": dataclasses.asdict(cfg)},
            enabled=self.coord)
        final = {}
        # transient-failure re-dispatch: safe for the chunked
        # (host-driven) iteration — its chunk programs donate only
        # loop-internal carries, rebuilt from the never-donated
        # `state` each invocation, so it recomputes the identical
        # result from the unchanged state (retries.retry refuses the
        # donating chunk programs themselves). The monolithic jit
        # DONATES the state buffers, so after a failed dispatch the
        # input may already be invalid: no retry there.
        step = self._iteration
        if cfg.chunk:
            step = retries.retry(max_attempts=3, base_delay=1.0,
                                 logger=self.metrics.log)(step)
        jaxobs.maybe_start_profiler()      # env-gated capture
        for it in range(self.start_iteration, cfg.iterations):
          with trace.span("rl.iteration", iteration=it):
            faults.barrier("rl.pre_iteration", it)
            with trace.span("rl.data"):    # opponent-pool draw (I/O)
                opp_params, opp_name = self.pool.sample(
                    cfg.seed, it, save_every=cfg.save_every)
                opp_params = meshlib.replicate(self.mesh, opp_params)
            t0 = time.time()
            self.state, m = step(self.state, opp_params)
            # the win-rate fetch syncs the iteration's programs, so
            # rl.iteration is real end-to-end wall time
            win = float(m["win_rate"])
            faults.barrier("rl.post_iteration", it)
            entry = {
                "iteration": it, "opponent": opp_name,
                "win_rate": win,
                "mean_moves": float(m["mean_moves"]),
                "games_per_min": cfg.game_batch * 60.0
                / max(time.time() - t0, 1e-9),
            }
            self.metrics.log("iteration", **entry)
            meta.record_epoch(entry)
            final = entry
            if (it + 1) % cfg.save_every == 0 or it + 1 == cfg.iterations:
              with trace.span("rl.save"):
                # pool snapshot and exports BEFORE the checkpoint
                # save (the commit point): a crash anywhere in here is
                # healed by resume re-running the iteration and
                # rewriting identical artifacts atomically
                self.pool.add(self.state.params, it + 1)
                self._export_weights(it + 1)
                faults.barrier("rl.pre_save", it)
                self.ckpt.save(it + 1, jax.device_get(self.state))
                if faults.active():
                    # deterministic barrier: commit the async save
                    # before post_save (see training.zero)
                    self.ckpt.wait()
                faults.barrier("rl.post_save", it)
        self.ckpt.wait()
        # the run's counter/histogram state, queryable by obs_report
        obs_registry.log_to(self.metrics)
        jaxobs.stop_profiler()
        return final

    def _export_weights(self, iteration: int) -> None:
        if not self.coord:
            return
        self.net.params = jax.device_get(self.state.params)
        weights = os.path.join(
            self.cfg.out_dir, f"weights.{iteration:05d}.flax.msgpack")
        # model.json always points at the latest weights (GTP-loadable)
        self.net.save_model(
            os.path.join(self.cfg.out_dir, "model.json"), weights)


def run_training(argv=None) -> dict:
    """CLI parity with the reference RL trainer."""
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    enable_compile_cache()      # before any compile (env-tunable)
    # multi-host bring-up (DCN); no-op for single-process runs
    meshlib.distributed_init()
    ap = argparse.ArgumentParser(
        description="REINFORCE policy training via self-play")
    ap.add_argument("model_json")
    ap.add_argument("out_dir")
    ap.add_argument("--learning-rate", type=float, default=0.001)
    ap.add_argument("--game-batch", type=int, default=20)
    ap.add_argument("--iterations", type=int, default=100)
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--policy-temp", type=float, default=0.67)
    ap.add_argument("--move-limit", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-devices", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=0,
                    help="plies per compiled segment (0 = monolithic; "
                         "use e.g. 10-60 on backends that kill long "
                         "device programs)")
    ap.add_argument("--komi", type=float, default=None,
                    help="area-scoring komi (default: the board "
                         "size's standard; engine.jaxgo.default_komi)")
    a = ap.parse_args(argv)
    cfg = RLConfig(
        model_json=a.model_json, out_dir=a.out_dir,
        learning_rate=a.learning_rate, game_batch=a.game_batch,
        iterations=a.iterations, save_every=a.save_every,
        policy_temp=a.policy_temp, move_limit=a.move_limit,
        seed=a.seed, num_devices=a.num_devices, chunk=a.chunk,
        komi=a.komi)
    return RLTrainer(cfg).run()


if __name__ == "__main__":
    run_training(sys.argv[1:])
