"""Value-network regression training, data-parallel over the mesh.

Parity: ``AlphaGo/training/reinforcement_value_trainer.py::run_training``
(MSE loss + SGD over (state, outcome z) pairs, CLI mirroring the SL
trainer, per-epoch checkpoints + ``metadata.json`` + persisted split;
SURVEY.md §2 "Value trainer"). The corpus comes from
:mod:`rocalphago_tpu.training.selfplay_data` — the de-correlated
one-position-per-game generator the reference lacks.

Same TPU shape as the SL trainer: one jitted sharded train step (batch
over the ``data`` mesh axis, XLA all-reduces gradients over ICI),
on-device dihedral augmentation (planes only — the scalar target is
rotation-invariant), Orbax checkpoints, prefetched input pipeline.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocalphago_tpu.data.pipeline import (
    ShardedDataset,
    batch_iterator,
    device_prefetch,
    split_indices,
)
from rocalphago_tpu.io.checkpoint import (
    MetadataWriter,
    TrainCheckpointer,
    pack_rng,
    unpack_rng,
)
from rocalphago_tpu.io.metrics import MetricsLogger
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.obs import jaxobs, trace
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.training.sl import pad_batch
from rocalphago_tpu.training.symmetries import transform_planes


@dataclasses.dataclass
class ValueConfig:
    """Flat, JSON-serializable stage config (SURVEY.md §5 "Config")."""

    model_json: str = ""
    train_data: str = ""          # shard prefix (npz pipeline)
    out_dir: str = ""
    minibatch: int = 32
    epochs: int = 10
    learning_rate: float = 0.003
    decay: float = 0.0
    momentum: float = 0.0
    train_val_test: tuple = (0.93, 0.05, 0.02)
    symmetries: bool = True
    seed: int = 0
    num_devices: int | None = None
    max_validation_batches: int = 200
    epoch_length: int | None = None
    save_every: int | None = None     # also checkpoint every N steps


class ValueState(NamedTuple):
    params: dict
    opt_state: tuple
    step: jax.Array
    rng: jax.Array


def value_loss_fn(apply_fn, params, planes, outcomes, weights=None):
    pred = apply_fn(params, planes)
    z = outcomes.astype(jnp.float32)
    sq = (pred - z) ** 2
    if weights is None:
        return jnp.mean(sq)
    return (sq * weights).sum() / jnp.maximum(weights.sum(), 1.0)


def make_train_step(apply_fn, tx, symmetries: bool):
    def train_step(state: ValueState, planes, outcomes):
        key = unpack_rng(state.rng)
        key, sub = jax.random.split(key)
        planes = planes.astype(jnp.float32)
        if symmetries:
            t = jax.random.randint(sub, (planes.shape[0],), 0, 8)
            planes = jax.vmap(transform_planes)(planes, t)
        loss, grads = jax.value_and_grad(
            lambda p: value_loss_fn(apply_fn, p, planes, outcomes))(
                state.params)
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        new = ValueState(params, opt_state, state.step + 1,
                         pack_rng(key))
        return new, {"mse": loss}

    return train_step


def make_eval_step(apply_fn):
    def eval_step(params, planes, outcomes, weights):
        return {"mse": value_loss_fn(apply_fn, params,
                                     planes.astype(jnp.float32),
                                     outcomes, weights),
                "count": weights.sum()}
    return eval_step


class ValueTrainer:
    """Wires value net + data + mesh + checkpointing together."""

    def __init__(self, cfg: ValueConfig, net: NeuralNetBase | None = None):
        self.cfg = cfg
        self.net = net or NeuralNetBase.load_model(cfg.model_json)
        self.mesh = meshlib.make_mesh(cfg.num_devices)
        self.dataset = ShardedDataset(cfg.train_data)
        if self.dataset.planes != self.net.preprocess.output_dim:
            raise ValueError(
                f"dataset has {self.dataset.planes} planes but the "
                f"model needs {self.net.preprocess.output_dim}")
        if self.dataset.manifest.get("targets") != "outcome":
            raise ValueError(
                "value training needs an outcome-labelled corpus "
                "(generate one with training.selfplay_data)")
        os.makedirs(cfg.out_dir, exist_ok=True)

        dwidth = self.mesh.shape[meshlib.DATA_AXIS]
        if cfg.minibatch % dwidth:
            raise ValueError(
                f"minibatch {cfg.minibatch} not divisible by "
                f"data-parallel width {dwidth}")

        if cfg.decay:
            sched = lambda s: cfg.learning_rate / (1.0 + cfg.decay * s)  # noqa: E731
        else:
            sched = cfg.learning_rate
        tx = optax.sgd(sched, momentum=cfg.momentum or None)
        opt_state0 = tx.init(self.net.params)
        batch_sh = meshlib.data_sharding(self.mesh, rank=4)
        z_sh = meshlib.data_sharding(self.mesh, rank=1)
        rep = meshlib.replicated(self.mesh)
        state_sh = ValueState(
            params=jax.tree.map(lambda _: rep, self.net.params),
            opt_state=jax.tree.map(lambda _: rep, opt_state0),
            step=rep, rng=rep)
        # compile-tracked (obs.jaxobs): recompiles surface as named
        # `compile` events (see training.sl)
        self._train_step = jaxobs.track("value.train_step", jax.jit(
            make_train_step(self.net.module.apply, tx, cfg.symmetries),
            in_shardings=(state_sh, batch_sh, z_sh),
            out_shardings=(state_sh, rep),
            donate_argnums=(0,)))
        self._eval_step = jaxobs.track("value.eval_step", jax.jit(
            make_eval_step(self.net.module.apply),
            in_shardings=(state_sh.params, batch_sh, z_sh, z_sh),
            out_shardings=rep))

        # multi-host: artifact files are coordinator-only; Orbax saves
        # stay all-process (SURVEY.md §2b "Multi-host")
        self.coord = meshlib.is_coordinator()
        self.ckpt = TrainCheckpointer(
            os.path.join(cfg.out_dir, "checkpoints"))
        self.metrics = MetricsLogger(
            os.path.join(cfg.out_dir, "metrics.jsonl")
            if self.coord else None, echo=self.coord)
        # spans/compile events share the metrics stream (obs.trace)
        trace.configure(self.metrics)
        self.state = meshlib.replicate(self.mesh, ValueState(
            params=self.net.params,
            opt_state=opt_state0,
            step=jnp.int32(0),
            rng=pack_rng(jax.random.key(cfg.seed))))
        self.train_idx, self.val_idx, self.test_idx = split_indices(
            len(self.dataset), cfg.train_val_test, seed=cfg.seed,
            path=os.path.join(cfg.out_dir, "shuffle.npz"),
            write=self.coord)
        self.start_epoch = 0
        self._resume_skip = 0
        self._maybe_resume()

    def _maybe_resume(self):
        restored, _ = self.ckpt.restore(jax.device_get(self.state))
        if restored is None:
            return
        self.state = meshlib.replicate(self.mesh, ValueState(*restored))
        # derived data cursor: batch order is a pure function of
        # (seed, epoch), so step % steps_per_epoch = consumed batches
        # (same scheme as SLTrainer._maybe_resume)
        self.start_epoch, self._resume_skip = divmod(
            int(restored.step), max(self._steps_per_epoch(), 1))
        self.metrics.log("resume", step=int(restored.step),
                         epoch=self.start_epoch, skip=self._resume_skip)

    def _steps_per_epoch(self) -> int:
        if self.cfg.epoch_length:
            return self.cfg.epoch_length
        return max(len(self.train_idx) // self.cfg.minibatch, 1)

    def run(self) -> dict:
        cfg = self.cfg
        meta = MetadataWriter(
            os.path.join(cfg.out_dir, "metadata.json"),
            header={"cmd": " ".join(sys.argv),
                    "config": dataclasses.asdict(cfg),
                    "dataset_positions": len(self.dataset)},
            enabled=self.coord)
        steps_per_epoch = self._steps_per_epoch()
        jaxobs.maybe_start_profiler()      # env-gated capture
        # host wait per prefetched batch (see training.sl)
        data_wait = obs_registry.histogram(
            "train_data_wait_seconds", trainer="value")
        final = {}
        for epoch in range(self.start_epoch, cfg.epochs):
          with trace.span("value.epoch", epoch=epoch):
            faults.barrier("value.pre_epoch", epoch)
            skip = self._resume_skip if epoch == self.start_epoch else 0
            host_rng = np.random.default_rng(
                np.random.SeedSequence([cfg.seed, epoch]))
            it = batch_iterator(self.dataset, self.train_idx,
                                cfg.minibatch, host_rng, epochs=1,
                                skip=skip)
            it = (meshlib.shard_batch(self.mesh, b) for b in it)
            t0 = time.time()
            losses = []
            with trace.span("value.train"):
              for i, (planes, z) in enumerate(obs_registry.timed(
                      device_prefetch(it, size=2), data_wait)):
                if i >= steps_per_epoch - skip:
                    break
                self.state, m = self._train_step(self.state, planes, z)
                losses.append(m["mse"])
                if cfg.save_every:
                    gstep = epoch * steps_per_epoch + skip + len(losses)
                    if gstep % cfg.save_every == 0:
                        self.ckpt.save(gstep, jax.device_get(self.state))
                        faults.barrier("value.step_save", gstep)
            if not losses:
                raise ValueError(
                    f"train split ({len(self.train_idx)} positions) "
                    f"yields no full minibatch of {cfg.minibatch}; "
                    "generate more data or shrink the minibatch")
            train_mse = float(jnp.mean(jnp.stack(losses)))
            dt = time.time() - t0
            with trace.span("value.eval"):
                val = self.evaluate(self.val_idx)
            step = int(jax.device_get(self.state.step))
            entry = {
                "epoch": epoch, "step": step,
                "train_mse": train_mse, "val_mse": val["mse"],
                "positions_per_s":
                    len(losses) * cfg.minibatch / max(dt, 1e-9),
            }
            self.metrics.log("epoch", **entry)
            meta.record_epoch(entry)
            # exports before the checkpoint save (commit point) — same
            # crash-safe ordering as SLTrainer.run
            with trace.span("value.export"):
                self._export_weights(epoch)
            with trace.span("value.save"):
                faults.barrier("value.pre_save", epoch)
                self.ckpt.save(step, jax.device_get(self.state))
                if faults.active():
                    # deterministic barrier: commit the async save
                    # before post_save (see training.zero)
                    self.ckpt.wait()
                faults.barrier("value.post_save", epoch)
            final = entry
        # held-out test-split MSE (AlphaGo paper reports train+test MSE)
        if len(self.test_idx):
            test = self.evaluate(self.test_idx)
            final = dict(final, test_mse=test["mse"])
            meta.update(test_mse=test["mse"])
            self.metrics.log("test", **test)
        self.ckpt.wait()
        # the run's counter/histogram state, queryable by obs_report
        obs_registry.log_to(self.metrics)
        jaxobs.stop_profiler()
        return final

    def evaluate(self, indices, max_batches: int | None = None) -> dict:
        cfg = self.cfg
        max_batches = max_batches or cfg.max_validation_batches
        rng = np.random.default_rng(0)
        mse_sum = count = 0.0
        it = batch_iterator(self.dataset, indices, cfg.minibatch, rng,
                            epochs=1, drop_remainder=False)
        for i, (planes, z) in enumerate(it):
            if i >= max_batches:
                break
            planes, z, weights = pad_batch(planes, z, cfg.minibatch)
            planes, z, weights = meshlib.shard_batch(
                self.mesh, (planes, z, weights))
            m = self._eval_step(self.state.params, planes, z, weights)
            c = float(m["count"])
            mse_sum += float(m["mse"]) * c
            count += c
        if not count:
            return {"mse": float("nan")}
        return {"mse": mse_sum / count}

    def _export_weights(self, epoch: int) -> None:
        if not self.coord:
            return
        self.net.params = jax.device_get(self.state.params)
        weights = os.path.join(
            self.cfg.out_dir, f"weights.{epoch:05d}.flax.msgpack")
        # model.json always points at the latest weights (GTP-loadable)
        self.net.save_model(
            os.path.join(self.cfg.out_dir, "model.json"), weights)


def run_training(argv=None) -> dict:
    """CLI parity with the reference value trainer."""
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    enable_compile_cache()      # before any compile (env-tunable)
    # multi-host bring-up (DCN); no-op for single-process runs
    meshlib.distributed_init()
    ap = argparse.ArgumentParser(
        description="Value network regression on self-play outcomes")
    ap.add_argument("model_json")
    ap.add_argument("train_data", help="npz shard prefix "
                                       "(training.selfplay_data output)")
    ap.add_argument("out_dir")
    ap.add_argument("--minibatch", "-B", type=int, default=32)
    ap.add_argument("--epochs", "-E", type=int, default=10)
    ap.add_argument("--learning-rate", "-l", type=float, default=0.003)
    ap.add_argument("--decay", "-d", type=float, default=0.0)
    ap.add_argument("--momentum", type=float, default=0.0)
    ap.add_argument("--train-val-test", nargs=3, type=float,
                    default=[0.93, 0.05, 0.02])
    ap.add_argument("--no-symmetries", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-devices", type=int, default=None)
    ap.add_argument("--epoch-length", type=int, default=None)
    ap.add_argument("--save-every", type=int, default=None,
                    help="extra checkpoint every N steps (mid-epoch "
                         "preemption recovery)")
    a = ap.parse_args(argv)
    cfg = ValueConfig(
        model_json=a.model_json, train_data=a.train_data,
        out_dir=a.out_dir, minibatch=a.minibatch, epochs=a.epochs,
        learning_rate=a.learning_rate, decay=a.decay,
        momentum=a.momentum, train_val_test=tuple(a.train_val_test),
        symmetries=not a.no_symmetries, seed=a.seed,
        num_devices=a.num_devices, epoch_length=a.epoch_length,
        save_every=a.save_every)
    return ValueTrainer(cfg).run()


if __name__ == "__main__":
    run_training(sys.argv[1:])
