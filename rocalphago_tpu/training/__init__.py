"""Trainers: supervised policy, REINFORCE self-play policy, value
regression, and the self-play value-dataset generator the reference
lacks (SURVEY.md §1 L4, §2 "Value trainer" gap)."""
