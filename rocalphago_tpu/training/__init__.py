"""Trainers: supervised policy, REINFORCE self-play policy, value
regression, and the self-play value-dataset generator the reference
lacks (SURVEY.md §1 L4, §2 "Value trainer" gap).

Re-exports are lazy — see :mod:`rocalphago_tpu.utils.lazy`.
"""

from rocalphago_tpu.utils.lazy import make_lazy

_EXPORTS = {
    "RLConfig": "rocalphago_tpu.training.rl",
    "RLTrainer": "rocalphago_tpu.training.rl",
    "ValueDataGenerator": "rocalphago_tpu.training.selfplay_data",
    "play_value_games": "rocalphago_tpu.training.selfplay_data",
    "SLConfig": "rocalphago_tpu.training.sl",
    "SLTrainer": "rocalphago_tpu.training.sl",
    "ValueConfig": "rocalphago_tpu.training.value",
    "ValueTrainer": "rocalphago_tpu.training.value",
    "ZeroState": "rocalphago_tpu.training.zero",
    "init_zero_state": "rocalphago_tpu.training.zero",
    "make_zero_iteration": "rocalphago_tpu.training.zero",
}

__getattr__, __dir__, __all__ = make_lazy(__name__, _EXPORTS)
