"""Trainers: supervised policy, REINFORCE self-play policy, value
regression, and the self-play value-dataset generator the reference
lacks (SURVEY.md §1 L4, §2 "Value trainer" gap)."""

from rocalphago_tpu.training.rl import RLConfig, RLTrainer  # noqa: F401
from rocalphago_tpu.training.selfplay_data import (  # noqa: F401
    ValueDataGenerator,
    play_value_games,
)
from rocalphago_tpu.training.sl import SLConfig, SLTrainer  # noqa: F401
from rocalphago_tpu.training.value import (  # noqa: F401
    ValueConfig,
    ValueTrainer,
)
