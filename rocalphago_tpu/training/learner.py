"""The sharded learner: replay updates at their own cadence.

The learner half of the actor/learner split (docs/SCALE.md).
:class:`ZeroLearner` wraps ``iteration.learn`` — the replay+update
half of ``training.zero.make_zero_iteration``, whose jitted programs
carry explicit ``NamedSharding`` in/out shardings when a mesh is
supplied (params/opt-state replicated, game batch sharded on
``data``) and keep their donated carries — and consumes the replay
buffer either FIFO (:meth:`ReplayBuffer.next_batch`, the bit-exact
lockstep path) or by prioritized-recency :meth:`ReplayBuffer.sample`.

The step is compiled once (same shapes every batch) and retried via
the PR-1 machinery on transient faults — legal because ``learn``
rebuilds its donated carry from never-donated state, the same
argument that lets the synchronous loop retry whole iterations.

Metrics: ``learner_steps_total`` counter, ``learner_wait_seconds``
histogram (time blocked on the buffer per step), and the headline
``learner_idle_frac`` gauge — cumulative wait over wall time, THE
number the actor/learner split exists to push down (the synchronous
loop's equivalent is its self-play phase fraction;
``benchmarks/bench_zero_scale.py`` measures both).
"""

from __future__ import annotations

import time

import jax

from rocalphago_tpu.obs import registry, trace
from rocalphago_tpu.runtime import faults, retries


class ZeroLearner:
    """``step(state)``: take one batch from the buffer, run one
    replay update, report idleness. No thread of its own — the
    training loop drives it (cadence = as fast as data allows)."""

    def __init__(self, learn_fn, buffer, *, sample: bool = False,
                 gang=None, metrics=None, retry_attempts: int = 3):
        self._learn_fn = learn_fn
        self._buffer = buffer
        self._sample = sample
        # training.actor.DispatchGang shared with the actors: on one
        # mesh, concurrent play/learn SPMD programs with collectives
        # can deadlock at the rendezvous — each step's dispatch+fetch
        # runs as one atomic device section when a gang is supplied
        self._gang = gang
        self._metrics = metrics
        self._retry_attempts = retry_attempts
        self._wait_s = 0.0
        self._busy_s = 0.0
        self.steps = 0

    @property
    def idle_frac(self) -> float:
        """Fraction of learner wall time spent waiting for games."""
        total = self._wait_s + self._busy_s
        return self._wait_s / total if total > 0 else 0.0

    def step(self, state, timeout: float | None = None):
        """One update. Returns ``(new_state, metrics_dict, entry)``
        — metrics fetched to host floats (the fetch is the sync
        point, so busy time is honest) — or None when the buffer
        timed out / closed empty. ``metrics_dict`` gains
        ``replay_version`` (the snapshot that played the batch) and
        ``replay_staleness_s``."""
        t0 = time.monotonic()
        entry = (self._buffer.sample(timeout) if self._sample
                 else self._buffer.next_batch(timeout))
        t1 = time.monotonic()
        if entry is None:
            self._wait_s += t1 - t0
            registry.gauge("learner_idle_frac").set(self.idle_frac)
            return None
        # mid-step kill point: the batch is already TAKEN, so a kill
        # here models the worst case the failover path must ride out
        # (a consumed-but-unlearned entry; see docs/RESILIENCE.md
        # "Fleet supervision" on why lockstep refuses the ride)
        faults.barrier("learner.step", iteration=self.steps)

        def _learn_synced():
            new_state, m = retries.retry_call(
                self._learn_fn, state, entry.games,
                _retry_kwargs=dict(
                    max_attempts=self._retry_attempts,
                    logger=(self._metrics.log
                            if self._metrics else None)))
            # the fetch is the sync point: busy time is honest and
            # the devices are free once the section returns
            return new_state, {k: float(jax.device_get(v))
                               for k, v in m.items()}

        with trace.span("learner.step", version=entry.version):
            new_state, m = (self._gang.run(_learn_synced)
                            if self._gang else _learn_synced())
        t2 = time.monotonic()
        self._wait_s += t1 - t0
        self._busy_s += t2 - t1
        self.steps += 1
        m["replay_version"] = entry.version
        m["replay_staleness_s"] = round(t1 - entry.t_ingest, 3)
        registry.counter("learner_steps_total").inc()
        registry.histogram("learner_wait_seconds").observe(t1 - t0)
        registry.gauge("learner_idle_frac").set(self.idle_frac)
        return new_state, m, entry
