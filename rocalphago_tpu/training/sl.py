"""Supervised policy training, data-parallel over the device mesh.

Parity: ``AlphaGo/training/supervised_policy_trainer.py::run_training``
(SGD + categorical cross-entropy on (state → expert move), minibatch 16,
lr ~0.003 with decay, .93/.05/.02 split, 8-symmetry augmentation,
per-epoch checkpoints + ``metadata.json``, persisted shuffle for resume;
SURVEY.md §2 "SL trainer", §3.1).

TPU-native design:
* one jitted ``train_step`` whose inputs carry `NamedSharding`s — batch
  split over the mesh ``data`` axis, params replicated; XLA inserts the
  gradient all-reduce over ICI (SURVEY.md §2b "Data parallel");
* dihedral augmentation runs *inside* the step on device
  (``symmetries.random_transform_batch``), not per-sample on host;
* input pipeline: sharded npz + double-buffered ``device_put`` prefetch;
* checkpoints are Orbax pytrees of (params, opt state, step, PRNG bits)
  — exact resume, async save.
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import os
import sys
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocalphago_tpu.data.pipeline import (
    ShardedDataset,
    batch_iterator,
    device_prefetch,
    split_indices,
)
from rocalphago_tpu.io.checkpoint import (
    MetadataWriter,
    TrainCheckpointer,
    pack_rng,
    unpack_rng,
)
from rocalphago_tpu.io.metrics import MetricsLogger
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.obs import jaxobs, trace
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.training.symmetries import random_transform_batch


@dataclasses.dataclass
class SLConfig:
    """Flat, JSON-serializable stage config (SURVEY.md §5 "Config")."""

    model_json: str = ""
    train_data: str = ""          # shard prefix (npz pipeline)
    out_dir: str = ""
    minibatch: int = 16           # per *mesh*, like the reference's 16
    epochs: int = 10
    learning_rate: float = 0.003
    decay: float = 0.0            # Keras-style lr/(1+decay*step)
    momentum: float = 0.0
    train_val_test: tuple = (0.93, 0.05, 0.02)
    symmetries: bool = True
    seed: int = 0
    num_devices: int | None = None
    max_validation_batches: int = 200
    epoch_length: int | None = None   # steps per epoch; None = full pass
    save_every: int | None = None     # also checkpoint every N steps
    #                                   (mid-epoch preemption recovery)


class SLState(NamedTuple):
    params: dict
    opt_state: tuple
    step: jax.Array     # int32 []
    rng: jax.Array      # uint32 key data


def make_optimizer(cfg: SLConfig) -> optax.GradientTransformation:
    """SGD with the reference's Keras-style inverse-time lr decay."""
    if cfg.decay:
        sched = lambda step: cfg.learning_rate / (1.0 + cfg.decay * step)  # noqa: E731
    else:
        sched = cfg.learning_rate
    return optax.sgd(sched, momentum=cfg.momentum or None)


def policy_loss_fn(apply_fn, params, planes, actions, weights=None):
    logits = apply_fn(params, planes)
    # pass actions (== N, present when a corpus was converted with
    # include_passes) are outside the policy's board-point output space
    # — mask them out rather than letting the xent gather clamp them
    # onto the last board point
    valid = (actions < logits.shape[-1]).astype(jnp.float32)
    if weights is not None:
        valid = valid * weights
    denom = jnp.maximum(valid.sum(), 1.0)
    xent = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.minimum(actions, logits.shape[-1] - 1))
    loss = (xent * valid).sum() / denom
    acc = (((logits.argmax(axis=-1) == actions) * valid).sum() / denom)
    return loss, acc


def make_train_step(apply_fn, tx, size: int, symmetries: bool):
    """Pure (state, planes, actions) → (state, metrics) step fn."""

    def train_step(state: SLState, planes, actions):
        key = unpack_rng(state.rng)
        key, sub = jax.random.split(key)
        planes = planes.astype(jnp.float32)
        if symmetries:
            planes, actions = random_transform_batch(
                sub, planes, actions, size)
        (loss, acc), grads = jax.value_and_grad(
            functools.partial(policy_loss_fn, apply_fn), has_aux=True)(
                state.params, planes, actions)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new = SLState(params, opt_state, state.step + 1, pack_rng(key))
        return new, {"loss": loss, "accuracy": acc}

    return train_step


def make_eval_step(apply_fn, num_points: int):
    def eval_step(params, planes, actions, weights):
        loss, acc = policy_loss_fn(
            apply_fn, params, planes.astype(jnp.float32), actions,
            weights)
        # effective sample count = the loss denominator (real rows
        # whose action is a board point)
        count = ((actions < num_points) * weights).sum()
        return {"loss": loss, "accuracy": acc, "count": count}
    return eval_step


def pad_batch(planes, targets, batch_size: int):
    """Pad a short final batch up to ``batch_size`` (repeating row 0)
    with a 0/1 weight vector marking the real rows — so evaluation
    keeps one compiled shape and small validation splits still
    contribute instead of being dropped."""
    k = len(targets)
    weights = np.ones(batch_size, np.float32)
    if k < batch_size:
        pad = batch_size - k
        planes = np.concatenate(
            [planes, np.repeat(planes[:1], pad, axis=0)])
        targets = np.concatenate(
            [targets, np.repeat(targets[:1], pad, axis=0)])
        weights[k:] = 0.0
    return planes, targets, weights


class SLTrainer:
    """Wires net + data + mesh + checkpointing into the train loop.

    Usable programmatically (tests drive small configs through it) or
    via the ``run_training`` CLI.
    """

    def __init__(self, cfg: SLConfig, net: NeuralNetBase | None = None):
        self.cfg = cfg
        self.net = net or NeuralNetBase.load_model(cfg.model_json)
        self.mesh = meshlib.make_mesh(cfg.num_devices)
        self.dataset = ShardedDataset(cfg.train_data)
        if self.dataset.planes != self.net.preprocess.output_dim:
            raise ValueError(
                f"dataset has {self.dataset.planes} planes but the model's "
                f"feature list needs {self.net.preprocess.output_dim}")
        os.makedirs(cfg.out_dir, exist_ok=True)

        dwidth = self.mesh.shape[meshlib.DATA_AXIS]
        if cfg.minibatch % dwidth:
            raise ValueError(
                f"minibatch {cfg.minibatch} not divisible by data-parallel "
                f"width {dwidth}")

        tx = make_optimizer(cfg)
        size = self.net.board
        opt_state0 = tx.init(self.net.params)
        batch_sh = meshlib.data_sharding(self.mesh, rank=4)
        act_sh = meshlib.data_sharding(self.mesh, rank=1)
        rep = meshlib.replicated(self.mesh)
        state_sh = SLState(
            params=jax.tree.map(lambda _: rep, self.net.params),
            opt_state=jax.tree.map(lambda _: rep, opt_state0),
            step=rep, rng=rep)
        # compile-tracked (obs.jaxobs): a recompile mid-run — a shape
        # drifting between epochs — surfaces as a named `compile`
        # event instead of a silent throughput cliff
        self._train_step = jaxobs.track("sl.train_step", jax.jit(
            make_train_step(self.net.module.apply, tx, size, cfg.symmetries),
            in_shardings=(state_sh, batch_sh, act_sh),
            out_shardings=(state_sh, rep),
            donate_argnums=(0,)))
        self._eval_step = jaxobs.track("sl.eval_step", jax.jit(
            make_eval_step(self.net.module.apply, size * size),
            in_shardings=(state_sh.params, batch_sh, act_sh, act_sh),
            out_shardings=rep))

        self.tx = tx
        # multi-host: artifact files are coordinator-only; Orbax saves
        # stay all-process (SURVEY.md §2b "Multi-host")
        self.coord = meshlib.is_coordinator()
        self.ckpt = TrainCheckpointer(
            os.path.join(cfg.out_dir, "checkpoints"))
        self.metrics = MetricsLogger(
            os.path.join(cfg.out_dir, "metrics.jsonl")
            if self.coord else None, echo=self.coord)
        # spans/compile events share the metrics stream (obs.trace)
        trace.configure(self.metrics)

        key = jax.random.key(cfg.seed)
        self.state = meshlib.replicate(self.mesh, SLState(
            params=self.net.params,
            opt_state=opt_state0,
            step=jnp.int32(0),
            rng=pack_rng(key)))

        self.train_idx, self.val_idx, self.test_idx = split_indices(
            len(self.dataset), cfg.train_val_test, seed=cfg.seed,
            path=os.path.join(cfg.out_dir, "shuffle.npz"),
            write=self.coord)
        self.start_epoch = 0
        self._resume_skip = 0
        self._maybe_resume()

    # ----------------------------------------------------------- resume

    def _maybe_resume(self):
        restored, step = self.ckpt.restore(jax.device_get(self.state))
        if restored is None:
            return
        self.state = meshlib.replicate(self.mesh, SLState(*restored))
        # the data cursor is derived, not stored: batch order within an
        # epoch is a pure function of (seed, epoch) — see run() — so
        # step % steps_per_epoch IS the number of consumed batches, and
        # a mid-epoch kill resumes at exactly the next unseen batch
        self.start_epoch, self._resume_skip = divmod(
            int(restored.step), max(self._steps_per_epoch(), 1))
        self.metrics.log("resume", step=int(restored.step),
                         epoch=self.start_epoch, skip=self._resume_skip)

    def _steps_per_epoch(self) -> int:
        if self.cfg.epoch_length:
            return self.cfg.epoch_length
        return max(len(self.train_idx) // self.cfg.minibatch, 1)

    # ------------------------------------------------------------- train

    def run(self) -> dict:
        cfg = self.cfg
        meta = MetadataWriter(
            os.path.join(cfg.out_dir, "metadata.json"),
            header={"cmd": " ".join(sys.argv),
                    "config": dataclasses.asdict(cfg),
                    "dataset_positions": len(self.dataset)},
            enabled=self.coord)
        steps_per_epoch = self._steps_per_epoch()
        jaxobs.maybe_start_profiler()      # env-gated capture
        # host wait per prefetched batch — the data-starvation probe
        # (near-zero while the input pipeline keeps up with the step)
        data_wait = obs_registry.histogram(
            "train_data_wait_seconds", trainer="sl")
        # host RNG seeded per-epoch → identical batch order on re-run
        # of the same epoch after resume (reference shuffle.npz trick)
        final = {}
        for epoch in range(self.start_epoch, cfg.epochs):
          with trace.span("sl.epoch", epoch=epoch):
            faults.barrier("sl.pre_epoch", epoch)
            skip = self._resume_skip if epoch == self.start_epoch else 0
            host_rng = np.random.default_rng(
                np.random.SeedSequence([cfg.seed, epoch]))
            it = batch_iterator(self.dataset, self.train_idx,
                                cfg.minibatch, host_rng, epochs=1,
                                skip=skip)
            it = (meshlib.shard_batch(self.mesh, b)
                  for b in it)
            t0 = time.time()
            losses, accs = [], []
            with trace.span("sl.train"):
              for i, (planes, actions) in enumerate(obs_registry.timed(
                      device_prefetch(it, size=2), data_wait)):
                if i >= steps_per_epoch - skip:
                    break
                self.state, m = self._train_step(
                    self.state, planes, actions)
                losses.append(m["loss"])
                accs.append(m["accuracy"])
                if cfg.save_every:
                    gstep = epoch * steps_per_epoch + skip + len(losses)
                    if gstep % cfg.save_every == 0:
                        self.ckpt.save(gstep, jax.device_get(self.state))
                        faults.barrier("sl.step_save", gstep)
            if not losses:
                raise ValueError(
                    f"train split ({len(self.train_idx)} positions) "
                    f"yields no full minibatch of {cfg.minibatch}; "
                    "convert more games or shrink the minibatch")
            train_loss = float(jnp.mean(jnp.stack(losses)))
            train_acc = float(jnp.mean(jnp.stack(accs)))
            dt = time.time() - t0
            with trace.span("sl.eval"):
                val = self.evaluate(self.val_idx)
            step = int(jax.device_get(self.state.step))
            entry = {
                "epoch": epoch, "step": step,
                "train_loss": train_loss, "train_accuracy": train_acc,
                "val_loss": val["loss"], "val_accuracy": val["accuracy"],
                "positions_per_s": len(losses) * cfg.minibatch / max(dt, 1e-9),
            }
            self.metrics.log("epoch", **entry)
            meta.record_epoch(entry)
            # exports BEFORE the checkpoint save (the commit point): a
            # crash in between is healed by resume re-running the
            # epoch and rewriting identical artifacts atomically
            with trace.span("sl.export"):
                self._export_weights(epoch)
            with trace.span("sl.save"):
                faults.barrier("sl.pre_save", epoch)
                self.ckpt.save(step, jax.device_get(self.state))
                if faults.active():
                    # deterministic barrier: commit the async save
                    # before post_save (see training.zero)
                    self.ckpt.wait()
                faults.barrier("sl.post_save", epoch)
            final = entry
        # held-out test-split metric (BASELINE.md metric 1: top-1 move
        # accuracy) — recorded in metadata.json for tooling and
        # reportable standalone via training.evaluate
        if len(self.test_idx):
            test = self.evaluate(self.test_idx)
            final = dict(final, test_loss=test["loss"],
                         test_accuracy=test["accuracy"])
            meta.update(test_loss=test["loss"],
                        test_accuracy=test["accuracy"])
            self.metrics.log("test", **test)
        self.ckpt.wait()
        # the run's counter/histogram state, queryable by obs_report
        obs_registry.log_to(self.metrics)
        jaxobs.stop_profiler()
        return final

    def evaluate(self, indices, max_batches: int | None = None) -> dict:
        cfg = self.cfg
        max_batches = max_batches or cfg.max_validation_batches
        params = self.state.params
        rng = np.random.default_rng(0)
        loss_sum = acc_sum = count = 0.0
        it = batch_iterator(self.dataset, indices, cfg.minibatch, rng,
                            epochs=1, drop_remainder=False)
        for i, (planes, actions) in enumerate(it):
            if i >= max_batches:
                break
            planes, actions, weights = pad_batch(
                planes, actions, cfg.minibatch)
            planes, actions, weights = meshlib.shard_batch(
                self.mesh, (planes, actions, weights))
            m = self._eval_step(params, planes, actions, weights)
            c = float(m["count"])
            loss_sum += float(m["loss"]) * c
            acc_sum += float(m["accuracy"]) * c
            count += c
        if not count:
            return {"loss": float("nan"), "accuracy": float("nan")}
        return {"loss": loss_sum / count, "accuracy": acc_sum / count}

    def _export_weights(self, epoch: int) -> None:
        """Reference-parity per-epoch weight export
        (``weights.NNNNN``-style) plus ``model.json`` — a loadable
        spec always pointing at the latest weights, so downstream
        stages (RL, GTP) can consume ``out_dir/model.json`` directly."""
        if not self.coord:
            return
        self.net.params = jax.device_get(self.state.params)
        weights = os.path.join(
            self.cfg.out_dir, f"weights.{epoch:05d}.flax.msgpack")
        self.net.save_model(
            os.path.join(self.cfg.out_dir, "model.json"), weights)


def run_training(argv=None) -> dict:
    """CLI parity with the reference trainer."""
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    # persistent compile cache before any compile (ROCALPHAGO_COMPILE_
    # CACHE): repeat/resumed runs skip the cold program compiles
    enable_compile_cache()
    # multi-host bring-up (DCN) before any backend touch; no-op for
    # single-process runs (SURVEY.md §7 step 7)
    meshlib.distributed_init()
    ap = argparse.ArgumentParser(
        description="Supervised policy training on expert games")
    ap.add_argument("model_json")
    ap.add_argument("train_data", help="npz shard prefix")
    ap.add_argument("out_dir")
    ap.add_argument("--minibatch", "-B", type=int, default=16)
    ap.add_argument("--epochs", "-E", type=int, default=10)
    ap.add_argument("--learning-rate", "-l", type=float, default=0.003)
    ap.add_argument("--decay", "-d", type=float, default=0.0)
    ap.add_argument("--momentum", type=float, default=0.0)
    ap.add_argument("--train-val-test", nargs=3, type=float,
                    default=[0.93, 0.05, 0.02])
    ap.add_argument("--no-symmetries", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-devices", type=int, default=None)
    ap.add_argument("--epoch-length", type=int, default=None)
    ap.add_argument("--save-every", type=int, default=None,
                    help="extra checkpoint every N steps (mid-epoch "
                         "preemption recovery)")
    a = ap.parse_args(argv)
    cfg = SLConfig(
        model_json=a.model_json, train_data=a.train_data, out_dir=a.out_dir,
        minibatch=a.minibatch, epochs=a.epochs,
        learning_rate=a.learning_rate, decay=a.decay, momentum=a.momentum,
        train_val_test=tuple(a.train_val_test),
        symmetries=not a.no_symmetries, seed=a.seed,
        num_devices=a.num_devices, epoch_length=a.epoch_length,
        save_every=a.save_every)
    return SLTrainer(cfg).run()


if __name__ == "__main__":
    run_training(sys.argv[1:])
