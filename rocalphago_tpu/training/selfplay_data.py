"""Self-play (state, outcome) dataset generator for value training.

The reference has **no** automated generator of the de-correlated
value-net training set — the AlphaGo paper's Step-3 data generation is
left to the user (SURVEY.md §2 "Value trainer", gap [C-HIGH]). This
module fills that gap, on device: following the paper's recipe, each
game samples a random ply U, plays plies ``t < U`` with the SL policy,
plays ply ``U`` uniformly at random over sensible moves, plies
``t > U`` with the RL policy, and records exactly ONE position per
game — the state right after the random move — labelled with the final
game outcome from that position's player-to-move perspective.

TPU-native design: the whole mixed-policy game is one ``lax.scan``
(like :mod:`rocalphago_tpu.search.selfplay`), with the per-game policy
switch as a ``jnp.where`` over the three candidate actions and the
recorded position captured into a snapshot ``GoState`` carry — no
``[T, B, …]`` plane materialization. Snapshots are encoded with the
*value* feature set in one batched call after the scan and written in
the sharded-npz layout the input pipeline reads (``targets:
"outcome"``, z in the ``actions`` slot).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.features import Preprocess
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.search.selfplay import sensible_mask


class ValueSamples(NamedTuple):
    recorded: jaxgo.GoState  # batched snapshot states (one per game)
    z: jax.Array             # int32 [B] outcome for the player to move
    valid: jax.Array         # bool  [B] game reached its sample ply
    u: jax.Array             # int32 [B] the game's random-ply index U


def _snapshot(mask: jax.Array, new, old):
    """Per-game select between two batched GoState pytrees."""
    def sel(a, b):
        m = mask.reshape((mask.shape[0],) + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)
    return jax.tree.map(sel, new, old)


def _make_value_ply(cfg: jaxgo.GoConfig, features: tuple,
                    apply_sl: Callable, apply_rl: Callable,
                    temperature: float):
    """Shared one-ply body of the mixed-policy value game (snapshot
    recording + SL/random/RL action switch), parameterized over params
    and the per-game random plies ``U`` so both the monolithic scan
    and the chunked runner trace the identical computation."""
    from rocalphago_tpu.features.planes import (
        batched_encoder,
        needs_member,
    )

    n = cfg.num_points
    vgd = jaxgo.vgroup_data(cfg, with_member=needs_member(features),
                            with_zxor=cfg.enforce_superko)
    enc = batched_encoder(cfg, features)
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(jaxgo.step, cfg))

    def ply(params_sl, params_rl, U, carry, t):
        states, rec, recorded, rng = carry
        rng, k_sl, k_rl, k_rand = jax.random.split(rng, 4)

        # record BEFORE stepping: ply U+1's pre-state is the position
        # right after the random move U was played
        hit = (t == U + 1) & ~states.done & ~recorded
        rec = _snapshot(hit, states, rec)
        recorded = recorded | hit

        gd = vgd(states)
        planes = enc(states, gd)
        sens = vsens(states, gd)
        neg = jnp.finfo(jnp.float32).min
        logits_sl = apply_sl(params_sl, planes)
        logits_rl = apply_rl(params_rl, planes)
        a_sl = jax.random.categorical(
            k_sl, jnp.where(sens, logits_sl / temperature, neg), axis=-1)
        a_rl = jax.random.categorical(
            k_rl, jnp.where(sens, logits_rl / temperature, neg), axis=-1)
        a_rand = jax.random.categorical(
            k_rand, jnp.where(sens, 0.0, neg), axis=-1)

        board_action = jnp.where(t < U, a_sl,
                                 jnp.where(t == U, a_rand, a_rl))
        must_pass = ~sens.any(axis=-1)
        action = jnp.where(must_pass, n, board_action).astype(jnp.int32)
        return (vstep(states, action, gd), rec, recorded, rng)

    return ply


def _value_u_cap(max_moves: int, u_max: int | None) -> int:
    return min(u_max if u_max is not None else max_moves - 2,
               max_moves - 2)


def _value_finish(cfg: jaxgo.GoConfig, final, rec, recorded,
                  U) -> ValueSamples:
    winners = jax.vmap(functools.partial(jaxgo.winner, cfg))(final)
    z = (winners.astype(jnp.int32)
         * rec.turn.astype(jnp.int32))
    return ValueSamples(rec, z, recorded, U.astype(jnp.int32))


def play_value_games(cfg: jaxgo.GoConfig, features: tuple,
                     apply_sl: Callable, params_sl,
                     apply_rl: Callable, params_rl,
                     rng: jax.Array, batch: int, max_moves: int = 500,
                     temperature: float = 1.0,
                     u_max: int | None = None) -> ValueSamples:
    """Play ``batch`` mixed-policy games, one value sample per game.

    ``features`` is the *policy* nets' feature set (used in the game
    loop); encode the returned snapshots with the value net's own
    preprocess. ``u_max`` caps the random ply U (default
    ``max_moves - 2`` so the recorded position can exist).
    """
    ply = _make_value_ply(cfg, features, apply_sl, apply_rl,
                          temperature)
    rng, u_key = jax.random.split(rng)
    U = jax.random.randint(u_key, (batch,), 0,
                           _value_u_cap(max_moves, u_max) + 1)

    states0 = jaxgo.new_states(cfg, batch)
    carry0 = (states0, states0, jnp.zeros((batch,), bool), rng)
    (final, rec, recorded, _), _ = lax.scan(
        lambda c, t: (ply(params_sl, params_rl, U, c, t), None),
        carry0, jnp.arange(max_moves))
    return _value_finish(cfg, final, rec, recorded, U)


def make_value_games_chunked(cfg: jaxgo.GoConfig, features: tuple,
                             apply_sl: Callable, apply_rl: Callable,
                             batch: int, max_moves: int = 500,
                             temperature: float = 1.0,
                             u_max: int | None = None,
                             chunk: int = 100):
    """Chunked ``(params_sl, params_rl, rng) -> ValueSamples`` — the
    same mixed-policy game as :func:`play_value_games`, but no device
    program runs longer than one ``chunk``-ply segment (the attached
    TPU tunnel kills programs past ~40s; same watchdog treatment as
    ``make_selfplay_chunked`` / ``make_rl_iteration_chunked``). The
    (states, snapshot, recorded, rng) carry stays device-resident
    between segments, and the host loop exits early once every game
    has ended (the remaining plies are no-ops for the snapshot and the
    outcome). Results are bit-identical to the monolithic scan —
    ``tests/test_value_path.py``."""
    ply = _make_value_ply(cfg, features, apply_sl, apply_rl,
                          temperature)
    u_cap = _value_u_cap(max_moves, u_max)

    @jax.jit
    def begin(rng):
        rng, u_key = jax.random.split(rng)
        U = jax.random.randint(u_key, (batch,), 0, u_cap + 1)
        states0 = jaxgo.new_states(cfg, batch)
        return (states0, states0, jnp.zeros((batch,), bool), rng), U

    @functools.partial(jax.jit, static_argnames=("length",))
    def segment(params_sl, params_rl, U, carry, offset, length):
        def body(c, t):
            return ply(params_sl, params_rl, U, c, t), None

        carry, _ = lax.scan(body, carry, offset + jnp.arange(length))
        return carry

    finish = jax.jit(functools.partial(_value_finish, cfg))

    def run(params_sl, params_rl, rng) -> ValueSamples:
        carry, U = begin(rng)
        for offset in range(0, max_moves, chunk):
            length = min(chunk, max_moves - offset)
            carry = segment(params_sl, params_rl, U, carry,
                            jnp.int32(offset), length)
            if bool(jax.device_get(carry[0].done.all())):
                break
        return finish(carry[0], carry[1], carry[2], U)

    return run


class ValueDataGenerator:
    """Host driver: batches of on-device games → sharded npz corpus."""

    def __init__(self, sl_net: NeuralNetBase, rl_net: NeuralNetBase,
                 value_features: tuple, batch: int = 64,
                 max_moves: int = 500, temperature: float = 1.0,
                 u_max: int | None = None, chunk: int = 0,
                 komi: float | None = None):
        if sl_net.feature_list != rl_net.feature_list or \
                sl_net.board != rl_net.board:
            raise ValueError("SL and RL nets must share features/board")
        import dataclasses

        # scoring komi: per-board-size standard unless overridden
        # (the net spec's GoConfig always carries the 19x19 value)
        self.cfg = dataclasses.replace(
            sl_net.cfg, komi=komi if komi is not None
            else jaxgo.default_komi(sl_net.cfg.size))
        self.sl = sl_net
        self.rl = rl_net
        self.pre = Preprocess(value_features, cfg=self.cfg)
        self.batch = batch

        if chunk:
            self._run = make_value_games_chunked(
                self.cfg, sl_net.feature_list, sl_net.module.apply,
                rl_net.module.apply, batch=batch, max_moves=max_moves,
                temperature=temperature, u_max=u_max, chunk=chunk)
        else:
            self._run = jax.jit(functools.partial(
                play_value_games, self.cfg, sl_net.feature_list,
                sl_net.module.apply, apply_rl=rl_net.module.apply,
                batch=batch, max_moves=max_moves,
                temperature=temperature, u_max=u_max))

    def generate(self, n_positions: int, out_prefix: str,
                 seed: int = 0, shard_size: int = 4096) -> dict:
        """Accumulate ≥ ``n_positions`` valid samples into
        ``{out_prefix}-NNNNN.npz`` shards + manifest (input-pipeline
        layout; z stored in the ``actions`` slot, ``targets:
        "outcome"``)."""
        os.makedirs(os.path.dirname(out_prefix) or ".", exist_ok=True)
        key = jax.random.key(seed)
        shard_counts: list[int] = []
        buf_s, buf_z, total = [], [], 0
        shard_id = 0

        def flush():
            nonlocal shard_id
            if not buf_s:
                return
            np.savez_compressed(
                f"{out_prefix}-{shard_id:05d}.npz",
                states=np.concatenate(buf_s),
                actions=np.concatenate(buf_z))
            shard_counts.append(sum(len(b) for b in buf_s))
            shard_id += 1
            buf_s.clear()
            buf_z.clear()

        dry_batches = 0
        while total < n_positions:
            key, sub = jax.random.split(key)
            samples = self._run(params_sl=self.sl.params,
                                params_rl=self.rl.params, rng=sub)
            planes = self.pre.states_to_tensor(samples.recorded)
            planes = np.asarray((planes > 0.5)).astype(np.uint8)
            valid = np.asarray(samples.valid)
            z = np.asarray(samples.z, np.int32)
            keep = valid & (z != 0)
            if not keep.any():
                # e.g. integer komi (all draws) or max_moves too small
                # for any game to reach its sample ply — fail loudly
                # instead of spinning forever
                dry_batches += 1
                if dry_batches >= 20:
                    raise RuntimeError(
                        "20 consecutive game batches produced no valid "
                        "value samples; check komi (draws are dropped) "
                        "and max_moves (games must reach ply U+1)")
                continue
            dry_batches = 0
            buf_s.append(planes[keep])
            buf_z.append(z[keep])
            total += int(keep.sum())
            if sum(len(b) for b in buf_s) >= shard_size:
                flush()
        flush()

        manifest = {
            "board_size": self.cfg.size,
            "komi": self.cfg.komi,
            "planes": self.pre.output_dim,
            "feature_list": list(self.pre.feature_list),
            "targets": "outcome",
            "shard_counts": shard_counts,
            "num_positions": total,
        }
        with open(f"{out_prefix}-manifest.json", "w") as f:
            json.dump(manifest, f, indent=2)
        return manifest


def run_generator(argv=None) -> dict:
    """CLI: generate the value-training corpus from saved model specs."""
    ap = argparse.ArgumentParser(
        description="Self-play value dataset generator (one "
                    "de-correlated position per game)")
    ap.add_argument("sl_model_json")
    ap.add_argument("rl_model_json")
    ap.add_argument("out_prefix")
    ap.add_argument("--n-positions", type=int, required=True)
    ap.add_argument("--value-features", nargs="*", default=None,
                    help="feature names for the recorded planes "
                         "(default: the SL net's feature list + the "
                         "'color' plane — the 49-plane value input)")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--max-moves", type=int, default=500)
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=0,
                    help="plies per compiled segment (0 = monolithic "
                         "scan; use e.g. 10-60 on backends that kill "
                         "long device programs) — with early exit "
                         "once every game in the batch has ended")
    ap.add_argument("--komi", type=float, default=None,
                    help="area-scoring komi (default: the board "
                         "size's standard; engine.jaxgo.default_komi)")
    a = ap.parse_args(argv)
    sl = NeuralNetBase.load_model(a.sl_model_json)
    rl = NeuralNetBase.load_model(a.rl_model_json)
    if a.value_features:
        features = tuple(a.value_features)
    elif "color" in sl.feature_list:
        features = sl.feature_list
    else:
        features = sl.feature_list + ("color",)
    gen = ValueDataGenerator(sl, rl, features, batch=a.batch,
                             max_moves=a.max_moves,
                             temperature=a.temperature, chunk=a.chunk,
                             komi=a.komi)
    manifest = gen.generate(a.n_positions, a.out_prefix, seed=a.seed)
    print(json.dumps({k: manifest[k] for k in
                      ("num_positions", "planes", "board_size")}))
    return manifest


if __name__ == "__main__":
    run_generator(sys.argv[1:])
