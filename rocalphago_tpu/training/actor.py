"""Self-play actors: paced producers feeding the replay buffer.

The actor half of the actor/learner split (docs/SCALE.md). Each
:class:`SelfplayActor` is a thread around ``iteration.play`` (the
self-play-only half ``training.zero.make_zero_iteration`` exposes)
that repeatedly: polls the :class:`ParamsPublisher` for a params
snapshot, walks its own rng chain with
:func:`rocalphago_tpu.training.zero.next_keys`, plays one batch of
games, and streams the host copy into the
:class:`rocalphago_tpu.data.replay.ReplayBuffer`.

Two pacing modes:

- **lockstep** (``lockstep=True``, 1 actor): game ``k`` waits for
  published version ``k`` and the rng chain starts from the trainer
  state's own rng — with a FIFO consumer this reproduces the
  synchronous loop bit-for-bit (the bit-exactness A/B `run_training
  --actor-learner` keeps).
- **free-run** (default): actors always play the latest snapshot;
  staleness is bounded by the buffer's pacing (blocking ``put``) and
  reported by its staleness histogram.

Preemption tolerance: each game is wrapped in the PR-1 retry
machinery (``runtime.retries``) — safe because ``play`` donates
nothing the caller can see — and a non-transient failure parks the
actor with ``error`` set instead of killing the process. Under
``runtime.supervisor`` the park becomes a death report: the
supervisor resurrects free-run actors from the factory (fresh rng
branch, in-flight game discarded) and REFUSES lockstep restarts
(docs/RESILIENCE.md "Fleet supervision"). Each game boundary
declares the ``actor.game`` fault barrier, and waits (params,
paced put) are tagged ``actor:<name>`` in the watchdog's
``waiting_on`` registry so stalls name the blocked fleet member.

Metrics: ``actor_games_total{actor=}`` counter,
``actor_params_version`` gauge; each game runs under an
``actor.play`` span.
"""

from __future__ import annotations

import os
import threading
import time

import jax

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry, trace
from rocalphago_tpu.runtime import faults, retries, watchdog
from rocalphago_tpu.training.zero import next_keys

POLL_ENV = "ROCALPHAGO_ACTOR_POLL_S"

#: the rollout spill pointer a serving process watches
#: (docs/ROLLOUT.md): ``{"version", "policy", "value"}`` next to the
#: checkpoint pair it names, atomically replaced on each publish
SPILL_NAME = "rollout.json"


def default_poll_s() -> float:
    """Wait-slice for params/buffer waits (responsiveness of stop)."""
    return float(os.environ.get(POLL_ENV, "0.5"))


def write_spill(dir_path: str, *, version: int, policy_path: str,
                value_path: str) -> str:
    """Atomically write ``dir_path/rollout.json`` naming the latest
    gated checkpoint pair — the cross-process half of the rollout
    path: a :class:`~rocalphago_tpu.rollout.hotswap.SpillWatcher` (or
    a restarted serving process) reads it to pick up the promoted
    version without sharing a process with training."""
    from rocalphago_tpu.runtime.atomic import atomic_write_json

    path = os.path.join(dir_path, SPILL_NAME)
    atomic_write_json(path, {
        "version": int(version),
        "policy": os.path.basename(policy_path),
        "value": os.path.basename(value_path),
    })
    return path


def read_spill(dir_path: str) -> dict | None:
    """The current spill pointer (None when absent/partial — the
    atomic replace means a reader never sees a torn file)."""
    import json

    try:
        with open(os.path.join(dir_path, SPILL_NAME),
                  encoding="utf-8") as f:
            spill = json.load(f)
    except (OSError, ValueError):
        return None
    if not all(k in spill for k in ("version", "policy", "value")):
        return None
    return spill


class DispatchGang:
    """Serializes whole device sections between threads sharing one
    multi-device mesh.

    Two concurrently executing SPMD programs that both contain
    collectives over the SAME devices can interleave their per-device
    executions in different orders and deadlock at the collective
    rendezvous — each program holds some device queues while waiting
    for the rest (observed as an XLA-CPU ``AllReduceParticipantData
    ... may be stuck`` hang; the hazard is generic to any shared
    single-controller device set). The gang makes each participant's
    dispatch-to-fetch section atomic: one ``play`` or one learner
    step owns the devices at a time. Nothing real is lost on a shared
    mesh — the programs were time-sharing the same chips anyway; what
    the actor/learner split still buys is learner cadence decoupled
    from game cadence (sample mode) and host-side overlap (encode,
    buffer ops, spill I/O all run outside the gang).
    """

    def __init__(self, name: str = "DispatchGang._lock"):
        self._lock = lockcheck.make_lock(name)

    def run(self, fn, *args, **kwargs):
        """Run ``fn`` — a dispatch+sync section: jitted calls plus
        the ``device_get`` that retires them — holding the gang."""
        with self._lock:
            # the callback IS the protected resource (an atomic
            # device section), not a re-entrancy hazard: sections
            # never touch the gang from inside
            return fn(*args, **kwargs)  # jaxlint: disable=callback-under-lock


class ParamsPublisher:
    """Versioned params snapshot actors poll between games.

    The learner (or the gate, after a promotion) calls
    :meth:`publish`; actors block in :meth:`wait_version` until the
    version they need exists. Snapshots are jax arrays shared by
    reference — publish is O(1), no copies.
    """

    def __init__(self, spill_dir: str | None = None):
        self._cond = lockcheck.make_condition("ParamsPublisher._cond")
        self._version = -1     # guarded-by: self._cond
        self._policy = None    # guarded-by: self._cond
        self._value = None     # guarded-by: self._cond
        #: directory to mirror each publish into as an on-disk
        #: checkpoint pair + rollout.json pointer (None = in-process
        #: only); lets a serving process in ANOTHER process follow
        self.spill_dir = spill_dir

    def publish(self, policy_params, value_params,
                version: int | None = None) -> int:
        """Install a snapshot; bumps the version (or sets it
        explicitly — the lockstep path pins version = iteration)."""
        with self._cond:
            self._version = (self._version + 1 if version is None
                             else int(version))
            self._policy = policy_params
            self._value = value_params
            v = self._version
            self._cond.notify_all()
        registry.gauge("actor_params_version").set(v)
        if self.spill_dir is not None:
            self._spill(v, policy_params, value_params)
        return v

    def _spill(self, version: int, policy_params,
               value_params) -> None:
        """Mirror one publish to disk: serialize the pair (flax
        msgpack, host copies), then atomically flip rollout.json at
        it. Pointer-last ordering means a watcher that reads the
        pointer always finds both files; older spill pairs are pruned
        best-effort once the pointer has moved on."""
        from flax import serialization

        from rocalphago_tpu.runtime.atomic import atomic_write_bytes

        d = self.spill_dir
        os.makedirs(d, exist_ok=True)
        ppath = os.path.join(d, f"spill.{version:05d}.policy.msgpack")
        vpath = os.path.join(d, f"spill.{version:05d}.value.msgpack")
        atomic_write_bytes(ppath, serialization.to_bytes(
            jax.device_get(policy_params)))
        atomic_write_bytes(vpath, serialization.to_bytes(
            jax.device_get(value_params)))
        write_spill(d, version=version, policy_path=ppath,
                    value_path=vpath)
        for name in sorted(os.listdir(d)):
            if (name.startswith("spill.") and name.endswith(".msgpack")
                    and not name.startswith(f"spill.{version:05d}.")):
                try:
                    os.remove(os.path.join(d, name))
                except OSError:
                    pass  # a concurrent reader may hold it open

    def get(self):
        """Latest ``(version, policy_params, value_params)``;
        version -1 before the first publish."""
        with self._cond:
            return self._version, self._policy, self._value

    def wait_version(self, min_version: int,
                     timeout: float | None = None):
        """Block until a snapshot with version >= ``min_version`` is
        published; returns ``(version, pp, vp)`` or None on
        timeout."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._version < min_version:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return None
                self._cond.wait(rem)
            return self._version, self._policy, self._value


class SelfplayActor:
    """A producer thread streaming finished game batches into the
    replay buffer (module docstring for the pacing modes).

    ``play_fn`` is ``iteration.play``; ``rng`` is the packed rng bits
    the chain starts from (the trainer state's own rng in lockstep, a
    ``fold_in``-derived per-actor key otherwise); ``games`` bounds
    how many batches to produce (None = until :meth:`stop`).
    """

    def __init__(self, play_fn, publisher: ParamsPublisher, buffer,
                 rng, *, name: str = "actor0", lockstep: bool = False,
                 start_index: int = 0, games: int | None = None,
                 pace: bool = True, poll_s: float | None = None,
                 gang: DispatchGang | None = None, metrics=None,
                 on_progress=None):
        self._play_fn = play_fn
        self._gang = gang
        self._publisher = publisher
        self._buffer = buffer
        self._rng = rng
        self.name = name
        self.lockstep = lockstep
        self._start_index = start_index
        self._games = games
        self._pace = pace
        self._poll_s = default_poll_s() if poll_s is None else poll_s
        self._metrics = metrics
        self._on_progress = on_progress   # supervisor heartbeat
        self._inject: BaseException | None = None
        self.games_played = 0
        self.error: BaseException | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"selfplay-{name}", daemon=True)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "SelfplayActor":
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def alive(self) -> bool:
        return self._thread.is_alive()

    def inject_fault(self, exc: BaseException | None = None) -> None:
        """Arm a one-shot fault raised at this actor's next game
        boundary (default :class:`~..runtime.faults.InjectedKill`) —
        the deterministic per-actor kill the recovery bench
        (``bench_zero_scale.py --kill-actor-at``) uses; randomized
        schedules go through ``ROCALPHAGO_FAULT_PLAN`` instead."""
        self._inject = exc if exc is not None else faults.InjectedKill(
            f"injected kill of {self.name} (inject_fault)")

    # ------------------------------------------------------ producer

    def _run(self) -> None:
        rng = self._rng
        index = self._start_index
        while not self._stop.is_set():
            if (self._games is not None
                    and index - self._start_index >= self._games):
                break
            # lockstep: game k is played by the version-k snapshot
            # (exactly the pair the synchronous loop would use);
            # free-run: whatever is freshest
            need = index if self.lockstep else 0
            with watchdog.waiting_on(f"actor:{self.name}"):
                got = self._publisher.wait_version(need, self._poll_s)
            if got is None:
                continue
            version, pp, vp = got
            rng, game_key = next_keys(rng)

            def _play_synced():
                # dispatch AND fetch inside one gang section — the
                # devices are only free again once the host copy
                # retires every program the game dispatched
                games = retries.retry_call(
                    self._play_fn, pp, vp, game_key,
                    _retry_kwargs=dict(
                        max_attempts=3, base_delay=0.5,
                        logger=(self._metrics.log
                                if self._metrics else None)))
                return jax.device_get(games)

            try:
                faults.barrier("actor.game", iteration=index)
                if self._inject is not None:
                    exc, self._inject = self._inject, None
                    raise exc
                with trace.span("actor.play", actor=self.name,
                                game=index):
                    host = (self._gang.run(_play_synced)
                            if self._gang else _play_synced())
            except BaseException as e:  # noqa: BLE001 — park, report
                self.error = e
                if self._metrics is not None:
                    self._metrics.log(
                        "actor_error", actor=self.name,
                        error=f"{type(e).__name__}: {e}")
                break
            while not self._stop.is_set():
                with watchdog.waiting_on(f"actor:{self.name}"):
                    accepted = self._buffer.put(
                        host, version=version, block=self._pace,
                        timeout=self._poll_s)
                if accepted:
                    registry.counter("actor_games_total",
                                     actor=self.name).inc()
                    self.games_played += 1
                    index += 1
                    if self._on_progress is not None:
                        self._on_progress()
                    break
                if self._buffer.closed:
                    self._stop.set()   # drain finished — park
                    break
