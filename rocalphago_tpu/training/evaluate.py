"""Standalone model evaluation: model.json + corpus → metric JSON.

The measurement path for BASELINE.md metric 1 (SL policy top-1 move
accuracy on held-out KGS positions) — and its value-net analogue —
without running a trainer: load any registered net from its JSON spec,
stream a converted corpus through the jitted forward, and print one
JSON line with the metric(s). The reference has no equivalent CLI (its
accuracy only appears inside Keras ``fit`` logs); this fills the
metric-plumbing gap called out in round 1.

Usage::

    python -m rocalphago_tpu.training.evaluate model.json corpus-prefix
        [--split test --shuffle-npz out/shuffle.npz]
        [--minibatch 256] [--max-batches N]

With ``--shuffle-npz`` the persisted trainer split is honored, so the
reported number is on exactly the positions the trainer never touched;
otherwise the whole corpus is evaluated.
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import numpy as np

from rocalphago_tpu.data.pipeline import ShardedDataset, batch_iterator
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.training.sl import (
    make_eval_step as make_policy_eval_step,
    pad_batch,
)
from rocalphago_tpu.training.value import (
    make_eval_step as make_value_eval_step,
)


def evaluate_model(net: NeuralNetBase, dataset: ShardedDataset,
                   indices: np.ndarray, minibatch: int = 256,
                   max_batches: int | None = None,
                   num_devices: int | None = None) -> dict:
    """Loss/top-1 (policy-shaped nets) or MSE (value nets) over
    ``indices``; streaming, one compiled shape (short batches padded
    with zero weights)."""
    mesh = meshlib.make_mesh(num_devices)
    dwidth = mesh.shape[meshlib.DATA_AXIS]
    if minibatch % dwidth:
        minibatch = dwidth * max(minibatch // dwidth, 1)
    is_value = dataset.manifest.get("targets") == "outcome"
    n = net.board * net.board
    if is_value:
        eval_step = jax.jit(make_value_eval_step(net.module.apply))
    else:
        eval_step = jax.jit(make_policy_eval_step(net.module.apply, n))

    sums: dict[str, float] = {}
    count = 0.0
    rng = np.random.default_rng(0)
    it = batch_iterator(dataset, indices, minibatch, rng, epochs=1,
                        drop_remainder=False)
    for i, (planes, targets) in enumerate(it):
        if max_batches is not None and i >= max_batches:
            break
        planes, targets, weights = pad_batch(planes, targets, minibatch)
        planes, targets, weights = meshlib.shard_batch(
            mesh, (planes, targets, weights))
        m = jax.device_get(eval_step(net.params, planes, targets,
                                     weights))
        c = float(m.pop("count"))
        for k, v in m.items():
            sums[k] = sums.get(k, 0.0) + float(v) * c
        count += c
    if not count:
        return {"positions": 0}
    out = {k: v / count for k, v in sums.items()}
    out["positions"] = int(count)
    if "accuracy" in out:
        out["top1"] = out.pop("accuracy")
    return out


def pick_split(dataset, split: str, shuffle_npz: str | None):
    if shuffle_npz is None:
        return np.arange(len(dataset))
    z = np.load(shuffle_npz)
    if split not in z:
        raise ValueError(f"split {split!r} not in {shuffle_npz} "
                         f"(has {sorted(z.keys())})")
    return z[split]


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(
        description="Evaluate a saved model on a converted corpus")
    ap.add_argument("model_json")
    ap.add_argument("corpus", help="npz shard prefix")
    ap.add_argument("--split", default="test",
                    choices=("train", "val", "test"))
    ap.add_argument("--shuffle-npz", default=None,
                    help="trainer split file; restricts to --split")
    ap.add_argument("--minibatch", "-B", type=int, default=256)
    ap.add_argument("--max-batches", type=int, default=None)
    ap.add_argument("--num-devices", type=int, default=None)
    a = ap.parse_args(argv)

    net = NeuralNetBase.load_model(a.model_json)
    dataset = ShardedDataset(a.corpus)
    if dataset.planes != net.preprocess.output_dim:
        raise ValueError(
            f"corpus has {dataset.planes} planes but the model needs "
            f"{net.preprocess.output_dim}")
    indices = pick_split(dataset, a.split, a.shuffle_npz)
    result = dict(evaluate_model(net, dataset, indices,
                                 minibatch=a.minibatch,
                                 max_batches=a.max_batches,
                                 num_devices=a.num_devices),
                  model=a.model_json, split=a.split)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main(sys.argv[1:])
