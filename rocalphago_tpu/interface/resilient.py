"""Self-healing serving wrapper: the degradation ladder.

PR 1 made the *training* side crash-safe; this module is the serving
counterpart. A GTP controller forfeits the game on any ``? error``
reply, so a raising ``player.get_move`` must never reach it — the
AlphaGo-lineage answer is that the policy net is the ANYTIME fallback
for the full search (Maddison et al., "Move Evaluation in Go Using
Deep CNNs") and a loaded server degrades its search budget rather
than erroring (KataGo's serving discipline, Wu arXiv:1902.10565).

:class:`ResilientPlayer` wraps any ``get_move(state)`` player in an
explicit four-rung ladder, walked top to bottom until a legal move
comes out:

1. **search** — the wrapped player's full search (optionally
   hang-protected: the call runs in a worker thread watched by the
   PR-1 :class:`~rocalphago_tpu.runtime.watchdog.Watchdog`; a stalled
   search is abandoned and the ladder continues without it);
2. **reduced** — ONE retry with a reduced simulation budget, taken
   only for transient device errors (classified by
   :func:`rocalphago_tpu.runtime.retries.is_transient` — the same
   line the training retry layer draws: re-dispatching a pure search
   after infrastructure flake is safe, retrying a programming error
   just replays the traceback);
3. **policy** — the raw policy net's argmax move over sensible legal
   moves (:class:`~rocalphago_tpu.search.players.GreedyPolicyPlayer`
   over the SAME policy net the search uses — no extra weights);
4. **fallback** — no nets at all: the first sensible legal move by
   the host rules oracle, else pass. This rung cannot fail; even an
   injected fault inside it degrades to an unconditional pass.

Every rung transition is recorded as a structured ``degradation``
event (rung, reason code, error, latency) to ``metrics.jsonl`` when a
:class:`~rocalphago_tpu.io.metrics.MetricsLogger` is attached, and
counted for the GTP ``rocalphago-health`` probe. Fault-injection
barriers ``serve.search`` / ``serve.reduced`` / ``serve.policy`` /
``serve.fallback`` (:mod:`rocalphago_tpu.runtime.faults`, iteration =
``state.turns_played``) let the chaos tests break every rung and
prove the ladder always lands on a legal move
(``tests/test_serving_chaos.py``).
"""

from __future__ import annotations

import threading
import time

from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.obs import trace
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.retries import is_transient
from rocalphago_tpu.runtime.watchdog import Watchdog

#: ladder rungs, strongest first (the order the ladder walks them)
RUNGS = ("search", "reduced", "policy", "fallback")

#: reason codes a degradation event may carry. ``overload`` is the
#: serving pool's load-shed signal (:class:`~rocalphago_tpu.serve.
#: admission.EvaluatorOverload`): the shared evaluator's bounded
#: queue refused the session's leaf evals, and the ladder IS the
#: per-session shed policy — step down to the reduced-sims retry
#: (less load), then the raw policy net (no evaluator at all).
REASONS = ("transient_error", "overload", "error", "hang",
           "illegal_from_player", "fallback_error", "barrier_fault")


class SearchHang(RuntimeError):
    """The primary search exceeded the hang timeout and was abandoned
    (the worker thread may still be running; its result is discarded).
    A RuntimeError — deliberately NON-transient: retrying a hang at
    the reduced rung would just hang again, so the ladder jumps
    straight to the policy rung."""


class _IllegalFromPlayer(Exception):
    """Internal: the rung produced a move the rules oracle rejects."""


def percentile(sorted_vals, q: float):
    """Nearest-rank percentile of an ascending list (None if empty) —
    tiny and dependency-free; serves the health probe's p50/p99."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ResilientPlayer:
    """Degradation-ladder wrapper around any ``get_move`` player.

    Parameters
    ----------
    primary : the wrapped player (``get_move(state)``; rung 1).
    policy : optional :class:`~rocalphago_tpu.models.policy.CNNPolicy`
        for the raw-policy rung. Defaults to ``primary.policy`` when
        the primary exposes one (DeviceMCTSPlayer and the policy
        players do); without a net the ladder skips rung 3.
    metrics : optional ``MetricsLogger``-shaped object (``log(event,
        **fields)``); degradation events and watchdog stalls land in
        its ``metrics.jsonl``.
    reduced_sims : simulation cap for the reduced-retry rung; default
        ``max(1, primary.n_sim // 4)`` when the primary has an
        ``n_sim``, else a plain retry. Applied via the primary's
        ``sim_limit`` attribute when it has one.
    hang_timeout_s : wall seconds after which a silent rung-1 search
        is abandoned (None disables hang protection — the default:
        no worker thread in the path unless asked for).
    """

    def __init__(self, primary, policy=None, metrics=None,
                 reduced_sims: int | None = None,
                 hang_timeout_s: float | None = None):
        self.primary = primary
        self._policy = (policy if policy is not None
                        else getattr(primary, "policy", None))
        self._greedy = None               # built on first policy rung
        self.metrics = metrics
        self.hang_timeout_s = hang_timeout_s
        if reduced_sims is None:
            n = getattr(primary, "n_sim", None)
            reduced_sims = max(1, n // 4) if n else None
        self.reduced_sims = reduced_sims
        # observability (the GTP health/stats probes read these)
        self.genmoves = 0
        self.served = {r: 0 for r in RUNGS}     # moves served per rung
        self.rung_failures = {r: 0 for r in RUNGS}
        self.reasons: dict = {}                 # reason code -> count
        self.illegal_from_player = 0
        self.barrier_faults = 0
        self.last_rung = None
        self.last_fallback = None       # {"rung","reason","turn"} | None
        self.latencies: list = []       # per-get_move wall seconds

    # ------------------------------------------------------------ rungs

    def _greedy_player(self):
        if self._greedy is None and self._policy is not None:
            from rocalphago_tpu.search.players import GreedyPolicyPlayer

            # a move cap (4·N² — far past any real game) so a
            # degraded endgame always terminates in passes even if
            # the deterministic greedy move would capture-cycle
            board = getattr(self._policy, "board", None)
            limit = 4 * board * board if board else None
            self._greedy = GreedyPolicyPlayer(self._policy,
                                              move_limit=limit)
        return self._greedy

    def _acceptable(self, state, move) -> bool:
        """A servable answer: a legal board move, or pass while the
        game is live (after the game has ended nothing is legal — the
        ladder then bottoms out and the engine reports game over)."""
        if move is None:
            return not state.is_end_of_game
        return bool(state.is_legal(move))

    def _attempt(self, rung: str, fn, state):
        """One rung: its fault barrier, then the rung's move fn —
        hang-protected for the search rung when configured. Raises on
        any failure; returns the move otherwise."""
        timeout = (self.hang_timeout_s if rung in ("search", "reduced")
                   else None)

        def protected():
            # the rung span pins WHERE a hang happened: the watchdog's
            # stall event reads the deepest open span across threads
            # (obs.trace.where), which is this one when a rung wedges
            with trace.span(f"serve.{rung}",
                            turn=state.turns_played):
                faults.barrier(f"serve.{rung}",
                               iteration=state.turns_played)
                return fn(state)

        if timeout is None:
            return protected()
        box: dict = {}
        done = threading.Event()
        abandoned = threading.Event()

        def work():
            try:
                box["move"] = protected()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["exc"] = e
            finally:
                done.set()

        # the PR-1 watchdog is the stall detector: no beat ever
        # arrives, so it fires once at the timeout — logging the
        # stall to metrics.jsonl — and flags the abandon event
        # instead of killing the process (exit=False).
        wd = Watchdog(timeout, metrics=self.metrics,
                      abort_fn=abandoned.set, name=f"serve.{rung}",
                      exit=False, poll_s=min(0.05, timeout / 4.0))
        # abandoned BY DESIGN on hang: joining a wedged search would
        # re-import the hang the ladder exists to escape — the daemon
        # worker's result is discarded (docs/CONCURRENCY.md)
        worker = threading.Thread(  # jaxlint: disable=thread-no-join
            target=work, daemon=True, name=f"genmove-{rung}")
        with wd:
            worker.start()
            while not done.is_set():
                if abandoned.is_set():
                    raise SearchHang(
                        f"{rung} rung silent for {timeout}s; "
                        "abandoned")
                done.wait(0.02)
        if "exc" in box:
            raise box["exc"]
        return box.get("move")

    def _reduced_call(self, state):
        """The reduced-budget re-dispatch: cap the primary's sims via
        its ``sim_limit`` hook when it has one (DeviceMCTSPlayer),
        else a plain retry."""
        if self.reduced_sims is not None and \
                hasattr(self.primary, "sim_limit"):
            prev = self.primary.sim_limit
            self.primary.sim_limit = self.reduced_sims
            try:
                return self.primary.get_move(state)
            finally:
                self.primary.sim_limit = prev
        return self.primary.get_move(state)

    def _fallback_move(self, state):
        """Rung 4: first sensible legal move by the rules oracle,
        else pass. Deterministic, net-free."""
        moves = state.get_legal_moves(include_eyes=False)
        return moves[0] if moves else None

    # ----------------------------------------------------- bookkeeping

    def _classify(self, exc) -> str:
        if isinstance(exc, _IllegalFromPlayer):
            return "illegal_from_player"
        if isinstance(exc, SearchHang):
            return "hang"
        # exceptions may name their own ladder reason (duck-typed so
        # serve.admission need not be imported here): the pool's
        # EvaluatorOverload carries "overload", keeping load sheds
        # distinct from generic transient flake in every probe
        named = getattr(exc, "degradation_reason", None)
        if isinstance(named, str) and named in REASONS:
            return named
        return "transient_error" if is_transient(exc) else "error"

    def _note(self, rung: str, reason: str, exc, t0: float,
              turn: int) -> None:
        self.rung_failures[rung] += 1
        self.reasons[reason] = self.reasons.get(reason, 0) + 1
        obs_registry.counter("serve_degradation_total", rung=rung,
                             reason=reason).inc()
        if reason == "illegal_from_player":
            self.illegal_from_player += 1
        if self.metrics is not None:
            err = None if exc is None else \
                f"{type(exc).__name__}: {exc}"
            self.metrics.log(
                "degradation", rung=rung, reason=reason,
                turn=turn, error=err,
                latency_s=round(time.monotonic() - t0, 4))

    def note_barrier_fault(self, barrier: str, exc) -> None:
        """An engine-level serving barrier (``genmove.*``) raised in
        resilient mode: counted + logged, never surfaced."""
        self.barrier_faults += 1
        self.reasons["barrier_fault"] = \
            self.reasons.get("barrier_fault", 0) + 1
        obs_registry.counter("serve_degradation_total", rung="barrier",
                             reason="barrier_fault").inc()
        if self.metrics is not None:
            self.metrics.log("degradation", rung="barrier",
                             reason="barrier_fault", barrier=barrier,
                             error=f"{type(exc).__name__}: {exc}")

    # ----------------------------------------------------------- serve

    def _run(self, rung: str, fn, state):
        """Attempt one rung end-to-end, including the legality check.
        Returns the move; raises (``_IllegalFromPlayer`` included) on
        anything unservable."""
        move = self._attempt(rung, fn, state)
        if not self._acceptable(state, move):
            raise _IllegalFromPlayer(f"{rung} rung returned {move!r}")
        return move

    def get_move(self, state):
        t0 = time.monotonic()
        turn = state.turns_played
        self.genmoves += 1
        try:
            move, rung = self._ladder(state, t0, turn)
        finally:
            self.latencies.append(time.monotonic() - t0)
        self.served[rung] += 1
        # ladder rungs as registry counters: the GTP stats probe and
        # obs_report read served-per-rung without a ladder reference
        obs_registry.counter("serve_rung_total", rung=rung).inc()
        self.last_rung = rung
        if rung != "search":
            self.last_fallback = {
                "rung": rung,
                "reason": self._last_reason,
                "turn": turn,
            }
        return move

    def _ladder(self, state, t0: float, turn: int):
        self._last_reason = None
        # rung 1: the full search
        try:
            return self._run("search", self.primary.get_move,
                             state), "search"
        except Exception as e:  # noqa: BLE001 — classified below
            reason = self._classify(e)
            self._note("search", reason, e, t0, turn)
            self._last_reason = reason
        # rung 2: reduced-sims retry — transient flake and load sheds
        # only (a re-dispatch after a hang would hang again, after a
        # programming error would re-raise, after an illegal move
        # would return it again). Under overload the reduced budget
        # IS the shed: a quarter of the leaf evals re-enters the
        # queue, and if even that sheds, the policy rung below costs
        # the evaluator nothing.
        if reason in ("transient_error", "overload"):
            try:
                return self._run("reduced", self._reduced_call,
                                 state), "reduced"
            except Exception as e:  # noqa: BLE001
                reason = self._classify(e)
                self._note("reduced", reason, e, t0, turn)
                self._last_reason = reason
        # rung 3: the raw policy net
        greedy = self._greedy_player()
        if greedy is not None:
            try:
                return self._run("policy", greedy.get_move,
                                 state), "policy"
            except Exception as e:  # noqa: BLE001
                reason = self._classify(e)
                self._note("policy", reason, e, t0, turn)
                self._last_reason = reason
        # rung 4: rules-oracle move or pass. Cannot fail: even an
        # injected fault here degrades to the unconditional pass.
        try:
            move = self._attempt("fallback", self._fallback_move,
                                 state)
            if move is not None and not state.is_legal(move):
                move = None
        except Exception as e:  # noqa: BLE001
            self._note("fallback", "fallback_error", e, t0, turn)
            self._last_reason = "fallback_error"
            move = None
        return move, "fallback"

    # ----------------------------------------------- player passthrough

    @property
    def policy(self):
        """The policy net backing the ladder's rung 3 (shared with the
        primary — also lets ``player_board`` see the net size)."""
        return self._policy

    def set_move_time(self, seconds) -> None:
        set_time = getattr(self.primary, "set_move_time", None)
        if set_time is not None:
            set_time(seconds)

    def reset(self) -> None:
        """New game: clear the primary's cross-move search state (the
        ladder itself carries none — its counters are per-process
        observability, deliberately NOT reset per game)."""
        from rocalphago_tpu.search.players import reset_player

        reset_player(self.primary)

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """The health-probe snapshot (``rocalphago-health`` schema —
        see docs/RESILIENCE.md)."""
        lat = sorted(self.latencies)
        degraded = {r: self.served[r] for r in RUNGS[1:]}
        return {
            "genmoves": self.genmoves,
            "degradations": degraded,
            "degraded_total": sum(degraded.values()),
            "rung_failures": dict(self.rung_failures),
            "reasons": dict(self.reasons),
            "illegal_from_player": self.illegal_from_player,
            "barrier_faults": self.barrier_faults,
            "last_rung": self.last_rung,
            "last_fallback": self.last_fallback,
            "latency_s": {
                "p50": (round(percentile(lat, 0.50), 4)
                        if lat else None),
                "p99": (round(percentile(lat, 0.99), 4)
                        if lat else None),
                "last": (round(self.latencies[-1], 4)
                         if self.latencies else None),
            },
        }
