"""Head-to-head evaluation: play two configured agents against each
other and report win rates.

Parity: the reference's SL-vs-RL-vs-MCTS evaluation configurations
(BASELINE.json configs; SURVEY.md §7 step 6 "tournament CLI"). Colors
alternate per game; results stream to stdout and a JSONL file.
"""

from __future__ import annotations

import argparse
import json
import sys

from rocalphago_tpu.engine import pygo


class GameCrash(Exception):
    """A player failed mid-game (raising ``get_move`` or an illegal
    move the engine rejected). Carries the side that crashed so the
    tournament can score the forfeit and play on."""

    def __init__(self, color: int, cause: BaseException):
        self.color = color
        self.cause = cause
        side = "black" if color == pygo.BLACK else "white"
        super().__init__(
            f"{side} crashed: {type(cause).__name__}: {cause}")


def play_match(black, white, size: int = 19, komi: float = 7.5,
               move_limit: int = 722, handicap: int = 0):
    """One game; returns +1 (black win), -1 (white win), 0 (draw).

    ``handicap`` places that many Black stones on the GTP fixed star-
    point layout before play (White moves first, as the rules demand)
    — the variant axis that measures strength GAPS too wide for even
    games to resolve.

    A raising player (or one whose move the rules reject) aborts the
    game with :class:`GameCrash` naming the crashing side — the
    caller decides whether that forfeits (``run_tournament``) or
    propagates."""
    from rocalphago_tpu.interface.gtp import fixed_handicap_points
    from rocalphago_tpu.search.players import reset_player

    state = pygo.GameState(size=size, komi=komi)
    if handicap:
        state.place_handicaps(fixed_handicap_points(size, handicap))
    players = {pygo.BLACK: black, pygo.WHITE: white}
    for player in players.values():
        reset_player(player)
    while not state.is_end_of_game and state.turns_played < move_limit:
        mover = state.current_player
        try:
            move = players[mover].get_move(state)
            state.do_move(move)
        except Exception as e:  # noqa: BLE001 — scored as a forfeit
            raise GameCrash(mover, e) from e
    return state.get_winner()


def run_tournament(player_a, player_b, games: int, size: int = 19,
                   komi: float = 7.5, move_limit: int = 722,
                   log=None, names=("A", "B"),
                   handicap: int = 0) -> dict:
    """``games`` games, colors alternating; returns the tally.

    The tally is kept by player INDEX (0 / 1 / draw) and mapped to
    ``names`` only for display — duplicate or reserved display names
    can't corrupt the counts, and are rejected up front.

    Per-game FAULT ISOLATION: a game a player crashes out of
    (:class:`GameCrash`) is scored as a forfeit — the crashing side
    loses, the log entry records the forfeit and cause — and the
    tournament plays on; one bad game no longer aborts the whole
    run. Forfeit counts come back in the tally (``forfeits``).

    With ``handicap`` every game opens on the star-point stones; the
    color alternation means each player takes Black (and the stones)
    in half the games, so the tally stays symmetric."""
    if len(set(names)) != 2 or "draw" in names:
        raise ValueError(
            f"names must be two distinct labels, neither 'draw'; "
            f"got {names!r}")
    tally = [0, 0, 0]                 # wins A, wins B, draws
    forfeits = [0, 0]                 # games A / B crashed out of
    for g in range(games):
        a_is_black = g % 2 == 0
        black, white = (player_a, player_b) if a_is_black \
            else (player_b, player_a)
        black_name, white_name = (names if a_is_black
                                  else names[::-1])
        forfeit = None
        try:
            w = play_match(black, white, size=size, komi=komi,
                           move_limit=move_limit, handicap=handicap)
        except GameCrash as e:
            w = -e.color              # the crashing side forfeits
            forfeit = {"side": ("black" if e.color == pygo.BLACK
                                else "white"),
                       "error": f"{type(e.cause).__name__}: "
                                f"{e.cause}"}
        idx = 2 if w == 0 else (0 if (w == pygo.BLACK) == a_is_black
                                else 1)
        tally[idx] += 1
        if forfeit is not None:
            # idx of the WINNER is 0/1; the loser crashed
            forfeits[1 - idx] += 1
        winner = "draw" if idx == 2 else names[idx]
        entry = {"game": g, "black": black_name, "white": white_name,
                 "winner": winner}
        if forfeit is not None:
            entry["forfeit"] = forfeit
        if log:
            log.write(json.dumps(entry) + "\n")
            log.flush()
        note = (f" (forfeit by {forfeit['side']}: {forfeit['error']})"
                if forfeit else "")
        print(f"game {g}: {black_name}(B) vs {white_name}(W) -> "
              f"{winner}{note}", file=sys.stderr)
    decided = max(tally[0] + tally[1], 1)
    return {"games": games,
            "wins": {names[0]: tally[0], names[1]: tally[1],
                     "draw": tally[2]},
            "forfeits": {names[0]: forfeits[0],
                         names[1]: forfeits[1]},
            # win rates are over decided games; draws reported apart
            "win_rate_a": tally[0] / decided,
            "win_rate_b": tally[1] / decided}


def _build_player(spec: str, temperature: float, playouts: int,
                  device_rollout: bool = False, board: int | None = None):
    """``kind:policy.json[:value.json[:rollout.json]]`` → agent.
    With ``board``, nets saved at another size re-board through
    ``at_board`` when their params are size-generic (FCN heads — the
    cross-size transfer ladder plays a 9×9-trained checkpoint at
    13×13 this way); size-locked nets are rejected up front (the same
    guard GTP's boardsize applies) instead of crashing with a shape
    error mid-game."""
    from rocalphago_tpu.search.players import build_player, player_board

    parts = spec.split(":")
    try:
        player = build_player(parts[0], parts[1],
                              parts[2] if len(parts) > 2 else None,
                              parts[3] if len(parts) > 3 else None,
                              temperature=temperature, playouts=playouts,
                              device_rollout=device_rollout, board=board)
    except (ValueError, IndexError) as e:
        raise SystemExit(f"bad player spec {spec!r}: {e}")
    net_board = player_board(player)
    if board is not None and net_board is not None and net_board != board:
        raise SystemExit(
            f"player {spec!r} nets are compiled for board "
            f"{net_board}, but the tournament is --board {board}")
    return player


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Agent-vs-agent evaluation tournament")
    ap.add_argument("player_a", help="kind:policy.json[:value.json]")
    ap.add_argument("player_b", help="kind:policy.json[:value.json]")
    ap.add_argument("--games", type=int, default=20)
    ap.add_argument("--board", type=int, default=19)
    ap.add_argument("--komi", type=float, default=None,
                    help="area-scoring komi (default: the board "
                         "size's standard — 7.5 at 13x13+, 7.0 below)")
    ap.add_argument("--move-limit", type=int, default=722)
    ap.add_argument("--handicap", type=int, default=0,
                    help="Black stones on the fixed star-point "
                         "layout before every game (0 = even; colors "
                         "still alternate, so each player takes the "
                         "stones in half the games)")
    ap.add_argument("--temperature", type=float, default=0.67)
    ap.add_argument("--playouts", type=int, default=100)
    ap.add_argument("--device-rollout", action="store_true",
                    help="mcts rollouts as one on-device scan per "
                         "wave instead of host rules")
    ap.add_argument("--log", default=None, help="JSONL game log path")
    a = ap.parse_args(argv)
    if a.komi is None:
        from rocalphago_tpu.engine.jaxgo import default_komi

        a.komi = default_komi(a.board)
    if a.handicap:
        from rocalphago_tpu.interface.gtp import fixed_handicap_points

        try:
            fixed_handicap_points(a.board, a.handicap)
        except ValueError as e:
            raise SystemExit(f"--handicap {a.handicap}: {e}")
    pa = _build_player(a.player_a, a.temperature, a.playouts,
                       device_rollout=a.device_rollout, board=a.board)
    pb = _build_player(a.player_b, a.temperature, a.playouts,
                       device_rollout=a.device_rollout, board=a.board)
    log = open(a.log, "w") if a.log else None
    try:
        tally = run_tournament(pa, pb, a.games, size=a.board,
                               komi=a.komi, move_limit=a.move_limit,
                               log=log, handicap=a.handicap)
    finally:
        if log:
            log.close()
    print(json.dumps(tally))
    return tally


if __name__ == "__main__":
    main(sys.argv[1:])
