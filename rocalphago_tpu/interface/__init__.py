"""Serving interface (reference layer L6): the GTP engine
(SURVEY.md §1 L6, §3.5).

Re-exports are lazy — see :mod:`rocalphago_tpu.utils.lazy`.
"""

from rocalphago_tpu.utils.lazy import make_lazy

_EXPORTS = {
    "GTPEngine": "rocalphago_tpu.interface.gtp",
    "move_to_vertex": "rocalphago_tpu.interface.gtp",
    "run_gtp": "rocalphago_tpu.interface.gtp",
    "vertex_to_move": "rocalphago_tpu.interface.gtp",
    "elo_table": "rocalphago_tpu.interface.elo",
    "ResilientPlayer": "rocalphago_tpu.interface.resilient",
    "GameCrash": "rocalphago_tpu.interface.tournament",
    "run_tournament": "rocalphago_tpu.interface.tournament",
}

__getattr__, __dir__, __all__ = make_lazy(__name__, _EXPORTS)
