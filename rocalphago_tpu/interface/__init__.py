"""Serving interface (reference layer L6): the GTP engine
(SURVEY.md §1 L6, §3.5)."""

from rocalphago_tpu.interface.gtp import (  # noqa: F401
    GTPEngine,
    move_to_vertex,
    run_gtp,
    vertex_to_move,
)
