"""Batched on-device self-play → SGF records CLI.

The reference's self-play lives inside its RL trainer; the rebuild
additionally exposes it standalone (SURVEY.md §7 package layout,
"selfplay CLI"): play N lockstep games entirely on device with any
saved policy (optionally vs a second policy), then write one SGF per
game plus a JSONL summary — inspectable in any SGF viewer, replayable
by the converter.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import jax
import numpy as np

from rocalphago_tpu.data import sgf
from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.atomic import atomic_write_json
from rocalphago_tpu.search.selfplay import make_selfplay


def result_strings(cfg, final_states) -> list:
    """SGF RE values ("B+7.5" area-margin form) per game."""
    b, w = jax.vmap(functools.partial(jaxgo.area_scores, cfg))(
        final_states)
    b, w = np.asarray(b, np.float64), np.asarray(w, np.float64)
    out = []
    for bi, wi in zip(b, w):
        if bi > wi:
            out.append(f"B+{bi - wi:g}")
        elif wi > bi:
            out.append(f"W+{wi - bi:g}")
        else:
            out.append("0")
    return out


def games_to_sgf(cfg, result, out_dir: str, prefix: str = "selfplay",
                 black_name: str = "policy-a",
                 white_name: str = "policy-b") -> list:
    """Write one SGF per game from a ``SelfplayResult``."""
    os.makedirs(out_dir, exist_ok=True)
    actions = np.asarray(result.actions)     # [T, B]
    live = np.asarray(result.live)           # [T, B]
    n = cfg.num_points
    res = result_strings(cfg, result.final)
    paths = []
    from rocalphago_tpu.engine import pygo

    for g in range(actions.shape[1]):
        moves = []
        for t in range(actions.shape[0]):
            if not live[t, g]:
                break
            a = int(actions[t, g])
            color = pygo.BLACK if t % 2 == 0 else pygo.WHITE
            moves.append(
                (color, None if a >= n else divmod(a, cfg.size)))
        game = sgf.from_moves(cfg.size, cfg.komi, moves, result=res[g])
        game.properties["PB"] = black_name
        game.properties["PW"] = white_name
        path = os.path.join(out_dir, f"{prefix}-{g:05d}.sgf")
        with open(path, "w") as f:
            f.write(sgf.render(game))
        paths.append(path)
    return paths


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Play batched on-device self-play games, save SGFs")
    ap.add_argument("--policy", required=True, help="policy model JSON")
    ap.add_argument("--opponent", default=None,
                    help="optional second policy JSON (default: self)")
    ap.add_argument("--games", type=int, default=16)
    ap.add_argument("--out", required=True)
    ap.add_argument("--max-moves", type=int, default=500)
    ap.add_argument("--temperature", type=float, default=0.67)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-sgf", action="store_true",
                    help="summary only (skip SGF files)")
    ap.add_argument("--chunk", type=int, default=0,
                    help="compiled-program size bound for backends "
                         "that kill long device programs: plies per "
                         "segment (policy mode; 0 = one monolithic "
                         "scan), or simulations per program with "
                         "--search-sims (0 = 8)")
    ap.add_argument("--shard", action="store_true",
                    help="shard the game batch over all devices "
                         "(env parallelism across the mesh data axis)")
    ap.add_argument("--search-sims", type=int, default=0,
                    help="play every move from an on-device MCTS of "
                         "this many simulations instead of sampling "
                         "the raw policy (AlphaZero-style generation; "
                         "requires --value; incompatible with "
                         "--opponent/--shard)")
    ap.add_argument("--value", default=None,
                    help="value model JSON (with --search-sims)")
    ap.add_argument("--gumbel", action="store_true",
                    help="with --search-sims: Gumbel root search "
                         "(sequential halving) instead of PUCT; "
                         "plays each ply's halving winner, so "
                         "--temperature does not apply")
    ap.add_argument("--m-root", type=int, default=16,
                    help="gumbel root candidate count; lower it at "
                         "small --search-sims (every halving phase "
                         "visits each survivor at least once)")
    ap.add_argument("--dirichlet-alpha", type=float, default=0.0,
                    help="AlphaZero root-noise Dir(α) for PUCT "
                         "search self-play (0 = off; incompatible "
                         "with --gumbel)")
    ap.add_argument("--noise-frac", type=float, default=0.25,
                    help="root-noise mix fraction ε")
    a = ap.parse_args(argv)
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    enable_compile_cache()      # before any compile (env-tunable)
    if a.gumbel and not a.search_sims:
        raise SystemExit("--gumbel requires --search-sims")
    if a.dirichlet_alpha and not a.search_sims:
        raise SystemExit("--dirichlet-alpha requires --search-sims")
    if a.dirichlet_alpha and a.gumbel:
        raise SystemExit("--dirichlet-alpha is PUCT-mode root noise; "
                         "--gumbel explores via the gumbel draw")
    if a.games % 2 and not a.search_sims:
        # search self-play uses ONE net for both colors — no color
        # split, so odd batches are fine there
        raise SystemExit("--games must be even (color split)")

    net = NeuralNetBase.load_model(a.policy)
    opp = NeuralNetBase.load_model(a.opponent) if a.opponent else net
    cfg = net.cfg
    if a.search_sims:
        if not a.value:
            raise SystemExit("--search-sims requires --value")
        if a.opponent or a.shard:
            raise SystemExit("--search-sims is self-play with one "
                             "net (no --opponent/--shard)")
        from rocalphago_tpu.search.device_mcts import make_mcts_selfplay
        from rocalphago_tpu.search.selfplay import _finish

        value = NeuralNetBase.load_model(a.value)
        # in search mode --chunk bounds SIMULATIONS per compiled
        # program (the per-ply unit of this path), keeping the flag's
        # watchdog contract meaningful
        mcts_run = make_mcts_selfplay(
            cfg, net.feature_list, value.feature_list,
            net.module.apply, value.module.apply, batch=a.games,
            max_moves=a.max_moves, n_sim=a.search_sims,
            temperature=a.temperature,
            sim_chunk=a.chunk or 8, gumbel=a.gumbel,
            m_root=a.m_root, dirichlet_alpha=a.dirichlet_alpha,
            noise_frac=a.noise_frac)

        def run(params_a, params_b, rng):
            final, actions, live = mcts_run(params_a, value.params,
                                            rng)
            # same result assembly as the policy-mode runners
            return _finish(cfg, final, actions, live,
                           score_on_device=True, batch=a.games)
    elif a.shard or a.chunk:
        from rocalphago_tpu.parallel.mesh import make_mesh
        from rocalphago_tpu.search.selfplay import make_selfplay_chunked

        runner = make_selfplay_chunked(
            cfg, net.feature_list, net.module.apply, opp.module.apply,
            batch=a.games, max_moves=a.max_moves,
            chunk=a.chunk or max(a.max_moves, 1),
            temperature=a.temperature,
            mesh=make_mesh() if a.shard else None)
        # stop once every game has ended by two passes: typical games
        # finish far before the move limit (9×9 self-play averages
        # ~70 plies against a 243-ply limit), and the skipped tail is
        # zero-padded with live=False, which the SGF writer already
        # treats as game-over — a 2-3× corpus-generation speedup
        run = lambda *args: runner(*args, stop_when_done=True)  # noqa: E731
    else:
        run = make_selfplay(cfg, net.feature_list, net.module.apply,
                            opp.module.apply, batch=a.games,
                            max_moves=a.max_moves,
                            temperature=a.temperature)
    faults.barrier("selfplay_cli.pre_play")
    import time as _time

    t0 = _time.monotonic()
    result = run(net.params, opp.params, jax.random.key(a.seed))
    jax.device_get(result.winners)
    dt = max(_time.monotonic() - t0, 1e-9)
    faults.barrier("selfplay_cli.post_play")

    # throughput + game-length telemetry (obs.registry): the headline
    # games/min number plus a ply histogram an operator can read off
    # the summary (or obs_report) instead of re-deriving from SGFs
    num_moves = np.asarray(result.num_moves)
    ply_h = obs_registry.histogram("selfplay_game_plies",
                                   edges=obs_registry.COUNT_EDGES)
    for moves in num_moves:
        ply_h.observe(float(moves))
    obs_registry.counter("selfplay_games_total").inc(a.games)
    games_per_min = a.games * 60.0 / dt
    obs_registry.gauge("selfplay_games_per_min").set(games_per_min)

    winners = np.asarray(result.winners)
    summary = {
        "games": a.games,
        "black_wins": int((winners > 0).sum()),
        "white_wins": int((winners < 0).sum()),
        "draws": int((winners == 0).sum()),
        "mean_moves": float(num_moves.mean()),
        "games_per_min": round(games_per_min, 3),
        "wall_s": round(dt, 3),
    }
    os.makedirs(a.out, exist_ok=True)
    if not a.no_sgf:
        paths = games_to_sgf(
            cfg, result, a.out,
            black_name=os.path.basename(a.policy),
            white_name=os.path.basename(a.opponent or a.policy))
        summary["sgf_files"] = len(paths)
        faults.barrier("selfplay_cli.post_sgf")
    # the full counter/histogram state rides along in the summary
    # (this CLI has no metrics.jsonl for obs_report to read)
    summary["registry"] = obs_registry.snapshot()
    atomic_write_json(os.path.join(a.out, "summary.json"), summary)
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main(sys.argv[1:])
