"""GTP (Go Text Protocol) engine over stdin/stdout.

Parity: ``interface/gtp_wrapper.py::run_gtp`` (engine wrapping any
player with a ``get_move(state)`` method, spoken to by GoGui/KGS-style
controllers; SURVEY.md §1 L6, §3.5). The reference leaned on the
``gtp`` pip package; the protocol is ~100 lines, so the rebuild ships
its own host-side implementation (SURVEY.md §2a — not
performance-relevant) rather than depending on it.

Supported commands: the GTP 2 administrative/core set
(``protocol_version name version known_command list_commands quit``),
setup (``boardsize clear_board komi fixed_handicap place_free_handicap
set_free_handicap``), play (``play genmove undo``), tournament
niceties (``showboard final_score time_left time_settings``), and the
private operator probes ``rocalphago-health`` / ``rocalphago-stats``
(one-line JSON; schema in docs/RESILIENCE.md).

RESILIENT SERVING (default): a GTP controller forfeits the game on
any ``? error`` genmove reply, so ``cmd_genmove`` never surfaces a
player exception — the player is wrapped in a
:class:`~rocalphago_tpu.interface.resilient.ResilientPlayer` and a
failing search walks the degradation ladder (full search →
reduced-sims retry → raw policy move → rules-oracle fallback) until a
legal vertex comes out. Fault-injection barriers
``genmove.pre_search`` / ``genmove.post_search`` /
``genmove.pre_apply`` (:mod:`rocalphago_tpu.runtime.faults`) cover
the engine's own serving path; in resilient mode a fault fired there
is counted and logged, never echoed to the controller.
``resilient=False`` restores the raw legacy behavior (exceptions
become ``? error`` replies).
"""

from __future__ import annotations

import argparse
import json
import sys

from rocalphago_tpu.engine import pygo
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.obs import trace
from rocalphago_tpu.runtime import faults

COLS = "ABCDEFGHJKLMNOPQRSTUVWXYZ"  # GTP skips I

def fixed_handicap_points(size: int, n: int) -> list:
    """GTP 2 fixed_handicap layouts on the star points: corners for
    2–4; center joins only at odd counts (5, 7, 9); 6 adds the left
    and right mid-sides, 8 all four mid-sides."""
    if size < 7 or size % 2 == 0:
        raise ValueError("board has no fixed handicap layout")
    edge = 2 if size < 13 else 3
    lo, hi, mid = edge, size - 1 - edge, size // 2
    corners = [(hi, hi), (lo, lo), (lo, hi), (hi, lo)]
    sides_lr = [(lo, mid), (hi, mid)]
    sides_tb = [(mid, lo), (mid, hi)]
    center = (mid, mid)
    layouts = {
        2: corners[:2], 3: corners[:3], 4: corners,
        5: corners + [center],
        6: corners + sides_lr,
        7: corners + sides_lr + [center],
        8: corners + sides_lr + sides_tb,
        9: corners + sides_lr + sides_tb + [center],
    }
    if n not in layouts:
        raise ValueError("invalid number of stones")
    return layouts[n]


def free_handicap_points(size: int, n: int) -> list:
    """Up to ``n`` engine-chosen handicap vertices (GTP
    ``place_free_handicap`` may place fewer): star points first, then a
    deterministic spread over remaining third-line points."""
    try:
        pts = list(fixed_handicap_points(size, min(n, 9)))
    except ValueError:
        pts = []
    if len(pts) >= n:
        return pts[:n]
    edge = 2 if size < 13 else 3
    lo, hi = edge, size - 1 - edge
    seen = set(pts)
    for x in range(lo, hi + 1, 2):
        for y in range(lo, hi + 1, 2):
            if len(pts) >= n:
                return pts
            if (x, y) not in seen:
                pts.append((x, y))
                seen.add((x, y))
    return pts


def move_to_vertex(move, size: int) -> str:
    """(x, y) board move (or None) → GTP vertex string. ``x`` is the
    column (A..T skipping I), ``y`` the row (1-based)."""
    if move is None:
        return "pass"
    x, y = move
    return f"{COLS[int(x)]}{int(y) + 1}"


def vertex_to_move(vertex: str, size: int):
    """GTP vertex → (x, y) or None for pass. Raises ValueError."""
    v = vertex.strip().upper()
    if v in ("PASS",):
        return None
    if v in ("RESIGN",):
        raise ValueError("resign is not a board vertex")
    col, row = v[0], v[1:]
    x = COLS.index(col)
    y = int(row) - 1
    if not (0 <= x < size and 0 <= y < size):
        raise ValueError(f"vertex {vertex!r} off the {size}x{size} board")
    return (x, y)


def parse_color(s: str) -> int:
    c = s.strip().lower()
    if c in ("b", "black"):
        return pygo.BLACK
    if c in ("w", "white"):
        return pygo.WHITE
    raise ValueError(f"invalid color {s!r}")


class GTPEngine:
    """Stateful GTP command dispatcher around a player object.

    ``player`` needs ``get_move(state)``; if it exposes a ``reset`` or
    its MCTS exposes ``reset``, a ``clear_board`` clears search state
    too.
    """

    def __init__(self, player, name: str = "rocalphago-tpu",
                 version: str = "0.1", metrics=None,
                 resilient: bool = True,
                 hang_timeout_s: float | None = None,
                 serve_pool=None, serve_session=None):
        from rocalphago_tpu.interface.resilient import ResilientPlayer

        self.player = player
        self._metrics = metrics
        self._resilient = resilient
        self._hang_timeout_s = hang_timeout_s
        # multi-size serving: the engine owns its pool session handle
        # so cmd_boardsize can re-route it to another size's pool
        self._serve_session = serve_session
        if not resilient:
            self._serve = None
        elif isinstance(player, ResilientPlayer):
            self._serve = player
            if metrics is not None and player.metrics is None:
                player.metrics = metrics
            if hang_timeout_s is not None \
                    and player.hang_timeout_s is None:
                player.hang_timeout_s = hang_timeout_s
        else:
            self._serve = ResilientPlayer(
                player, metrics=metrics,
                hang_timeout_s=hang_timeout_s)
        self.illegal_from_player = 0  # engine-level final-guard count
        # serve-backed players (rocalphago_tpu/serve) surface their
        # pool's live stats through the probes: explicit serve_pool,
        # else discovered off the primary (SessionPlayer.pool)
        self._serve_pool = serve_pool
        self.name = name
        self.version = version
        self.size = self._player_board() or 19
        self.komi = 7.5
        self.state = pygo.GameState(size=self.size, komi=self.komi)
        self._undo_stack: list = []
        self._time_settings = None    # (main_s, byo_s, byo_stones)
        # color -> (seconds, stones, spent-at-report, genmoves-at-
        # report): the trailing pair ages the report (ADVICE r4 —
        # GTP does not require per-move time_left, so a one-shot
        # report must decay as the engine spends its own time)
        self._time_left: dict = {}
        self._time_spent: dict = {}   # color -> own-genmove seconds
        self._genmoves: dict = {}     # color -> genmove count
        # GTP command names may not contain "_" per the method-name
        # mapping; the private extensions are conventionally dashed
        # (rocalphago-health), so display/dispatch translate the
        # rocalphago_ prefix both ways
        self._commands = sorted(
            m[4:].replace("rocalphago_", "rocalphago-", 1)
            for m in dir(self) if m.startswith("cmd_"))

    # ------------------------------------------------------------ admin

    def cmd_protocol_version(self, args):
        return "2"

    def cmd_name(self, args):
        return self.name

    def cmd_version(self, args):
        return self.version

    def cmd_known_command(self, args):
        return "true" if args and args[0] in self._commands else "false"

    def cmd_list_commands(self, args):
        return "\n".join(self._commands)

    def cmd_quit(self, args):
        return ""

    # ------------------------------------------------------------ setup

    def _new_game(self, reason: str = "clear_board"):
        from rocalphago_tpu.search.players import reset_player

        self.state = pygo.GameState(size=self.size, komi=self.komi)
        self._undo_stack.clear()
        self._time_left = {}      # fresh game, fresh clocks
        self._time_spent = {}
        self._genmoves = {}
        # reason labels the player's cache/carry invalidation
        # (encode_cache_resets_total{reason=...} — the incremental
        # encoder's explicit full-re-encode fallbacks)
        reset_player(self.player, reason=reason)

    def _player_board(self):
        """Fixed board size the wrapped player's nets were built for
        (None when the player is size-agnostic)."""
        from rocalphago_tpu.search.players import player_board

        return player_board(self.player)

    def cmd_boardsize(self, args):
        size = int(args[0])
        if not 2 <= size <= 25:
            raise ValueError("unacceptable size")
        # the nets are compiled for a fixed board; accepting another
        # size would only fail later inside genmove with an opaque
        # shape error — reply per GTP instead. A multi-size serve
        # pool instead RE-ROUTES the session to the target size's
        # member pool (a dict lookup over shared weights, not an
        # engine rebuild — rocalphago_tpu/multisize)
        net_board = self._player_board()
        if net_board is not None and size != net_board \
                and not self._reroute_board(size):
            raise ValueError("unacceptable size")
        self.size = size
        self._new_game(reason="boardsize")
        return ""

    def _reroute_board(self, size: int) -> bool:
        """Swap this engine's serve session to ``size``'s member pool
        (multi-size pools only). The engine's komi travels with it."""
        from rocalphago_tpu.interface.resilient import ResilientPlayer

        pool = self._serve_pool
        if pool is None or not hasattr(pool, "pool_for"):
            return False
        try:
            new = pool.open_session(size=size,
                                    resilient=self._resilient)
        except KeyError:
            return False            # size not active on this pool
        if self._serve_session is not None:
            self._serve_session.close()
        self._serve_session = new
        new.set_komi(self.komi)
        self.player = new.player
        if isinstance(new.player, ResilientPlayer):
            self._serve = new.player
            if self._metrics is not None and new.player.metrics is None:
                new.player.metrics = self._metrics
            if self._hang_timeout_s is not None \
                    and new.player.hang_timeout_s is None:
                new.player.hang_timeout_s = self._hang_timeout_s
        return True

    def cmd_clear_board(self, args):
        self._new_game()
        return ""

    def cmd_komi(self, args):
        self.komi = float(args[0])
        self.state.komi = self.komi
        # serve-backed engine: re-thread the pool session's komi too,
        # so terminal leaf values in the shared evaluator score under
        # it (komi is request data there, not a recompile — see
        # rocalphago_tpu/serve/sessions.py)
        primary = self._primary_player()
        if getattr(primary, "pool", None) is not None \
                and hasattr(primary, "komi"):
            primary.komi = self.komi
        return ""

    def cmd_fixed_handicap(self, args):
        pts = fixed_handicap_points(self.size, int(args[0]))
        self.state.place_handicaps(pts)
        return " ".join(move_to_vertex(p, self.size) for p in pts)

    def cmd_place_free_handicap(self, args):
        # free placement: the engine chooses; GTP 2 allows returning
        # fewer stones than requested, but must place some. Use the
        # star-point layouts as far as they go.
        n = int(args[0])
        if n < 2:
            raise ValueError("invalid number of stones")
        pts = free_handicap_points(self.size, n)
        self.state.place_handicaps(pts)
        return " ".join(move_to_vertex(p, self.size) for p in pts)

    def cmd_set_free_handicap(self, args):
        pts = [vertex_to_move(v, self.size) for v in args]
        if None in pts:
            raise ValueError("pass is not a handicap vertex")
        self.state.place_handicaps(pts)
        return ""

    # ------------------------------------------------------------- play

    def _apply_move(self, move, color) -> None:
        """Snapshot + play; a rejected move leaves the undo stack
        untouched (do_move raises before mutating on illegal input,
        including moves after the game has ended)."""
        snapshot = self.state.copy()
        self.state.do_move(move, color)
        self._undo_stack.append(snapshot)

    def cmd_play(self, args):
        color = parse_color(args[0])
        move = vertex_to_move(args[1], self.size)
        prev = self.state.current_player
        self.state.current_player = color
        try:
            if move is not None and not self.state.is_legal(move):
                raise ValueError("illegal move")
            self._apply_move(move, color)
        except Exception:
            # a rejected command must leave the GameState untouched,
            # including the side to move
            self.state.current_player = prev
            raise
        return ""

    def _serving_barrier(self, name: str) -> None:
        """Declare a fault barrier on the genmove path. In resilient
        mode an injected fault here is counted + logged (the move
        must still go out); raw mode lets it raise like any command
        error."""
        try:
            faults.barrier(name, iteration=self.state.turns_played)
        except Exception as e:  # noqa: BLE001 — injected by design
            if self._serve is None:
                raise
            self._serve.note_barrier_fault(name, e)

    def _generate(self, color):
        """One move off the player surface. Resilient mode guarantees
        a servable answer (the ladder bottoms out at pass); raw mode
        propagates player exceptions (legacy ``? error`` replies)."""
        try:
            # a raising time hook must not take the move down with it
            set_time = getattr(self.player, "set_move_time", None)
            if set_time is not None:
                set_time(self._move_budget_s(color))
        except Exception as e:  # noqa: BLE001
            if self._serve is None:
                raise
            self._serve.note_barrier_fault("genmove.set_move_time", e)
        self._serving_barrier("genmove.pre_search")
        if self._serve is not None:
            move = self._serve.get_move(self.state)
        else:
            move = self.player.get_move(self.state)
        self._serving_barrier("genmove.post_search")
        if move is not None and not self.state.is_legal(move):
            # final guard (the ladder validates before this in
            # resilient mode): historically a silent pass — count it
            # and emit the degradation signal instead of losing it
            self.illegal_from_player += 1
            if self._metrics is not None:
                self._metrics.log(
                    "degradation", rung="engine",
                    reason="illegal_from_player",
                    turn=self.state.turns_played, move=str(move))
            move = None
        return move

    def cmd_genmove(self, args):
        color = parse_color(args[0])
        prev = self.state.current_player
        self.state.current_player = color
        import time as _time

        t0 = _time.monotonic()
        try:
            # inside the try: any genmove failure must restore the
            # side to move (raw mode; resilient mode only raises
            # below for a game already over). The span names this
            # phase for watchdog stall events; the histogram backs
            # the latency section of the stats probe.
            with trace.span("gtp.genmove",
                            turn=self.state.turns_played):
                move = self._generate(color)
                self._serving_barrier("genmove.pre_apply")
                self._apply_move(move, color)
        except Exception:
            self.state.current_player = prev
            raise
        finally:
            dt = _time.monotonic() - t0
            self._time_spent[color] = (self._time_spent.get(color, 0.0)
                                       + dt)
            self._genmoves[color] = self._genmoves.get(color, 0) + 1
            obs_registry.histogram("gtp_genmove_seconds").observe(dt)
        return move_to_vertex(move, self.size)

    def cmd_undo(self, args):
        if not self._undo_stack:
            raise ValueError("cannot undo")
        self.state = self._undo_stack.pop()
        # a komi set after the snapshot must survive the undo
        self.state.komi = self.komi
        # rewinds are a history jump: the device player's subtree
        # walk detects it on its own (turns_played decreased), and
        # the incremental-encode cache stays CORRECT either way
        # (board-diff invalidation) — no reset needed here, the next
        # root encode simply refreshes what the jump dirtied
        return ""

    # ------------------------------------------------------ observation

    def cmd_showboard(self, args):
        s = self.state
        rows = []
        for y in reversed(range(s.size)):
            cells = []
            for x in range(s.size):
                v = s.board[x, y]
                cells.append("X" if v == pygo.BLACK
                             else "O" if v == pygo.WHITE else ".")
            rows.append(f"{y + 1:2d} " + " ".join(cells))
        rows.append("   " + " ".join(COLS[:s.size]))
        return "\n" + "\n".join(rows)

    def cmd_final_score(self, args):
        black, white = self.state.get_scores()
        if black > white:
            return f"B+{black - white:g}"
        if white > black:
            return f"W+{white - black:g}"
        return "0"

    # ----------------------------------------------- operator probes
    #
    # Private extensions (the `rocalphago-` prefix keeps them out of
    # controllers' way; GoGui shows them under "analyze commands"):
    # one-line JSON so an operator — or a load balancer — can probe a
    # live engine over its GTP pipe. Schema: docs/RESILIENCE.md.

    def _primary_player(self):
        return self._serve.primary if self._serve is not None \
            else self.player

    def _pool(self):
        """The serving pool behind this engine's player, if any."""
        if self._serve_pool is not None:
            return self._serve_pool
        return getattr(self._primary_player(), "pool", None)

    def cmd_rocalphago_health(self, args):
        """Degradation-ladder health: counts per rung, p50/p99
        genmove latency, last fallback reason, sims actually run.
        Serve-backed engines add the pool block (live sessions,
        queue depth, batch occupancy, sheds — docs/SERVING.md), the
        fields an LB health check keys on."""
        if self._serve is None:
            raise ValueError("resilient serving disabled")
        s = self._serve.stats()
        s["illegal_from_player"] += self.illegal_from_player
        s["status"] = ("ok" if s["last_rung"] in (None, "search")
                       else "degraded")
        primary = self._primary_player()
        s["sims"] = {"last": getattr(primary, "last_n_sim", None),
                     "nominal": getattr(primary, "n_sim", None)}
        s["deadline"] = {
            "hits": getattr(primary, "deadline_hits", 0),
            "last_hit": bool(getattr(primary, "last_deadline_hit",
                                     False))}
        pool = self._pool()
        if pool is not None:
            s["serve"] = pool.stats()
        return json.dumps(s, sort_keys=True)

    def cmd_rocalphago_stats(self, args):
        """Operational snapshot: game/clock/search state plus the
        full ladder stats (superset of rocalphago-health)."""
        primary = self._primary_player()
        clock = getattr(primary, "_clock", None)

        def per_color(d, r=None):
            return {"black": (round(d.get(pygo.BLACK, 0), 3)
                              if r else d.get(pygo.BLACK, 0)),
                    "white": (round(d.get(pygo.WHITE, 0), 3)
                              if r else d.get(pygo.WHITE, 0))}

        out = {
            "name": self.name,
            "version": self.version,
            "game": {
                "size": self.size,
                "komi": self.komi,
                "turns": self.state.turns_played,
                "to_move": ("black" if self.state.current_player
                            == pygo.BLACK else "white"),
                "over": bool(self.state.is_end_of_game),
            },
            "genmoves": per_color(self._genmoves),
            "time_spent_s": per_color(self._time_spent, r=True),
            "clock": {
                "settings": (list(self._time_settings)
                             if self._time_settings else None),
                "move_time_s": getattr(clock, "move_time", None),
                "rate_units_per_s": getattr(clock, "rate", None),
            },
            "search": {
                "last_n_sim": getattr(primary, "last_n_sim", None),
                "nominal_n_sim": getattr(primary, "n_sim", None),
                "reuses": getattr(primary, "reuses", None),
                "deadline_hits": getattr(primary, "deadline_hits",
                                         None),
                "last_deadline_hit": getattr(
                    primary, "last_deadline_hit", None),
            },
            "ladder": (self._serve.stats()
                       if self._serve is not None else None),
            # the serving pool's live stats (serve-backed player)
            "serve": (self._pool().stats()
                      if self._pool() is not None else None),
            # the live process-wide metric registry (ladder-rung
            # counters, genmove/chunk latency histograms, deadline
            # margin — obs.registry; schema docs/OBSERVABILITY.md)
            "registry": obs_registry.snapshot(),
        }
        return json.dumps(out, sort_keys=True)

    # ------------------------------------------------------------- time
    #
    # The reference wrapper delegates clock handling to its GTP shim
    # (SURVEY.md §1 L6); here the engine owns the clock arithmetic
    # and the player owns the sims-per-second conversion: genmove
    # hands the moving color's per-move second budget to the player's
    # ``set_move_time`` hook (when it has one — DeviceMCTSPlayer
    # shrinks its simulation count proportionally).

    def cmd_time_settings(self, args):
        # GTP-2: main_time byo_yomi_time byo_yomi_stones (canadian)
        main, byo_t, byo_s = (float(args[0]), float(args[1]),
                              int(args[2]))
        if main < 0 or byo_t < 0 or byo_s < 0:
            raise ValueError("time arguments must be non-negative")
        self._time_settings = (main, byo_t, byo_s)
        self._time_left = {}
        self._time_spent = {}     # a re-issued clock starts fresh
        self._genmoves = {}
        return ""

    def cmd_time_left(self, args):
        color = parse_color(args[0])
        # snapshot our own spend/move counters so the report can be
        # aged: a controller that reports once must not yield a
        # frozen budget for the rest of the game (ADVICE r4)
        self._time_left[color] = (
            float(args[1]), int(args[2]),
            self._time_spent.get(color, 0.0),
            self._genmoves.get(color, 0))
        return ""

    def _est_moves_left(self) -> float:
        """Per-player moves still to come: a game runs ~0.75·N² plies
        total, floored so late-game budgets never spike."""
        total = 0.75 * self.size * self.size
        return max(10.0, (total - self.state.turns_played) / 2.0)

    def _move_budget_s(self, color):
        """Seconds this genmove may spend, or None (no time control).

        Proportional rule: in byo-yomi (``time_left`` with stones>0),
        the remaining period time splits evenly over the remaining
        period stones; in main time, the remaining clock splits over
        the estimated moves left.

        Idempotent per position: the byo-yomi rebase below rewrites
        ``self._time_left`` from the REPORT snapshot (a pure function
        of the cached report and the settings), so any number of
        budget queries between genmoves (analysis, debug probes)
        converge on the same ledger instead of re-basing a fresh
        period at each query time — which would restart the period
        clock on every call and never age it."""
        settings = self._time_settings
        left = self._time_left.get(color)
        if left is not None:
            t, stones, spent0, moves0 = left
            # age the report by our own spend since it arrived; a
            # synthetic rebased ledger can place the period start
            # before spend already made, so cap at the period size —
            # byo-yomi time never accumulates
            rem = min(t, t - (self._time_spent.get(color, 0.0)
                              - spent0))
            if stones > 0:                     # canadian byo-yomi
                # period stones also shrink by the moves we've made
                # since the report
                made = self._genmoves.get(color, 0) - moves0
                if rem > 0 and made < stones:
                    return rem / (stones - made)
                if rem > 0 and made >= stones:
                    # all reported stones played WITH time to spare:
                    # a NEW period legitimately began when the
                    # stones-th stone went down. REBASE the cached
                    # report to that period, baselined at the REPORT
                    # snapshot (its whole t consumed, its stones all
                    # made) rather than at query-time counters: the
                    # rewrite is then idempotent, and the new period
                    # is not over-credited by whatever was spent
                    # between the last period stone and this query.
                    if settings is not None and settings[2] > 0:
                        byo_t, byo_s = settings[1], settings[2]
                        self._time_left[color] = (
                            byo_t, byo_s, spent0 + t,
                            moves0 + stones)
                        # recurse on the rebased ledger (terminates:
                        # each level consumes byo_s made-moves, and a
                        # blitz across several unreported periods
                        # just rebases once per period)
                        return self._move_budget_s(color)
                # rem <= 0: by our own ledger the period flag has
                # fallen (time ran out with stones owed, or stones
                # completed only after the time was gone) — refilling
                # would search on lost time, so play out at minimum
                # budget until the controller's next time_left report
                # replaces this ledger. Sticky by design: blitzing
                # out the owed stones must NOT re-arm the clock.
                return 0.0
            if rem > 0:
                return rem / self._est_moves_left()
            # reported main time is exhausted: fall into byo-yomi if
            # the settings define one
            if settings is not None and settings[2] > 0:
                return settings[1] / settings[2]
            return 0.0
        if settings is not None:
            main, byo_t, byo_s = settings
            if main > 0:
                # no time_left report: the engine must decrement its
                # OWN clock — budgeting the full main time every move
                # would plan several times the allotment over a game
                rem = main - self._time_spent.get(color, 0.0)
                if rem > 0:
                    return rem / self._est_moves_left()
                # main time self-exhausted (ADVICE r4): byo-yomi
                # periods remain playable forever, not budget 0.0
                if byo_s > 0:
                    return byo_t / byo_s
                return 0.0
            if byo_s > 0:
                return byo_t / byo_s
        return None

    # --------------------------------------------------------- dispatch

    def handle(self, line: str):
        """One GTP line → (reply string or None to terminate)."""
        line = line.split("#", 1)[0].strip()
        if not line:
            return None, False
        parts = line.split()
        cmd_id = ""
        if parts[0].isdigit():
            cmd_id = parts[0]
            parts = parts[1:]
        if not parts:
            return None, False
        cmd, args = parts[0], parts[1:]
        # the private extensions are dashed on the wire
        # (rocalphago-health) but methods can't be — translate
        lookup = cmd.replace("-", "_") \
            if cmd.startswith("rocalphago-") else cmd
        fn = getattr(self, f"cmd_{lookup}", None)
        if fn is None:
            return f"?{cmd_id} unknown command\n\n", False
        try:
            result = fn(args)
        except Exception as e:  # noqa: BLE001 — GTP reports all errors
            return f"?{cmd_id} {e}\n\n", False
        sep = " " if result else ""
        return f"={cmd_id}{sep}{result}\n\n", cmd == "quit"


def run_gtp(player, instream=None, outstream=None, **engine_kwargs):
    """Blocking GTP loop (reference ``run_gtp`` entry point)."""
    instream = instream or sys.stdin
    outstream = outstream or sys.stdout
    engine = GTPEngine(player, **engine_kwargs)
    for line in instream:
        reply, done = engine.handle(line)
        if reply is not None:
            outstream.write(reply)
            outstream.flush()
        if done:
            break
    return engine


class GatewayBridge:
    """GTP front end over a network gateway (docs/GATEWAY.md).

    ``gtp.py --connect host:port`` speaks stdin/stdout GTP to the
    controller while every board mutation and genmove goes over the
    gateway's NDJSON wire — the process holds NO models and NO
    devices, so a laptop GoGui can drive a pool on a TPU host.

    Refusals stay structured end to end: a gateway shed
    (``overload``/``draining``) surfaces as a clean GTP error with
    the server's retry hint (``? gateway overload, retry in 1.0s``)
    instead of a hang or a dead pipe; a dropped connection ends the
    session (the controller sees the error and the loop stops, like
    ``quit``).
    """

    def __init__(self, client, name: str = "rocalphago-gateway",
                 version: str = "0.1"):
        self.client = client
        self.name = name
        self.version = version
        self._board = int(client.default_board)
        self._komi = None
        self._open = False

    # ------------------------------------------------------- commands

    def _ensure_game(self) -> None:
        if not self._open:
            self.client.new_game(board=self._board, komi=self._komi)
            self._open = True

    def cmd_protocol_version(self, args):
        return "2"

    def cmd_name(self, args):
        return self.name

    def cmd_version(self, args):
        return self.version

    def cmd_known_command(self, args):
        known = args and hasattr(self, f"cmd_{args[0]}")
        return "true" if known else "false"

    def cmd_list_commands(self, args):
        return "\n".join(sorted(
            m[len("cmd_"):] for m in dir(self)
            if m.startswith("cmd_")))

    def cmd_boardsize(self, args):
        size = int(args[0])
        if size not in self.client.boards:
            raise ValueError("unacceptable size")
        self._board = size
        self._open = False
        return ""

    def cmd_clear_board(self, args):
        self._open = False
        self._ensure_game()
        return ""

    def cmd_komi(self, args):
        self._komi = float(args[0])
        if self._open:
            self.client.set_komi(self._komi)
        return ""

    def cmd_play(self, args):
        self._ensure_game()
        self.client.play(args[0], args[1])
        return ""

    def cmd_genmove(self, args):
        self._ensure_game()
        return self.client.genmove(args[0])["move"]

    def cmd_quit(self, args):
        self.client.close()
        return ""

    # ------------------------------------------------------- dispatch

    def handle(self, line: str):
        """One GTP line → (reply string or None, done) — the same
        contract as :meth:`GTPEngine.handle`."""
        from rocalphago_tpu.gateway.client import (
            GatewayClosed,
            GatewayRefused,
        )

        line = line.split("#", 1)[0].strip()
        if not line:
            return None, False
        parts = line.split()
        cmd_id = ""
        if parts[0].isdigit():
            cmd_id = parts[0]
            parts = parts[1:]
        if not parts:
            return None, False
        cmd, args = parts[0], parts[1:]
        fn = getattr(self, f"cmd_{cmd}", None)
        if fn is None:
            return f"?{cmd_id} unknown command\n\n", False
        try:
            result = fn(args)
        except GatewayRefused as e:
            retry = ("" if e.retry_after_s is None
                     else f", retry in {e.retry_after_s}s")
            return f"?{cmd_id} gateway {e.code}{retry}\n\n", False
        except GatewayClosed as e:
            # the wire is gone: report once and end the session
            return f"?{cmd_id} gateway connection lost: {e}\n\n", True
        except Exception as e:  # noqa: BLE001 — GTP reports all errors
            return f"?{cmd_id} {e}\n\n", False
        sep = " " if result else ""
        return f"={cmd_id}{sep}{result}\n\n", cmd == "quit"


def run_bridge(bridge, instream=None, outstream=None):
    """Blocking GTP loop over a :class:`GatewayBridge` (the
    ``--connect`` path of :func:`main`)."""
    instream = instream or sys.stdin
    outstream = outstream or sys.stdout
    for line in instream:
        reply, done = bridge.handle(line)
        if reply is not None:
            outstream.write(reply)
            outstream.flush()
        if done:
            break
    return bridge


def make_player(args):
    """Build the requested agent from saved model specs."""
    from rocalphago_tpu.search.players import build_player

    try:
        return build_player(args.player, args.policy, args.value,
                            args.rollout, temperature=args.temperature,
                            playouts=args.playouts,
                            leaf_batch=args.leaf_batch,
                            lmbda=args.lmbda, symmetric=args.symmetric,
                            device_rollout=args.device_rollout)
    except ValueError as e:
        raise SystemExit(str(e))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="GTP engine (GoGui/KGS-compatible) over the "
                    "framework's players")
    ap.add_argument("--policy",
                    help="policy model JSON spec (required unless "
                         "--connect)")
    ap.add_argument("--connect", metavar="HOST:PORT", default=None,
                    help="bridge GTP to a network play gateway "
                         "(docs/GATEWAY.md) instead of loading "
                         "models locally; a gateway shed is a clean "
                         "GTP error with the retry hint")
    ap.add_argument("--value", help="value model JSON spec "
                                    "(for mcts / device-mcts)")
    ap.add_argument("--rollout", help="rollout model JSON spec")
    ap.add_argument("--player", default="greedy",
                    choices=("greedy", "probabilistic", "mcts",
                             "device-mcts", "gumbel-mcts"))
    ap.add_argument("--temperature", type=float, default=0.1)
    ap.add_argument("--lmbda", type=float, default=0.5)
    ap.add_argument("--playouts", type=int, default=100)
    ap.add_argument("--leaf-batch", type=int, default=8)
    ap.add_argument("--symmetric", action="store_true",
                    help="ensemble evals over the 8 board symmetries")
    ap.add_argument("--device-rollout", action="store_true",
                    help="mcts rollouts as one on-device scan per "
                         "wave instead of host rules")
    ap.add_argument("--metrics", default=None,
                    help="JSONL path for degradation/stall events "
                         "(the serving metrics.jsonl)")
    ap.add_argument("--genmove-timeout", type=float, default=None,
                    help="abandon a silent search after this many "
                         "seconds and degrade to the policy rung "
                         "(watchdog hang protection; default off)")
    ap.add_argument("--no-resilient", action="store_true",
                    help="raw legacy serving: player exceptions "
                         "become ? error replies (forfeits under "
                         "most controllers)")
    ap.add_argument("--serve", action="store_true",
                    help="serve-backed player: this engine's game is "
                         "one session of a rocalphago_tpu.serve pool "
                         "(shared batching evaluator, admission "
                         "control, pool stats on the probes); needs "
                         "--value")
    ap.add_argument("--serve-slo-ms", type=float, default=None,
                    help="per-genmove SLO for the serve pool in ms "
                         "(anytime answer on expiry; default "
                         "ROCALPHAGO_SERVE_SLO_MS / off)")
    ap.add_argument("--serve-sizes", default=None,
                    help="comma list of board sizes to serve from ONE "
                         "multi-size pool (e.g. 9,13,19; implies "
                         "--serve, needs FCN-head models — the GTP "
                         "boardsize command then re-routes the "
                         "session instead of erroring; "
                         "docs/MULTISIZE.md)")
    a = ap.parse_args(argv)
    if a.connect:
        # the bridge path: no models, no devices — just the wire.
        # The resilient client follows router spillover and replica
        # drains transparently (reconnect + replay, backoff honoring
        # retry_after_s) — a mid-game drain re-lands the game on
        # another replica instead of ending the GTP session
        from rocalphago_tpu.gateway.client import (
            GatewayRefused,
            ResilientGatewayClient,
        )

        host, _, port = a.connect.rpartition(":")
        if not host or not port.isdigit():
            ap.error("--connect wants HOST:PORT")
        try:
            client = ResilientGatewayClient(host, int(port))
        except GatewayRefused as e:
            retry = ("" if e.retry_after_s is None
                     else f" (retry in {e.retry_after_s}s)")
            raise SystemExit(f"gateway refused: {e}{retry}")
        except OSError as e:
            raise SystemExit(f"cannot reach gateway "
                             f"{a.connect}: {e}")
        try:
            run_bridge(GatewayBridge(client))
        finally:
            client.close()
        return
    if not a.policy:
        ap.error("--policy is required (unless --connect)")
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    # a restarted GTP engine replays the same compiles every launch —
    # the persistent cache turns those into loads
    enable_compile_cache()
    metrics = None
    if a.metrics:
        from rocalphago_tpu.io.metrics import MetricsLogger

        metrics = MetricsLogger(a.metrics, echo=False)
        # genmove spans + compile events join the serving metrics
        trace.configure(metrics)
    pool = None
    session = None
    if a.serve or a.serve_sizes:
        from rocalphago_tpu.models.nn_util import NeuralNetBase

        if not a.value:
            raise SystemExit("--serve needs a --value model")
        policy = NeuralNetBase.load_model(a.policy)
        value = NeuralNetBase.load_model(a.value)
        slo_s = (a.serve_slo_ms / 1e3
                 if a.serve_slo_ms is not None else None)
        if a.serve_sizes:
            from rocalphago_tpu.multisize import MultiSizePool

            sizes = tuple(int(s) for s in a.serve_sizes.split(",")
                          if s.strip())
            pool = MultiSizePool(
                value, policy, sizes=sizes, n_sim=a.playouts,
                metrics=metrics, hang_timeout_s=a.genmove_timeout,
                slo_s=slo_s)
        else:
            from rocalphago_tpu.serve.sessions import ServePool

            pool = ServePool(
                value, policy, n_sim=a.playouts, metrics=metrics,
                hang_timeout_s=a.genmove_timeout, slo_s=slo_s)
        pool.warm()
        # the session arrives ladder-wrapped; the engine adopts it
        session = pool.open_session(resilient=not a.no_resilient)
        player = session.player
    else:
        player = make_player(a)
    try:
        run_gtp(player, metrics=metrics,
                resilient=not a.no_resilient,
                hang_timeout_s=a.genmove_timeout,
                serve_pool=pool, serve_session=session)
    finally:
        if pool is not None:
            pool.close()
        # end-of-session registry snapshot (same idiom as the
        # trainers): obs_report's encode/dispatch sections read their
        # histograms from this event, so a serving run's metrics file
        # is reportable too — not just queryable live via
        # rocalphago-stats
        from rocalphago_tpu.obs import registry as obs_registry

        obs_registry.log_to(metrics)


if __name__ == "__main__":
    main(sys.argv[1:])
