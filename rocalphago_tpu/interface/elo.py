"""Elo ratings from tournament game logs (Bradley–Terry MLE).

The reference evaluates agents by head-to-head win rates only (its
eval configurations pit SL vs RL vs MCTS; SURVEY.md §7 step 6); the
AlphaGo paper reports strengths on the Elo scale. This closes the gap:
feed it one or more JSONL logs written by
``rocalphago_tpu.interface.tournament --log`` (lines of
``{"game": n, "black": name, "white": name, "winner": name|"draw"}``)
and it fits a Bradley–Terry model by minorization–maximization and
reports ratings in Elo points.

Conventions:
- a draw counts as half a win for each player (the standard reduction;
  Go draws only occur at integer komi or move-limit adjournments);
- ratings are translation-invariant, so they are anchored: the
  ``--anchor`` player (default: alphabetically first) is pinned to
  ``--anchor-elo`` (default 0);
- players connected by no game path to the anchor cannot be placed on
  the same scale — they are reported with ``"elo": null`` rather than
  a fabricated number.

CLI:
    python -m rocalphago_tpu.interface.elo games1.jsonl games2.jsonl \
        [--anchor NAME] [--anchor-elo E] [--bootstrap N]

``--bootstrap N`` adds percentile-bootstrap 95% rating intervals from
N game resamples — small-sample Elo is noisy, and the tool says so
with numbers.
"""

from __future__ import annotations

import argparse
import collections
import json
import math
import sys


def wilson_lower_bound(wins: float, n: int, z: float = 1.96) -> float:
    """Lower edge of the Wilson score interval for a binomial win
    rate: the smallest true rate plausibly consistent (at confidence
    ``z``; default 95%) with ``wins`` observed wins in ``n`` decided
    games. The zero-loop's evaluator gate promotes only when this
    bound clears 0.5 (``training/zero.py``; VERDICT r5 next-round #4:
    a 64-game 0.59 point estimate has a ~±0.12 CI — promotions on
    such margins were coin flips). ``n <= 0`` returns 0.0 (no
    evidence, no promotion). Fractional wins (draw = half) are fine.
    """
    if n <= 0:
        return 0.0
    p = min(max(wins / n, 0.0), 1.0)
    z2 = z * z
    center = p + z2 / (2.0 * n)
    margin = z * math.sqrt(p * (1.0 - p) / n + z2 / (4.0 * n * n))
    return max(0.0, (center - margin) / (1.0 + z2 / n))


def read_games(paths) -> list[dict]:
    """Parse tournament JSONL logs; skips malformed lines."""
    games = []
    for path in paths:
        try:
            f = open(path)
        except OSError as e:
            raise SystemExit(f"cannot read game log {path}: {e}")
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    g = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(g, dict)
                        and {"black", "white", "winner"} <= g.keys()):
                    games.append(g)
    return games


def pair_counts(games):
    """-> (wins[a][b] = fractional wins of a over b, players set)."""
    wins: dict = collections.defaultdict(lambda: collections.defaultdict(float))
    players: set = set()
    for g in games:
        b, w, won = g["black"], g["white"], g["winner"]
        players.update((b, w))
        if won == "draw":
            wins[b][w] += 0.5
            wins[w][b] += 0.5
        elif won in (b, w):
            loser = w if won == b else b
            wins[won][loser] += 1.0
    return wins, players


def _components(players, wins):
    """Connected components of the played-against graph."""
    adj = collections.defaultdict(set)
    for a in wins:
        for b in wins[a]:
            adj[a].add(b)
            adj[b].add(a)
    seen, comps = set(), []
    for p in sorted(players):
        if p in seen:
            continue
        comp, stack = set(), [p]
        while stack:
            q = stack.pop()
            if q in comp:
                continue
            comp.add(q)
            stack.extend(adj[q] - comp)
        seen |= comp
        comps.append(comp)
    return comps


def bradley_terry(players, wins, iters: int = 200,
                  tol: float = 1e-10) -> dict:
    """MM fit of BT strengths p_i (Hunter 2004); -> {player: p}.

    Each player's strength update is
        p_i <- W_i / sum_j n_ij / (p_i + p_j)
    where W_i is i's total (fractional) wins and n_ij the games played
    between i and j. A player with zero wins (or zero losses) has no
    finite MLE; a half-game virtual draw against every opponent played
    regularizes (standard practice, keeps orderings).
    """
    players = sorted(players)
    n = collections.defaultdict(float)
    for a in wins:
        for b, w in wins[a].items():
            n[(a, b)] += w
            n[(b, a)] += w
    reg_wins = collections.defaultdict(float)
    opponents = collections.defaultdict(set)
    for (a, b), cnt in list(n.items()):
        if cnt > 0:
            opponents[a].add(b)
    for a in players:
        for b in opponents[a]:
            reg_wins[a] += wins[a][b] + 0.25   # + virtual half-draw
            n[(a, b)] = wins[a][b] + wins[b][a] + 0.5

    p = {a: 1.0 for a in players}
    for _ in range(iters):
        delta = 0.0
        for a in players:
            if not opponents[a]:
                continue
            denom = sum(n[(a, b)] / (p[a] + p[b])
                        for b in opponents[a])
            new = reg_wins[a] / denom if denom > 0 else p[a]
            delta = max(delta, abs(new - p[a]))
            p[a] = new
        # renormalize (geometric mean 1) for numeric stability
        logs = [math.log(v) for v in p.values() if v > 0]
        shift = math.exp(sum(logs) / len(logs)) if logs else 1.0
        for a in p:
            p[a] /= shift
        if delta < tol:
            break
    return p


def elo_table(games, anchor: str | None = None,
              anchor_elo: float = 0.0) -> dict:
    """games -> {"players": {name: {elo, games, wins, losses, draws}},
    "anchor": name}. Elo = 400·log10(p) shifted so anchor lands on
    ``anchor_elo``; players not connected to the anchor get null."""
    wins, players = pair_counts(games)
    if not players:
        return {"players": {}, "anchor": None}
    if anchor is not None and anchor not in players:
        # a typo'd anchor silently re-anchoring the whole table is
        # worse than an error
        raise ValueError(f"anchor {anchor!r} appears in no game; "
                         f"players: {sorted(players)}")
    p = bradley_terry(players, wins)
    anchor = anchor if anchor is not None else sorted(players)[0]
    comps = _components(players, wins)
    anchored = next(c for c in comps if anchor in c)

    raw = {a: 400.0 * math.log10(v) if v > 0 else None
           for a, v in p.items()}
    shift = anchor_elo - raw[anchor] if raw[anchor] is not None else 0.0

    tally = collections.defaultdict(lambda: [0, 0, 0])  # w, l, d
    for g in games:
        b, w, won = g["black"], g["white"], g["winner"]
        if won == "draw":
            tally[b][2] += 1
            tally[w][2] += 1
        elif won in (b, w):
            loser = w if won == b else b
            tally[won][0] += 1
            tally[loser][1] += 1

    out = {}
    for a in sorted(players):
        elo = (round(raw[a] + shift, 1)
               if a in anchored and raw[a] is not None else None)
        out[a] = {"elo": elo, "games": sum(tally[a]),
                  "wins": tally[a][0], "losses": tally[a][1],
                  "draws": tally[a][2]}
    return {"players": out, "anchor": anchor}


def bootstrap_ci(games, anchor=None, anchor_elo: float = 0.0,
                 n_boot: int = 200, seed: int = 0,
                 pct: tuple = (2.5, 97.5)) -> dict:
    """Percentile bootstrap over games: ``{player: [lo, hi] | None}``.

    Resamples the game list with replacement ``n_boot`` times and
    refits; a player whose rating is null (disconnected from the
    anchor) in any resample — or who drops out of a resample entirely
    — contributes no sample there, and gets null bounds if fewer than
    half the COMPLETED resamples (those whose table fit — resamples
    that drop the anchor entirely are skipped and don't count) rate
    them. Small-sample Elo is NOISY; the
    point of this is to say so with numbers."""
    import random

    rng = random.Random(seed)
    # resolve the anchor ONCE from the full game set: with
    # anchor=None each resample would otherwise pick its own
    # alphabetically-first player, mixing rating scales across
    # resamples and corrupting the intervals
    _, players = pair_counts(games)
    if anchor is None and players:
        anchor = sorted(players)[0]
    samples: dict = {}
    completed = 0   # resamples whose table fit — the null-CI
    for _ in range(n_boot):     # threshold denominator (advisor r3:
        # skipped resamples must not count against always-rated
        # players on sparse logs)
        resample = rng.choices(games, k=len(games))
        try:
            t = elo_table(resample, anchor, anchor_elo)
        except ValueError:      # anchor absent from this resample
            continue
        completed += 1
        for name, row in t["players"].items():
            if row["elo"] is not None:
                samples.setdefault(name, []).append(row["elo"])

    def pick(vals, q):
        vals = sorted(vals)
        i = q / 100.0 * (len(vals) - 1)
        lo, hi = int(math.floor(i)), int(math.ceil(i))
        return vals[lo] + (vals[hi] - vals[lo]) * (i - lo)

    out = {}
    # the honest-interval floor scales down with the REQUEST
    # (ADVICE r4): a smoke-test n_boot=5 where all 5 resamples
    # complete should yield (noisy) bounds, not silent nulls — the
    # floor only nulls when resamples were LOST to anchor dropout
    floor = min(10, n_boot)
    for name, vals in samples.items():
        # below the floor: too few surviving resamples for ANY honest
        # interval — a "95% CI" from 1-2 points would carry the same
        # authority as a real one
        if completed < floor or len(vals) < completed / 2:
            out[name] = None
        else:
            out[name] = [round(pick(vals, pct[0]), 1),
                         round(pick(vals, pct[1]), 1)]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Elo ratings from tournament JSONL logs")
    ap.add_argument("logs", nargs="+", help="tournament --log files")
    ap.add_argument("--anchor", default=None,
                    help="player pinned to --anchor-elo "
                         "(default: alphabetically first)")
    ap.add_argument("--anchor-elo", type=float, default=0.0)
    ap.add_argument("--bootstrap", type=int, default=0, metavar="N",
                    help="add [2.5%%, 97.5%%] percentile-bootstrap "
                         "rating intervals from N game resamples")
    a = ap.parse_args(argv)
    games = read_games(a.logs)
    try:
        table = elo_table(games, a.anchor, a.anchor_elo)
        if a.bootstrap and games:
            ci = bootstrap_ci(games, a.anchor, a.anchor_elo,
                              n_boot=a.bootstrap)
            for name, row in table["players"].items():
                row["elo_ci95"] = ci.get(name)
    except ValueError as e:
        raise SystemExit(str(e))
    print(json.dumps(table, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
