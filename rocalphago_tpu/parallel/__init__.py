"""Device topology + shardings — the communication backend
(SURVEY.md §2c). XLA collectives over ICI/DCN; no hand-written comms."""

from rocalphago_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    distributed_init,
    global_batch_size,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
