"""Device topology + named shardings — the rebuild's entire "comm backend".

The reference has no distributed layer at all (single process, single
Theano device; SURVEY.md §2c). The TPU rebuild's communication backend
is exactly this module: construct one `jax.sharding.Mesh` over the
slice, name the axes, and hand out `NamedSharding`s. XLA inserts the
collectives (gradient `psum` over ICI for data-parallel training,
DCN across hosts once `jax.distributed` is initialized) — there is no
hand-written NCCL/MPI analogue to port.

Axis convention:
  * ``data``  — batch / self-play game axis (the only axis the AlphaGo
    workload needs; SURVEY.md §2b).
  * ``model`` — reserved tensor-parallel axis, size 1 by default. The
    nets are small enough that TP is never profitable, but keeping the
    axis in the mesh means evaluator/trainer code is already written
    against a 2-D mesh if someone shards a bigger trunk later.
"""

from __future__ import annotations

import functools
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def cpu_collectives_available() -> bool:
    """Whether this jaxlib ships gloo TCP collectives for the CPU
    backend. Without them a multi-process CPU bring-up constructs a
    client whose collectives raise ``Multiprocess computations aren't
    implemented on the CPU backend`` at the first cross-process op —
    the capability the CPU DCN test keys its skip on."""
    try:
        import jaxlib.xla_extension as _xe

        return hasattr(_xe, "make_gloo_tcp_collectives")
    except Exception:  # noqa: BLE001 — capability probe must not raise
        return False


def distributed_init(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Multi-host bring-up (DCN). No-op for single-process runs.

    On Cloud TPU pods the arguments are auto-detected from the
    environment; pass them explicitly elsewhere. Multi-process CPU
    runs (the localhost DCN test, CPU-only actor fleets) need a real
    collectives transport — the default CPU client has none and fails
    at the first cross-process op — so gloo is selected here whenever
    the installed jaxlib ships it.
    """
    multiproc = (num_processes is not None and num_processes > 1
                 or coordinator is not None
                 or int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1)
    if not multiproc:
        return
    if cpu_collectives_available():
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax without the knob
            pass
    if num_processes is not None and num_processes > 1 or (
            coordinator is not None):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    else:
        jax.distributed.initialize()


def is_coordinator() -> bool:
    """True on the process that owns artifact writes — metadata.json,
    metrics.jsonl, weight exports, the persisted shuffle split. Orbax
    checkpoint saves are NOT guarded by this: every process must
    participate in a multi-host save (each holds addressable shards).
    Single-process runs are always the coordinator."""
    return jax.process_index() == 0


def make_mesh(num_devices: int | None = None,
              model_parallel: int = 1) -> Mesh:
    """A ``(data, model)`` mesh over the first ``num_devices`` devices.

    ``model_parallel`` must divide the device count; data-parallel width
    is whatever remains. With the virtual-CPU trick
    (``--xla_force_host_platform_device_count=N``) the same call builds
    an N-way test mesh on one host (SURVEY.md §4 multi-node testing).
    """
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    if n % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide {n} devices")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


@functools.lru_cache(maxsize=None)
def _cached_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def data_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard the leading (batch) axis over ``data``; trailing axes
    replicated."""
    return _cached_sharding(
        mesh, P(DATA_AXIS, *(None,) * (rank - 1)))


def replicated(mesh: Mesh) -> NamedSharding:
    return _cached_sharding(mesh, P())


def axis_sharding(mesh: Mesh, axis: int) -> NamedSharding:
    """Shard dimension ``axis`` over ``data``, all other dimensions
    replicated — e.g. ``axis=1`` for time-major ``[T, B, ...]`` game
    histories (the zero replay layout, docs/SCALE.md). The spec is a
    valid pytree-prefix/partial spec: trailing dimensions beyond
    ``axis`` are implicitly replicated."""
    return _cached_sharding(mesh, P(*(None,) * axis, DATA_AXIS))


def shard_batch(mesh: Mesh, batch):
    """Place a host pytree of arrays with leading batch axes onto the
    mesh, batch axis split over ``data``."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, data_sharding(mesh, np.ndim(x) or 1)), batch)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (params, opt state) across every device."""
    return jax.device_put(tree, replicated(mesh))


def global_batch_size(mesh: Mesh, per_device: int) -> int:
    return per_device * mesh.shape[DATA_AXIS]
