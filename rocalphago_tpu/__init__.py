"""rocalphago_tpu — a TPU-native rebuild of the RocAlphaGo AlphaGo pipeline.

A complete, from-scratch JAX/XLA framework with the capability surface of
the reference (``vaporized/RocAlphaGo``): a Go rules engine, the 48-plane
AlphaGo feature encoder, policy/value/rollout convnets, supervised /
REINFORCE / value trainers, batched APV-MCTS, SGF data pipeline and a GTP
interface — redesigned TPU-first:

* the game engine is a pure-functional JAX program (``engine.jaxgo``):
  state is a pytree of fixed-shape arrays, ``step`` is jittable and
  ``vmap``-able over thousands of concurrent boards;
* the feature encoder runs on device with no per-cell Python
  (``features``), using dense liberty-set bitmaps instead of per-move
  board simulation;
* networks are Flax modules in NHWC bfloat16-friendly layout (``models``);
* trainers are data-parallel over a ``jax.sharding.Mesh`` with gradients
  ``psum``-reduced over ICI (``training``, ``parallel``);
* MCTS batches leaf evaluation through a single jitted policy+value
  evaluator (``search``).

Layer map parity with the reference is documented per-module; see
SURVEY.md at the repo root for the blueprint. The reference mount was
empty this round, so citations are at file/symbol granularity
(e.g. ``AlphaGo/go.py::GameState``) per SURVEY.md's provenance protocol.
"""

__version__ = "0.1.0"
