"""The gateway socket server: one connection ↔ one pool session.

A threaded stdlib server over a :class:`~rocalphago_tpu.serve.
sessions.ServePool` (or :class:`~rocalphago_tpu.multisize.pool.
MultiSizePool` — ``new_game``'s ``board`` then routes to the member
pool of that size). Each accepted connection gets a handler thread, a
ladder-wrapped session (admission-controlled by the pool), and its
own server-side :class:`~rocalphago_tpu.engine.pygo.GameState`; the
wire stays NDJSON (:mod:`~rocalphago_tpu.gateway.protocol`).

Load shedding is STRUCTURED, never a hang: past ``max_conns`` the
accept loop answers with an ``overload`` error frame (carrying
``retry_after_s``) and closes; a pool at its session cap turns
``new_game`` into the same refusal. Every shed is counted
(``gateway_connections_total{result=}``, ``gateway_errors_total
{code=}``) so ``/metrics`` sees pressure before clients do.

Per-request SLO: ``slo_ms`` (or ``ROCALPHAGO_GATEWAY_SLO_MS``) arms a
:class:`~rocalphago_tpu.runtime.deadline.Deadline` per genmove — the
session's anytime search answers inside it, and the reply reports
whether the deadline fired.

Faults: the handler runs each request behind the ``gateway.conn``
barrier (docs/RESILIENCE.md) — an injected transient fails THAT
request with a typed ``internal`` error, an injected kill aborts the
connection; either way the session is closed, the admission slot
released, and nothing escapes the handler (the ``serve.dispatch``
-style fault wall; ``requests.unhandled`` in the probe counts any
escape, and the soak green-gates on zero).

Drain (docs/GATEWAY.md "Drain semantics"): :meth:`GatewayServer.
drain` — or SIGTERM via the supervisor in :func:`main` — stops the
accept loop, lets in-flight moves finish, nudges idle connections
with a read-side shutdown (their handlers say goodbye and close
their sessions), joins every handler within ``drain_s``, and leaves
the process free to exit 0.
"""

from __future__ import annotations

import os
import socket
import threading
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.engine import pygo
from rocalphago_tpu.gateway import protocol
from rocalphago_tpu.interface.gtp import (
    move_to_vertex,
    parse_color,
    vertex_to_move,
)
from rocalphago_tpu.interface.resilient import percentile
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.deadline import Deadline
from rocalphago_tpu.serve.admission import AdmissionError

#: cap on concurrently served connections (env override)
MAX_CONNS_ENV = "ROCALPHAGO_GATEWAY_MAX_CONNS"
#: per-genmove SLO in milliseconds ('' = off; env override)
SLO_ENV = "ROCALPHAGO_GATEWAY_SLO_MS"
#: drain grace: seconds in-flight handlers get to finish
DRAIN_ENV = "ROCALPHAGO_GATEWAY_DRAIN_S"

#: retry hint a shed/refused client receives (seconds)
RETRY_AFTER_S = 1.0

#: wire-latency samples kept for the probe's p50/p99
_LAT_KEEP = 512


def _env_float(name: str, default):
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


class _Game:
    """One live game on one connection: the pool session plus the
    server-side rules state the session's player searches from."""

    def __init__(self, session, board: int, komi: float):
        self.session = session
        self.board = board
        self.state = pygo.GameState(size=board, komi=komi)


class GatewayServer:
    """Threaded NDJSON front end over a serve pool (module docstring).

    Parameters: ``pool`` (ServePool or MultiSizePool), ``host``/
    ``port`` (0 = ephemeral), ``max_conns`` / ``slo_ms`` / ``drain_s``
    (default from their env knobs), ``metrics`` (drain-phase events
    land there for obs_report's gateway timeline).
    """

    def __init__(self, pool, host: str = "127.0.0.1", port: int = 0,
                 max_conns: int | None = None,
                 slo_ms: float | None = None,
                 drain_s: float | None = None, metrics=None):
        self.pool = pool
        self.host = host
        self._port_arg = int(port)
        self.metrics = metrics
        self.max_conns = (int(_env_float(MAX_CONNS_ENV, 64))
                          if max_conns is None else int(max_conns))
        self.slo_ms = (_env_float(SLO_ENV, None)
                       if slo_ms is None else float(slo_ms))
        self.drain_s = (_env_float(DRAIN_ENV, 10.0)
                        if drain_s is None else float(drain_s))
        self._max_frame = protocol.max_frame_bytes()
        self._lock = lockcheck.make_lock("GatewayServer._lock")
        self._conns: dict = {}       # guarded-by: self._lock
        self._live = 0               # guarded-by: self._lock
        self._next_cid = 0           # guarded-by: self._lock
        self._accepted = 0           # guarded-by: self._lock
        self._shed = 0               # guarded-by: self._lock
        self._requests = 0           # guarded-by: self._lock
        self._errors = 0             # guarded-by: self._lock
        self._genmoves = 0           # guarded-by: self._lock
        self._unhandled = 0          # guarded-by: self._lock
        self._faults = 0             # guarded-by: self._lock
        self._kills = 0              # guarded-by: self._lock
        self._draining = False       # guarded-by: self._lock
        self._lat: list = []         # guarded-by: self._lock
        self._sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._closed = False
        self._live_g = obs_registry.gauge("gateway_conns_live")
        self._acc_c = obs_registry.counter("gateway_connections_total",
                                           result="accepted")
        self._shed_c = obs_registry.counter("gateway_connections_total",
                                            result="shed")
        self._wire_h = obs_registry.histogram("gateway_wire_seconds")

    # ------------------------------------------------------ lifecycle

    def start(self) -> "GatewayServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self._port_arg))
        s.listen(128)
        # a timeout on the listener is the only portable way to wake
        # the accept loop on drain: closing a socket from another
        # thread does NOT interrupt a blocked accept() on Linux
        s.settimeout(0.2)
        self._sock = s
        t = threading.Thread(target=self._accept_loop,
                             name="gateway-accept")
        t.start()
        self._accept_thread = t
        return self

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def _emit(self, phase: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("drain", phase=phase, **fields)

    def drain(self, reason: str = "requested",
              timeout: float | None = None) -> None:
        """Graceful stop: refuse new work, finish in-flight moves,
        close every session, quiesce every thread (module docstring).
        Idempotent; bounded by ``timeout`` (default ``drain_s``)."""
        timeout = self.drain_s if timeout is None else timeout
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return
        self._emit("gateway_requested", reason=reason)
        # 1. stop accepting: closing the listener pops the accept loop
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        self._emit("gateway_accept_stopped")
        # 2. nudge idle connections: a read-side shutdown EOFs their
        # next readline; handlers finish the move in flight, say
        # goodbye on the still-open write side, close their sessions
        with self._lock:
            conns = list(self._conns.values())
        for conn, _t in conns:
            try:
                conn.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        deadline = Deadline.after(timeout)
        for _conn, t in conns:
            t.join(timeout=max(0.05, deadline.remaining() or 0.05))
        # 3. stragglers — including connections admitted just before
        # _draining was set and registered after step 2's snapshot —
        # get the read-side nudge again plus the write side cut;
        # close() alone does not wake a blocked readline on Linux, so
        # loop the SHUT_RD until _conns empties or the tail expires
        tail = Deadline.after(5.0)
        while True:
            with self._lock:
                leftover = list(self._conns.values())
            if not leftover or tail.expired():
                break
            for conn, _t in leftover:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            for _conn, t in leftover:
                t.join(timeout=max(0.05, tail.remaining() or 0.05))
        with self._lock:
            live = self._live
        self._emit("gateway_drained", live_conns=live)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.drain(reason="close")

    def __enter__(self) -> "GatewayServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- accept

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                with self._lock:
                    if self._draining:
                        return
                continue
            except OSError:
                return                 # listener closed: drain/close
            with self._lock:
                refuse = None
                if self._draining:
                    refuse = "draining"
                elif self._live >= self.max_conns:
                    refuse = "overload"
                    self._shed += 1
                else:
                    self._live += 1
                    self._accepted += 1
                    cid = self._next_cid
                    self._next_cid += 1
                self._live_g.set(self._live)
            if refuse is not None:
                if refuse == "overload":
                    self._shed_c.inc()
                self._count_error(refuse)
                self._send(conn, protocol.error_frame(
                    refuse,
                    f"gateway {refuse}: "
                    f"{self.max_conns} connections live",
                    retry_after_s=RETRY_AFTER_S))
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._acc_c.inc()
            t = threading.Thread(target=self._handle,
                                 args=(conn, cid),
                                 name=f"gateway-conn-{cid}")
            with self._lock:
                self._conns[cid] = (conn, t)
            t.start()

    # ------------------------------------------------------- handler

    def _send(self, conn, msg: dict) -> bool:
        try:
            conn.sendall(protocol.encode_frame(msg))
            return True
        except (OSError, ValueError):
            return False               # peer gone mid-reply

    def _count_error(self, code: str) -> None:
        obs_registry.counter("gateway_errors_total", code=code).inc()
        with self._lock:
            self._errors += 1

    def _handle(self, conn, cid: int) -> None:
        game = None
        reader = conn.makefile("rb")
        try:
            self._send(conn, protocol.hello_frame(
                self._boards(), self._default_board(), self.slo_ms))
            n = 0
            while True:
                with self._lock:
                    draining = self._draining
                if draining:
                    self._send(conn, {"type": "goodbye",
                                      "reason": "draining"})
                    break
                try:
                    msg = protocol.read_frame(reader, self._max_frame)
                except protocol.ProtocolError as e:
                    self._count_error(e.code)
                    self._send(conn, protocol.error_frame(
                        e.code, str(e)))
                    if e.fatal:
                        break
                    continue
                if msg is None:
                    break              # disconnect / torn frame
                n += 1
                with self._lock:
                    self._requests += 1
                rid = msg.get("id")
                # the per-request fault wall (docs/RESILIENCE.md):
                # a transient fails this request, a kill this
                # connection — never the server
                try:
                    faults.barrier("gateway.conn", iteration=n)
                except faults.InjectedKill as e:
                    with self._lock:
                        self._kills += 1
                    obs_registry.counter("gateway_faults_total",
                                         kind="kill").inc()
                    self._send(conn, protocol.error_frame(
                        "internal", f"connection aborted: {e}",
                        id=rid))
                    break
                except Exception as e:  # noqa: BLE001 — injected
                    with self._lock:
                        self._faults += 1
                    obs_registry.counter("gateway_faults_total",
                                         kind="fault").inc()
                    self._count_error("internal")
                    self._send(conn, protocol.error_frame(
                        "internal", f"transient fault: {e}", id=rid))
                    continue
                try:
                    reply, game = self._dispatch(msg, game)
                except Exception as e:  # noqa: BLE001 — fault wall:
                    #   the connection must answer, the server live on
                    with self._lock:
                        self._unhandled += 1
                    self._count_error("internal")
                    reply = protocol.error_frame(
                        "internal", f"{type(e).__name__}: {e}",
                        id=rid)
                if reply is not None and not self._send(conn, reply):
                    break
        finally:
            if game is not None:
                game.session.close()
            try:
                reader.close()     # drops the makefile's fd reference
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._conns.pop(cid, None)
                self._live = max(0, self._live - 1)
                self._live_g.set(self._live)

    # ------------------------------------------------------ dispatch

    def _dispatch(self, msg: dict, game):
        """One request → (reply frame, game). Refusals are typed
        error frames; only genuine bugs raise (counted unhandled)."""
        rid = msg.get("id")
        mtype = msg.get("type")
        obs_registry.counter("gateway_requests_total",
                             type=str(mtype)).inc()
        if mtype == "hello":
            proto = msg.get("proto", protocol.PROTO_VERSION)
            if proto != protocol.PROTO_VERSION:
                self._count_error("bad_proto")
                return protocol.error_frame(
                    "bad_proto",
                    f"server speaks proto {protocol.PROTO_VERSION}, "
                    f"client pinned {proto}", id=rid), game
            return {"type": "ok", "id": rid,
                    "proto": protocol.PROTO_VERSION}, game
        if mtype == "new_game":
            return self._new_game(msg, game)
        if mtype == "close":
            if game is not None:
                game.session.close()
            return {"type": "ok", "id": rid}, None
        if mtype in ("play", "genmove", "komi"):
            if game is None:
                self._count_error("no_game")
                return protocol.error_frame(
                    "no_game", f"{mtype} before new_game",
                    id=rid), game
            if mtype == "komi":
                try:
                    komi = float(msg.get("komi", game.state.komi))
                except (TypeError, ValueError) as e:
                    self._count_error("bad_request")
                    return protocol.error_frame(
                        "bad_request", f"unparseable komi: {e}",
                        id=rid), game
                game.session.set_komi(komi)
                game.state.komi = komi
                return {"type": "ok", "id": rid}, game
            if mtype == "play":
                return self._play(msg, game), game
            return self._genmove(msg, game), game
        self._count_error("unknown_type")
        return protocol.error_frame(
            "unknown_type", f"unknown message type {mtype!r}",
            id=rid), game

    def _boards(self) -> tuple:
        pool = self.pool
        return (tuple(pool.sizes) if hasattr(pool, "pool_for")
                else (pool.board,))

    def _default_board(self) -> int:
        pool = self.pool
        return (pool.default_size if hasattr(pool, "pool_for")
                else pool.board)

    def _new_game(self, msg: dict, game):
        rid = msg.get("id")
        # client fields parse BEFORE any side effect: a malformed
        # value is a typed refusal, never a leaked session or a
        # torn-down previous game
        try:
            board = int(msg.get("board", self._default_board()))
            komi = msg.get("komi")
            if komi is not None:
                komi = float(komi)
        except (TypeError, ValueError) as e:
            self._count_error("bad_request")
            return protocol.error_frame(
                "bad_request",
                f"unparseable new_game field: {e}", id=rid), game
        if game is not None:
            game.session.close()
            game = None
        try:
            if hasattr(self.pool, "pool_for"):
                session = self.pool.open_session(size=board)
            else:
                if board != self.pool.board:
                    raise KeyError(board)
                session = self.pool.open_session()
        except KeyError:
            self._count_error("bad_board")
            return protocol.error_frame(
                "bad_board",
                f"board {board} not served (serving "
                f"{list(self._boards())})", id=rid), None
        except AdmissionError as e:
            # the pool's AdmissionController said no: the structured
            # refusal the load balancer backs off on
            self._count_error("overload")
            self._shed_c.inc()
            with self._lock:
                self._shed += 1
            return protocol.error_frame(
                "overload", str(e), id=rid,
                retry_after_s=RETRY_AFTER_S), None
        try:
            if komi is not None:
                session.set_komi(komi)
            eff_komi = komi if komi is not None \
                else float(session.raw.pool.cfg.komi)
            game = _Game(session, board, eff_komi)
        except BaseException:
            # the admission slot must come back even on a genuine
            # bug — a raise between open and _Game would otherwise
            # strand the session until restart
            session.close()
            raise
        return {"type": "ok", "id": rid, "board": board,
                "komi": eff_komi}, game

    def _play(self, msg: dict, game) -> dict:
        rid = msg.get("id")
        state = game.state
        prev = state.current_player
        try:
            color = parse_color(str(msg.get("color", "")))
            move = vertex_to_move(str(msg.get("move", "")),
                                  game.board)
            state.current_player = color
            if state.is_end_of_game:
                raise _GameOver()
            if move is not None and not state.is_legal(move):
                raise ValueError("illegal move")
            state.do_move(move, color)
        except _GameOver:
            state.current_player = prev
            self._count_error("game_over")
            return protocol.error_frame(
                "game_over", "the game has ended", id=rid)
        except Exception as e:  # noqa: BLE001 — refusal, state intact
            state.current_player = prev
            self._count_error("illegal_move")
            return protocol.error_frame("illegal_move", str(e),
                                        id=rid)
        return {"type": "ok", "id": rid}

    def _genmove(self, msg: dict, game) -> dict:
        rid = msg.get("id")
        state = game.state
        if state.is_end_of_game:
            self._count_error("game_over")
            return protocol.error_frame(
                "game_over", "the game has ended", id=rid)
        try:
            color = parse_color(str(msg.get("color", "")))
        except ValueError as e:
            self._count_error("bad_request")
            return protocol.error_frame("bad_request", str(e),
                                        id=rid)
        prev = state.current_player
        state.current_player = color
        # per-request SLO: the deadline arms inside the session's
        # anytime search (min of this and the pool's own SLO)
        slo_s = None if self.slo_ms is None else self.slo_ms / 1e3
        deadline = Deadline.after(slo_s)
        game.session.raw.set_move_time(slo_s)
        t0 = time.monotonic()
        try:
            move = game.session.get_move(state)
            if move is not None and not state.is_legal(move):
                move = None            # final guard, like the engine
            state.do_move(move, color)
        except Exception:
            state.current_player = prev
            raise
        dt = time.monotonic() - t0
        self._wire_h.observe(dt)
        with self._lock:
            self._genmoves += 1
            self._lat.append(dt)
            if len(self._lat) > _LAT_KEEP:
                del self._lat[: len(self._lat) - _LAT_KEEP]
        return {"type": "move", "id": rid,
                "move": move_to_vertex(move, game.board),
                "elapsed_ms": round(dt * 1e3, 3),
                "slo_hit": bool(not deadline.unlimited
                                and deadline.expired()),
                "rung": getattr(game.session.player, "last_rung",
                                None)}

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``gateway`` block (schema: docs/GATEWAY.md —
        the ``gateway-probe-drift`` lint rule diffs this literal
        against the documented schema both ways)."""
        with self._lock:
            live = self._live
            accepted = self._accepted
            shed = self._shed
            requests = self._requests
            errors = self._errors
            genmoves = self._genmoves
            unhandled = self._unhandled
            injected = self._faults
            kills = self._kills
            draining = self._draining
            lat = sorted(self._lat)
        p50 = percentile(lat, 0.5)
        p99 = percentile(lat, 0.99)
        return {
            "proto": protocol.PROTO_VERSION,
            "draining": draining,
            "conns": {
                "live": live,
                "max": self.max_conns,
                "accepted": accepted,
                "shed": shed,
            },
            "requests": {
                "total": requests,
                "errors": errors,
                "genmoves": genmoves,
                "unhandled": unhandled,
            },
            "faults": {
                "injected": injected,
                "kills": kills,
            },
            "wire_ms": {
                "p50": None if p50 is None else round(p50 * 1e3, 3),
                "p99": None if p99 is None else round(p99 * 1e3, 3),
            },
            "slo_ms": self.slo_ms,
            "drain_s": self.drain_s,
            "boards": list(self._boards()),
            "default_board": self._default_board(),
        }


class _GameOver(Exception):
    """Internal: a move was requested after the game ended."""


def main(argv=None) -> int:
    """Launch a gateway over saved models and serve until SIGTERM
    (the supervisor's drain — stop accepting, finish in-flight
    moves, close sessions, exit 0) or Ctrl-C."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Network play gateway over a serve pool "
                    "(docs/GATEWAY.md)")
    ap.add_argument("--policy", required=True,
                    help="policy model JSON spec")
    ap.add_argument("--value", required=True,
                    help="value model JSON spec")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9462)
    ap.add_argument("--http-port", type=int, default=9463,
                    help="/healthz + /metrics port (0 disables)")
    ap.add_argument("--playouts", type=int, default=100)
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="per-genmove SLO (default "
                         "ROCALPHAGO_GATEWAY_SLO_MS / off)")
    ap.add_argument("--max-conns", type=int, default=None,
                    help="connection cap (default "
                         "ROCALPHAGO_GATEWAY_MAX_CONNS / 64)")
    ap.add_argument("--sizes", default=None,
                    help="comma list of board sizes for a multi-size "
                         "pool (needs FCN heads; docs/MULTISIZE.md)")
    ap.add_argument("--metrics", default=None,
                    help="JSONL path for drain/degradation events")
    a = ap.parse_args(argv)

    from rocalphago_tpu.gateway.httpapi import GatewayHTTP
    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache
    from rocalphago_tpu.runtime.supervisor import Supervisor

    enable_compile_cache()
    metrics = None
    if a.metrics:
        from rocalphago_tpu.io.metrics import MetricsLogger

        metrics = MetricsLogger(a.metrics, echo=False)
    policy = NeuralNetBase.load_model(a.policy)
    value = NeuralNetBase.load_model(a.value)
    if a.sizes:
        from rocalphago_tpu.multisize import MultiSizePool

        sizes = tuple(int(s) for s in a.sizes.split(",") if s.strip())
        pool = MultiSizePool(value, policy, sizes=sizes,
                             n_sim=a.playouts, metrics=metrics)
    else:
        from rocalphago_tpu.serve.sessions import ServePool

        pool = ServePool(value, policy, n_sim=a.playouts,
                         metrics=metrics)
    pool.warm()
    server = GatewayServer(pool, host=a.host, port=a.port,
                           max_conns=a.max_conns, slo_ms=a.slo_ms,
                           metrics=metrics).start()
    http = None
    if a.http_port:
        http = GatewayHTTP(server, host=a.host,
                           port=a.http_port).start()
    sup = Supervisor(metrics=metrics)
    sup.install_sigterm()
    print(f"gateway: serving on {a.host}:{server.port} "
          f"(http {'off' if http is None else http.port})")
    try:
        while not sup.draining:
            time.sleep(0.2)
    except KeyboardInterrupt:
        sup.request_drain(reason="keyboard")
    server.drain(reason="sigterm")
    if http is not None:
        http.close()
    pool.close()
    if metrics is not None:
        obs_registry.log_to(metrics)
        metrics.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
