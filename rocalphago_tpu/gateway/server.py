"""The gateway socket server: one connection ↔ one pool session.

A threaded stdlib server over a :class:`~rocalphago_tpu.serve.
sessions.ServePool` (or :class:`~rocalphago_tpu.multisize.pool.
MultiSizePool` — ``new_game``'s ``board`` then routes to the member
pool of that size). Each accepted connection gets a handler thread, a
ladder-wrapped session (admission-controlled by the pool), and its
own server-side :class:`~rocalphago_tpu.engine.pygo.GameState`; the
wire stays NDJSON (:mod:`~rocalphago_tpu.gateway.protocol`).

Load shedding is STRUCTURED, never a hang: past ``max_conns`` the
accept loop answers with an ``overload`` error frame (carrying
``retry_after_s``) and closes; a pool at its session cap turns
``new_game`` into the same refusal. Every shed is counted
(``gateway_connections_total{result=}``, ``gateway_errors_total
{code=}``) so ``/metrics`` sees pressure before clients do.

Per-request SLO: ``slo_ms`` (or ``ROCALPHAGO_GATEWAY_SLO_MS``) arms a
:class:`~rocalphago_tpu.runtime.deadline.Deadline` per genmove — the
session's anytime search answers inside it, and the reply reports
whether the deadline fired.

Faults: the handler runs each request behind the ``gateway.conn``
barrier (docs/RESILIENCE.md) — an injected transient fails THAT
request with a typed ``internal`` error, an injected kill aborts the
connection; either way the session is closed, the admission slot
released, and nothing escapes the handler (the ``serve.dispatch``
-style fault wall; ``requests.unhandled`` in the probe counts any
escape, and the soak green-gates on zero).

Drain (docs/GATEWAY.md "Drain semantics"): :meth:`GatewayServer.
drain` — or SIGTERM via the supervisor in :func:`main` — stops the
accept loop, lets in-flight moves finish, nudges idle connections
with a read-side shutdown (their handlers say goodbye and close
their sessions), joins every handler within ``drain_s``, and leaves
the process free to exit 0.

The accept loop, admission refusals, connection registry and the
three-step drain are the shared :class:`~rocalphago_tpu.net.server
.LineServerCore` (composed — the same machinery the replay service
runs); this module keeps the gateway-specific parts: session
mapping, dispatch, the per-request SLO and the probe.
"""

from __future__ import annotations

import os
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.engine import pygo
from rocalphago_tpu.gateway import protocol
from rocalphago_tpu.interface.gtp import (
    move_to_vertex,
    parse_color,
    vertex_to_move,
)
from rocalphago_tpu.interface.resilient import percentile
from rocalphago_tpu.net.server import LineServerCore
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.deadline import Deadline
from rocalphago_tpu.serve.admission import AdmissionError

#: cap on concurrently served connections (env override)
MAX_CONNS_ENV = "ROCALPHAGO_GATEWAY_MAX_CONNS"
#: per-genmove SLO in milliseconds ('' = off; env override)
SLO_ENV = "ROCALPHAGO_GATEWAY_SLO_MS"
#: drain grace: seconds in-flight handlers get to finish
DRAIN_ENV = "ROCALPHAGO_GATEWAY_DRAIN_S"

#: retry hint a shed/refused client receives (seconds)
RETRY_AFTER_S = 1.0

#: wire-latency samples kept for the probe's p50/p99
_LAT_KEEP = 512


def _env_float(name: str, default):
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


class _Game:
    """One live game on one connection: the pool session plus the
    server-side rules state the session's player searches from."""

    def __init__(self, session, board: int, komi: float,
                 arm: str | None = None):
        self.session = session
        self.board = board
        self.state = pygo.GameState(size=board, komi=komi)
        #: canary arm ("candidate"/"incumbent") when a controller is
        #: routing; None otherwise
        self.arm = arm
        #: colors THIS connection genmoved — an outcome only counts
        #: for the canary when exactly one side was served here
        self.served: set = set()
        self.finished = False


class GatewayServer:
    """Threaded NDJSON front end over a serve pool (module docstring).

    Parameters: ``pool`` (ServePool or MultiSizePool), ``host``/
    ``port`` (0 = ephemeral), ``max_conns`` / ``slo_ms`` / ``drain_s``
    (default from their env knobs), ``metrics`` (drain-phase events
    land there for obs_report's gateway timeline).
    """

    def __init__(self, pool, host: str = "127.0.0.1", port: int = 0,
                 max_conns: int | None = None,
                 slo_ms: float | None = None,
                 drain_s: float | None = None, metrics=None,
                 canary=None):
        self.pool = pool
        self.host = host
        self._port_arg = int(port)
        self.metrics = metrics
        #: optional CanaryController routing a slice of new sessions
        #: to a staged candidate version (docs/ROLLOUT.md)
        self.canary = canary
        self.max_conns = (int(_env_float(MAX_CONNS_ENV, 64))
                          if max_conns is None else int(max_conns))
        self.slo_ms = (_env_float(SLO_ENV, None)
                       if slo_ms is None else float(slo_ms))
        self.drain_s = (_env_float(DRAIN_ENV, 10.0)
                        if drain_s is None else float(drain_s))
        self._max_frame = protocol.max_frame_bytes()
        self._lock = lockcheck.make_lock("GatewayServer._lock")
        self._shed = 0               # guarded-by: self._lock
        self._requests = 0           # guarded-by: self._lock
        self._errors = 0             # guarded-by: self._lock
        self._genmoves = 0           # guarded-by: self._lock
        self._unhandled = 0          # guarded-by: self._lock
        self._faults = 0             # guarded-by: self._lock
        self._kills = 0              # guarded-by: self._lock
        self._lat: list = []         # guarded-by: self._lock
        self._closed = False
        self._live_g = obs_registry.gauge("gateway_conns_live")
        self._acc_c = obs_registry.counter("gateway_connections_total",
                                           result="accepted")
        self._shed_c = obs_registry.counter("gateway_connections_total",
                                            result="shed")
        self._wire_h = obs_registry.histogram("gateway_wire_seconds")
        # accept/admission/registry/drain: the shared wire core
        # (docs/GATEWAY.md semantics, byte-identical refusals)
        self._core = LineServerCore(
            host=host, port=port, max_conns=self.max_conns,
            drain_s=self.drain_s, handler=self._handle,
            refusal=self._refusal_frame, name="gateway",
            metrics=metrics, live_gauge=self._live_g,
            accepted_counter=self._acc_c, shed_counter=self._shed_c)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "GatewayServer":
        self._core.start()
        return self

    @property
    def port(self) -> int:
        return self._core.port

    @property
    def draining(self) -> bool:
        return self._core.draining

    def drain(self, reason: str = "requested",
              timeout: float | None = None) -> None:
        """Graceful stop: refuse new work, finish in-flight moves,
        close every session, quiesce every thread (module docstring).
        Idempotent; bounded by ``timeout`` (default ``drain_s``)."""
        self._core.drain(reason=reason, timeout=timeout)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.drain(reason="close")

    def __enter__(self) -> "GatewayServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- handler

    def _refusal_frame(self, code: str) -> dict:
        """At-accept shed (``overload``/``draining``): the typed
        refusal the core sends before closing the connection."""
        self._count_error(code)
        return protocol.error_frame(
            code,
            f"gateway {code}: {self.max_conns} connections live",
            retry_after_s=RETRY_AFTER_S)

    def _send(self, conn, msg: dict) -> bool:
        return self._core.send(conn, msg)

    def _count_error(self, code: str) -> None:
        obs_registry.counter("gateway_errors_total", code=code).inc()
        with self._lock:
            self._errors += 1

    def _handle(self, conn, reader, cid: int) -> None:
        game = None
        try:
            self._send(conn, protocol.hello_frame(
                self._boards(), self._default_board(), self.slo_ms))
            n = 0
            while True:
                if self._core.draining:
                    self._send(conn, {"type": "goodbye",
                                      "reason": "draining"})
                    break
                try:
                    msg = protocol.read_frame(reader, self._max_frame)
                except protocol.ProtocolError as e:
                    self._count_error(e.code)
                    self._send(conn, protocol.error_frame(
                        e.code, str(e)))
                    if e.fatal:
                        break
                    continue
                if msg is None:
                    break              # disconnect / torn frame
                n += 1
                with self._lock:
                    self._requests += 1
                rid = msg.get("id")
                # the per-request fault wall (docs/RESILIENCE.md):
                # a transient fails this request, a kill this
                # connection — never the server
                try:
                    faults.barrier("gateway.conn", iteration=n)
                except faults.InjectedKill as e:
                    with self._lock:
                        self._kills += 1
                    obs_registry.counter("gateway_faults_total",
                                         kind="kill").inc()
                    self._send(conn, protocol.error_frame(
                        "internal", f"connection aborted: {e}",
                        id=rid))
                    break
                except Exception as e:  # noqa: BLE001 — injected
                    with self._lock:
                        self._faults += 1
                    obs_registry.counter("gateway_faults_total",
                                         kind="fault").inc()
                    self._count_error("internal")
                    self._send(conn, protocol.error_frame(
                        "internal", f"transient fault: {e}", id=rid))
                    continue
                try:
                    reply, game = self._dispatch(msg, game)
                except Exception as e:  # noqa: BLE001 — fault wall:
                    #   the connection must answer, the server live on
                    with self._lock:
                        self._unhandled += 1
                    self._count_error("internal")
                    reply = protocol.error_frame(
                        "internal", f"{type(e).__name__}: {e}",
                        id=rid)
                if reply is not None and not self._send(conn, reply):
                    break
        finally:
            if game is not None:
                game.session.close()

    # ------------------------------------------------------ dispatch

    def _dispatch(self, msg: dict, game):
        """One request → (reply frame, game). Refusals are typed
        error frames; only genuine bugs raise (counted unhandled)."""
        rid = msg.get("id")
        mtype = msg.get("type")
        obs_registry.counter("gateway_requests_total",
                             type=str(mtype)).inc()
        if mtype == "hello":
            proto = msg.get("proto", protocol.PROTO_VERSION)
            if proto != protocol.PROTO_VERSION:
                self._count_error("bad_proto")
                return protocol.error_frame(
                    "bad_proto",
                    f"server speaks proto {protocol.PROTO_VERSION}, "
                    f"client pinned {proto}", id=rid), game
            return {"type": "ok", "id": rid,
                    "proto": protocol.PROTO_VERSION}, game
        if mtype == "new_game":
            return self._new_game(msg, game)
        if mtype == "close":
            if game is not None:
                game.session.close()
            return {"type": "ok", "id": rid}, None
        if mtype in ("play", "genmove", "komi"):
            if game is None:
                self._count_error("no_game")
                return protocol.error_frame(
                    "no_game", f"{mtype} before new_game",
                    id=rid), game
            if mtype == "komi":
                try:
                    komi = float(msg.get("komi", game.state.komi))
                except (TypeError, ValueError) as e:
                    self._count_error("bad_request")
                    return protocol.error_frame(
                        "bad_request", f"unparseable komi: {e}",
                        id=rid), game
                game.session.set_komi(komi)
                game.state.komi = komi
                return {"type": "ok", "id": rid}, game
            if mtype == "play":
                return self._play(msg, game), game
            return self._genmove(msg, game), game
        self._count_error("unknown_type")
        return protocol.error_frame(
            "unknown_type", f"unknown message type {mtype!r}",
            id=rid), game

    def _boards(self) -> tuple:
        pool = self.pool
        return (tuple(pool.sizes) if hasattr(pool, "pool_for")
                else (pool.board,))

    def _default_board(self) -> int:
        pool = self.pool
        return (pool.default_size if hasattr(pool, "pool_for")
                else pool.board)

    def _new_game(self, msg: dict, game):
        rid = msg.get("id")
        # client fields parse BEFORE any side effect: a malformed
        # value is a typed refusal, never a leaked session or a
        # torn-down previous game
        try:
            board = int(msg.get("board", self._default_board()))
            komi = msg.get("komi")
            if komi is not None:
                komi = float(komi)
        except (TypeError, ValueError) as e:
            self._count_error("bad_request")
            return protocol.error_frame(
                "bad_request",
                f"unparseable new_game field: {e}", id=rid), game
        if game is not None:
            game.session.close()
            game = None
        try:
            if hasattr(self.pool, "pool_for"):
                session = self.pool.open_session(size=board)
            else:
                if board != self.pool.board:
                    raise KeyError(board)
                session = self.pool.open_session()
        except KeyError:
            self._count_error("bad_board")
            return protocol.error_frame(
                "bad_board",
                f"board {board} not served (serving "
                f"{list(self._boards())})", id=rid), None
        except AdmissionError as e:
            # the pool's AdmissionController said no: the structured
            # refusal the load balancer backs off on
            self._count_error("overload")
            self._shed_c.inc()
            with self._lock:
                self._shed += 1
            return protocol.error_frame(
                "overload", str(e), id=rid,
                retry_after_s=RETRY_AFTER_S), None
        try:
            if komi is not None:
                session.set_komi(komi)
            eff_komi = komi if komi is not None \
                else float(session.raw.pool.cfg.komi)
            arm = None
            if self.canary is not None:
                pin = self.canary.assign()
                if pin is not None:
                    session.pin_version(pin)
                    arm = "candidate"
                elif self.canary.state == "running":
                    arm = "incumbent"
            game = _Game(session, board, eff_komi, arm=arm)
        except BaseException:
            # the admission slot must come back even on a genuine
            # bug — a raise between open and _Game would otherwise
            # strand the session until restart
            session.close()
            raise
        return {"type": "ok", "id": rid, "board": board,
                "komi": eff_komi}, game

    def _play(self, msg: dict, game) -> dict:
        rid = msg.get("id")
        state = game.state
        prev = state.current_player
        try:
            color = parse_color(str(msg.get("color", "")))
            move = vertex_to_move(str(msg.get("move", "")),
                                  game.board)
            state.current_player = color
            if state.is_end_of_game:
                raise _GameOver()
            if move is not None and not state.is_legal(move):
                raise ValueError("illegal move")
            state.do_move(move, color)
        except _GameOver:
            state.current_player = prev
            self._count_error("game_over")
            return protocol.error_frame(
                "game_over", "the game has ended", id=rid)
        except Exception as e:  # noqa: BLE001 — refusal, state intact
            state.current_player = prev
            self._count_error("illegal_move")
            return protocol.error_frame("illegal_move", str(e),
                                        id=rid)
        if state.is_end_of_game:
            self._finish_game(game)
        return {"type": "ok", "id": rid}

    def _genmove(self, msg: dict, game) -> dict:
        rid = msg.get("id")
        state = game.state
        if state.is_end_of_game:
            self._count_error("game_over")
            return protocol.error_frame(
                "game_over", "the game has ended", id=rid)
        try:
            color = parse_color(str(msg.get("color", "")))
        except ValueError as e:
            self._count_error("bad_request")
            return protocol.error_frame("bad_request", str(e),
                                        id=rid)
        prev = state.current_player
        state.current_player = color
        # per-request SLO: the deadline arms inside the session's
        # anytime search (min of this and the pool's own SLO)
        slo_s = None if self.slo_ms is None else self.slo_ms / 1e3
        deadline = Deadline.after(slo_s)
        game.session.raw.set_move_time(slo_s)
        t0 = time.monotonic()
        try:
            move = game.session.get_move(state)
            if move is not None and not state.is_legal(move):
                move = None            # final guard, like the engine
            state.do_move(move, color)
        except Exception:
            state.current_player = prev
            raise
        dt = time.monotonic() - t0
        self._wire_h.observe(dt)
        game.served.add(color)
        if state.is_end_of_game:
            self._finish_game(game)
        with self._lock:
            self._genmoves += 1
            self._lat.append(dt)
            if len(self._lat) > _LAT_KEEP:
                del self._lat[: len(self._lat) - _LAT_KEEP]
        return {"type": "move", "id": rid,
                "move": move_to_vertex(move, game.board),
                "elapsed_ms": round(dt * 1e3, 3),
                "slo_hit": bool(not deadline.unlimited
                                and deadline.expired()),
                "rung": getattr(game.session.player, "last_rung",
                                None)}

    def _finish_game(self, game) -> None:
        """Game over: feed the canary ONE decided outcome, once —
        and only when this connection genmoved exactly one side (a
        self-play connection has no arm-attributable winner)."""
        if game.finished:
            return
        game.finished = True
        if self.canary is None or game.arm is None:
            return
        if len(game.served) != 1:
            return
        winner = game.state.get_winner()
        if winner == 0:
            return                     # draw: not a decided game
        color = next(iter(game.served))
        self.canary.record(game.arm, won=(winner == color))

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``gateway`` block (schema: docs/GATEWAY.md —
        the ``gateway-probe-drift`` lint rule diffs this literal
        against the documented schema both ways)."""
        wire = self._core.counters()
        with self._lock:
            shed = self._shed
            requests = self._requests
            errors = self._errors
            genmoves = self._genmoves
            unhandled = self._unhandled
            injected = self._faults
            kills = self._kills
            lat = sorted(self._lat)
        p50 = percentile(lat, 0.5)
        p99 = percentile(lat, 0.99)
        return {
            "proto": protocol.PROTO_VERSION,
            "draining": wire["draining"],
            "conns": {
                "live": wire["live"],
                "max": self.max_conns,
                "accepted": wire["accepted"],
                # at-accept conn sheds (core) + pool-admission sheds
                "shed": wire["shed"] + shed,
            },
            "requests": {
                "total": requests,
                "errors": errors,
                "genmoves": genmoves,
                "unhandled": unhandled,
            },
            "faults": {
                "injected": injected,
                "kills": kills,
            },
            "wire_ms": {
                "p50": None if p50 is None else round(p50 * 1e3, 3),
                "p99": None if p99 is None else round(p99 * 1e3, 3),
            },
            "slo_ms": self.slo_ms,
            "drain_s": self.drain_s,
            "boards": list(self._boards()),
            "default_board": self._default_board(),
        }


class _GameOver(Exception):
    """Internal: a move was requested after the game ended."""


def main(argv=None) -> int:
    """Launch a gateway over saved models and serve until SIGTERM
    (the supervisor's drain — stop accepting, finish in-flight
    moves, close sessions, exit 0) or Ctrl-C."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Network play gateway over a serve pool "
                    "(docs/GATEWAY.md)")
    ap.add_argument("--policy", required=True,
                    help="policy model JSON spec")
    ap.add_argument("--value", required=True,
                    help="value model JSON spec")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9462)
    ap.add_argument("--http-port", type=int, default=9463,
                    help="/healthz + /metrics port (0 disables)")
    ap.add_argument("--playouts", type=int, default=100)
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="per-genmove SLO (default "
                         "ROCALPHAGO_GATEWAY_SLO_MS / off)")
    ap.add_argument("--max-conns", type=int, default=None,
                    help="connection cap (default "
                         "ROCALPHAGO_GATEWAY_MAX_CONNS / 64)")
    ap.add_argument("--sizes", default=None,
                    help="comma list of board sizes for a multi-size "
                         "pool (needs FCN heads; docs/MULTISIZE.md)")
    ap.add_argument("--metrics", default=None,
                    help="JSONL path for drain/degradation events")
    ap.add_argument("--spill", default=None,
                    help="rollout spill dir to watch (the gate's "
                         "pool dir): promoted params hot-swap into "
                         "the live pool, no restart; docs/ROLLOUT.md")
    a = ap.parse_args(argv)

    from rocalphago_tpu.gateway.httpapi import GatewayHTTP
    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.runtime.compilecache import enable_compile_cache
    from rocalphago_tpu.runtime.supervisor import Supervisor

    enable_compile_cache()
    metrics = None
    if a.metrics:
        from rocalphago_tpu.io.metrics import MetricsLogger

        metrics = MetricsLogger(a.metrics, echo=False)
    policy = NeuralNetBase.load_model(a.policy)
    value = NeuralNetBase.load_model(a.value)
    if a.sizes:
        from rocalphago_tpu.multisize import MultiSizePool

        sizes = tuple(int(s) for s in a.sizes.split(",") if s.strip())
        pool = MultiSizePool(value, policy, sizes=sizes,
                             n_sim=a.playouts, metrics=metrics)
    else:
        from rocalphago_tpu.serve.sessions import ServePool

        pool = ServePool(value, policy, n_sim=a.playouts,
                         metrics=metrics)
    pool.warm()
    watcher = None
    if a.spill:
        from rocalphago_tpu.rollout.hotswap import (
            HotSwapper,
            SpillWatcher,
        )

        watcher = SpillWatcher(
            a.spill, HotSwapper(pool, metrics=metrics),
            policy.params, value.params, metrics=metrics).start()
    server = GatewayServer(pool, host=a.host, port=a.port,
                           max_conns=a.max_conns, slo_ms=a.slo_ms,
                           metrics=metrics).start()
    http = None
    if a.http_port:
        http = GatewayHTTP(server, host=a.host,
                           port=a.http_port).start()
    sup = Supervisor(metrics=metrics)
    sup.install_sigterm()
    print(f"gateway: serving on {a.host}:{server.port} "
          f"(http {'off' if http is None else http.port})")
    try:
        while not sup.draining:
            time.sleep(0.2)
    except KeyboardInterrupt:
        sup.request_drain(reason="keyboard")
    server.drain(reason="sigterm")
    if watcher is not None:
        watcher.stop()
    if http is not None:
        http.close()
    pool.close()
    if metrics is not None:
        obs_registry.log_to(metrics)
        metrics.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
