"""Minimal HTTP sidecar for the gateway: ``/healthz`` + ``/metrics``.

Two read-only endpoints, stdlib ``http.server`` only:

* ``GET /healthz`` — the health JSON a load balancer keys on: the
  familiar ``serve`` pool block (docs/SERVING.md) plus the gateway's
  own ``gateway`` block (:meth:`~rocalphago_tpu.gateway.server.
  GatewayServer.stats`; schema docs/GATEWAY.md). ``status`` is
  ``draining`` once a drain started (an LB should stop routing
  here), else ``ok``. Served with 503 while draining so dumb HTTP
  checks fail over without parsing.
* ``GET /metrics`` — the obs registry's Prometheus text exposition
  (:func:`rocalphago_tpu.obs.registry.render_text`), so the
  gateway's counters (connections, sheds, wire latency) scrape like
  every other metric in the process.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from rocalphago_tpu.obs import registry as obs_registry


class GatewayHTTP:
    """The probe server; ``server`` is the :class:`GatewayServer`
    whose pool/stats it exposes. ``port=0`` binds an ephemeral port
    (tests); :meth:`close` is bounded (threaded handlers are
    daemonic inside ThreadingHTTPServer, the serve loop is joined).
    """

    def __init__(self, server, host: str = "127.0.0.1",
                 port: int = 0):
        gateway = server

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server contract
                if self.path == "/metrics":
                    self._reply(200,
                                obs_registry.render_text().encode(),
                                "text/plain; version=0.0.4")
                    return
                if self.path == "/healthz":
                    draining = gateway.draining
                    body = json.dumps({
                        "status": ("draining" if draining else "ok"),
                        "serve": gateway.pool.stats(),
                        "gateway": gateway.stats(),
                    }, sort_keys=True).encode()
                    self._reply(503 if draining else 200, body,
                                "application/json")
                    return
                self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, name="gateway-http")

    def start(self) -> "GatewayHTTP":
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=10.0)
        self._httpd.server_close()
