"""Gateway client: the protocol handle plus the synthetic-load rig.

:class:`GatewayClient` is the blocking request/response handle every
consumer shares — the GTP bridge (``interface/gtp.py --connect``),
``benchmarks/bench_gateway.py`` and ``scripts/gateway_soak.py``. A
structured refusal (``overload``/``draining``) surfaces as
:class:`GatewayRefused` carrying the server's ``retry_after_s`` so
callers back off instead of spinning; a dropped connection is
:class:`GatewayClosed`.

:func:`connect_with_retry` is the backoff-aware way in: it wraps
the constructor in the shared :func:`rocalphago_tpu.net.client
.call_with_backoff` loop, so a shed client sleeps at least the
server's ``retry_after_s`` (deterministic-jitter backoff as the
floor) and succeeds on a later attempt instead of hand-rolling the
sleep — or spinning.

:func:`run_load` drives N concurrent synthetic games (one
connection each, barrier-started) and returns per-genmove latencies
plus shed/disconnect counts — the measurement half of the wire-tax
A/B and the soak's traffic source.
"""

from __future__ import annotations

import socket
import threading
import time

from rocalphago_tpu.gateway import protocol
from rocalphago_tpu.net import client as net_client


class GatewayError(Exception):
    """A typed error frame; ``code`` is one of
    :data:`~rocalphago_tpu.gateway.protocol.ERROR_CODES`."""

    def __init__(self, code: str, msg: str,
                 retry_after_s: float | None = None):
        super().__init__(f"{code}: {msg}")
        self.code = code
        self.retry_after_s = retry_after_s


class GatewayRefused(GatewayError):
    """The gateway shed this connection/request (``overload`` or
    ``draining``) — retry elsewhere or after ``retry_after_s``."""


class GatewayClosed(Exception):
    """The connection dropped mid-conversation (kill, drain nudge,
    network)."""


_REFUSAL_CODES = ("overload", "draining")


def _raise_error(frame: dict) -> None:
    code = frame.get("code", "internal")
    msg = frame.get("msg", "")
    retry = frame.get("retry_after_s")
    if code in _REFUSAL_CODES:
        raise GatewayRefused(code, msg, retry_after_s=retry)
    raise GatewayError(code, msg, retry_after_s=retry)


class GatewayClient:
    """One wire connection (= one server-side session slot).

    Connecting reads the server's ``hello`` (board sizes, SLO) — or
    raises :class:`GatewayRefused` when the gateway sheds at accept.
    Request helpers raise :class:`GatewayError` on typed refusals
    and :class:`GatewayClosed` on disconnect; the game survives
    non-fatal errors (``illegal_move``, ``internal``) server-side.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._reader = self.sock.makefile("rb")
        self._next_id = 0
        self.hello = self._recv()
        if self.hello.get("type") == "error":
            self.close()
            _raise_error(self.hello)
        self.boards = tuple(self.hello.get("boards", ()))
        self.default_board = self.hello.get("default_board")

    # --------------------------------------------------------- wire

    def _recv(self) -> dict:
        try:
            frame = protocol.read_frame(self._reader)
        except protocol.ProtocolError as e:
            raise GatewayClosed(f"unreadable frame: {e}")
        if frame is None:
            raise GatewayClosed("connection closed by gateway")
        return frame

    def request(self, msg: dict) -> dict:
        """Send one frame, return its (id-matched) reply. Unsolicited
        frames (``goodbye``) surface as :class:`GatewayClosed`."""
        self._next_id += 1
        msg = dict(msg, id=self._next_id)
        try:
            self.sock.sendall(protocol.encode_frame(msg))
        except OSError:
            raise GatewayClosed("send failed: connection closed")
        while True:
            reply = self._recv()
            if reply.get("type") == "goodbye":
                raise GatewayClosed(
                    f"gateway said goodbye "
                    f"({reply.get('reason', '?')})")
            if reply.get("id") == self._next_id:
                if reply.get("type") == "error":
                    _raise_error(reply)
                return reply
            # a reply to nothing we asked: protocol confusion
            raise GatewayClosed(f"unexpected frame {reply!r}")

    # -------------------------------------------------------- games

    def new_game(self, board: int | None = None,
                 komi: float | None = None) -> dict:
        msg: dict = {"type": "new_game"}
        if board is not None:
            msg["board"] = int(board)
        if komi is not None:
            msg["komi"] = float(komi)
        return self.request(msg)

    def play(self, color: str, vertex: str) -> dict:
        return self.request({"type": "play", "color": color,
                             "move": vertex})

    def genmove(self, color: str) -> dict:
        return self.request({"type": "genmove", "color": color})

    def set_komi(self, komi: float) -> dict:
        return self.request({"type": "komi", "komi": float(komi)})

    def close_game(self) -> dict:
        return self.request({"type": "close"})

    def close(self) -> None:
        # the makefile reader holds a reference on the underlying fd:
        # closing only the socket object would leave the fd open (no
        # FIN) and the server's handler blocked in readline forever
        try:
            self._reader.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def connect_with_retry(host: str, port: int, *, timeout: float = 60.0,
                       attempts: int = 6, base_delay: float = 0.25,
                       max_delay: float = 5.0, seed: int = 0,
                       sleep=time.sleep) -> GatewayClient:
    """Connect like :class:`GatewayClient`, but ride out sheds.

    A :class:`GatewayRefused` (``overload``/``draining``) or a
    dropped connection retries on the shared reconnect/backoff loop,
    sleeping at least the refusal's ``retry_after_s`` each round;
    the final attempt's exception propagates unchanged. ``sleep`` is
    injectable so tests assert the schedule instead of waiting it.
    """
    return net_client.call_with_backoff(
        lambda: GatewayClient(host, port, timeout=timeout),
        attempts=attempts, base_delay=base_delay,
        max_delay=max_delay, seed=seed, key="gateway.connect",
        sleep=sleep)


class GameLog:
    """Enough client-side state to reconstruct a live game on
    another replica: the admitted board/komi plus every landed move
    in order. Shared by the router's failover path and
    :class:`ResilientGatewayClient`."""

    def __init__(self):
        self.active = False
        self.board: int | None = None
        self.komi: float | None = None
        self.moves: list = []          # (color, vertex) play order

    def start(self, board, komi) -> None:
        self.active = True
        self.board = board
        self.komi = komi
        self.moves = []

    def play(self, color: str, vertex: str) -> None:
        self.moves.append((color, vertex))

    def set_komi(self, komi) -> None:
        self.komi = komi

    def clear(self) -> None:
        self.active = False
        self.board = None
        self.komi = None
        self.moves = []

    def replay(self, client) -> None:
        """Re-create the game on ``client`` (a fresh connection to
        any replica serving the same board)."""
        client.new_game(board=self.board, komi=self.komi)
        for color, vertex in self.moves:
            client.play(color, vertex)


class ResilientGatewayClient:
    """A :class:`GatewayClient` surface that survives replica drains
    and router spillover transparently.

    Every request runs inside the shared
    :func:`~rocalphago_tpu.net.client.call_with_backoff` loop: a
    dropped connection (:class:`GatewayClosed` — a drain nudge, a
    kill, a router failing over) or a structured refusal
    (:class:`GatewayRefused`, honoring its ``retry_after_s``)
    reconnects, replays the live game from the :class:`GameLog`, and
    retries the in-flight request. Typed game errors
    (``illegal_move``, ``game_over`` …) propagate unchanged — they
    are answers, not outages. ``reconnects`` counts recoveries (the
    mid-game-drain regression test's probe).
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 attempts: int = 6, base_delay: float = 0.25,
                 max_delay: float = 5.0, seed: int = 0,
                 sleep=time.sleep):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._retry = dict(attempts=attempts, base_delay=base_delay,
                           max_delay=max_delay, seed=seed,
                           sleep=sleep)
        self.log = GameLog()
        self.reconnects = 0
        self._client = connect_with_retry(host, port,
                                          timeout=timeout,
                                          **self._retry)
        self.hello = self._client.hello
        self.boards = self._client.boards
        self.default_board = self._client.default_board

    # --------------------------------------------------------- wire

    def _reconnect(self) -> None:
        self._client = connect_with_retry(self.host, self.port,
                                          timeout=self.timeout,
                                          **self._retry)
        self.reconnects += 1
        if self.log.active:
            self.log.replay(self._client)

    def _request(self, msg: dict) -> dict:
        def attempt():
            if self._client is None:
                self._reconnect()
            try:
                return self._client.request(dict(msg))
            except (GatewayRefused, GatewayClosed):
                # this connection is spent; the next attempt starts
                # clean (reconnect + replay)
                client, self._client = self._client, None
                client.close()
                raise

        return net_client.call_with_backoff(
            attempt, key="gateway.reconnect", **self._retry)

    # -------------------------------------------------------- games

    def new_game(self, board: int | None = None,
                 komi: float | None = None) -> dict:
        msg: dict = {"type": "new_game"}
        if board is not None:
            msg["board"] = int(board)
        if komi is not None:
            msg["komi"] = float(komi)
        reply = self._request(msg)
        self.log.start(reply.get("board"), reply.get("komi"))
        return reply

    def play(self, color: str, vertex: str) -> dict:
        reply = self._request({"type": "play", "color": color,
                               "move": vertex})
        self.log.play(color, vertex)
        return reply

    def genmove(self, color: str) -> dict:
        reply = self._request({"type": "genmove", "color": color})
        if reply.get("type") == "move":
            self.log.play(color, reply.get("move"))
        return reply

    def set_komi(self, komi: float) -> dict:
        reply = self._request({"type": "komi", "komi": float(komi)})
        self.log.set_komi(float(komi))
        return reply

    def close_game(self) -> dict:
        reply = self._request({"type": "close"})
        self.log.clear()
        return reply

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


# ------------------------------------------------------ load generator


def drive_game(client: GatewayClient, moves: int,
               board: int | None = None,
               latencies: list | None = None) -> int:
    """One synthetic game: alternate-color genmoves until ``moves``
    moves landed (re-opening on natural game end). Returns the move
    count; per-genmove wall times append to ``latencies``."""
    client.new_game(board=board)
    colors = ("b", "w")
    done = 0
    while done < moves:
        try:
            t0 = time.monotonic()
            client.genmove(colors[done % 2])
            if latencies is not None:
                latencies.append(time.monotonic() - t0)
            done += 1
        except GatewayError as e:
            if e.code != "game_over":
                raise
            client.new_game(board=board)
    client.close_game()
    return done


def run_load(host: str, port: int, conns: int, moves: int,
             board: int | None = None,
             timeout: float = 120.0) -> dict:
    """N concurrent synthetic games against a gateway.

    Barrier-started so every connection ramps together (the same
    idiom as ``benchmarks/bench_serve.py``). Returns moves/sheds/
    disconnect/error counts, the elapsed wall time and every
    per-genmove latency — :func:`summarize` turns that into the
    bench row.
    """
    start = threading.Barrier(conns + 1)
    lock = threading.Lock()
    out = {"moves": 0, "sheds": 0, "disconnects": 0, "errors": 0,
           "latencies_s": []}

    def worker():
        lat: list = []
        sheds = drops = errors = 0
        try:
            start.wait(timeout)
            client = GatewayClient(host, port, timeout=timeout)
            try:
                drive_game(client, moves, board=board,
                           latencies=lat)
            finally:
                client.close()
        except GatewayRefused:
            sheds = 1
        except GatewayClosed:
            drops = 1
        except Exception:  # noqa: BLE001 — counted, load goes on
            errors = 1
        with lock:
            # len(lat) counts the moves that actually landed, even
            # when the game was cut short by a kill or drain
            out["moves"] += len(lat)
            out["sheds"] += sheds
            out["disconnects"] += drops
            out["errors"] += errors
            out["latencies_s"].extend(lat)

    threads = [threading.Thread(target=worker,
                                name=f"gateway-load-{i}")
               for i in range(conns)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    start.wait(timeout)
    for t in threads:
        t.join(timeout=timeout)
    out["elapsed_s"] = time.monotonic() - t0
    return out
