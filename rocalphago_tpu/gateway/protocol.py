"""The gateway wire protocol: newline-delimited JSON, versioned.

One frame = one JSON object on one line (NDJSON). The server speaks
first (a ``hello`` frame carrying ``proto`` and the active board
sizes — or a structured refusal when the gateway sheds the
connection); after that the client drives request/response pairs
correlated by ``id``:

=============  =======================================================
request        response
=============  =======================================================
``hello``      ``ok`` (optional; pins the protocol version — a
               mismatch is ``bad_proto``)
``new_game``   ``ok`` with the admitted ``board``/``komi`` (errors:
               ``bad_board``, ``overload`` + ``retry_after_s``)
``play``       ``ok`` (error ``illegal_move`` leaves the game
               untouched)
``genmove``    ``move`` with the vertex, elapsed wall time and the
               resilience rung that produced it
``komi``       ``ok`` (re-threads the live session's komi)
``close``      ``ok`` (ends the game, releases the session slot; the
               connection may open another game)
=============  =======================================================

Typed error codes (``{"type": "error", "code": …}``) are the
protocol's refusal surface — a shed NEVER looks like a hang:
``overload`` and ``draining`` carry ``retry_after_s`` so clients and
load balancers back off instead of spinning. Frames are bounded at
``ROCALPHAGO_GATEWAY_MAX_FRAME`` bytes (newline included); a line
over the bound is refused with ``frame_too_big`` and the connection
is dropped (the reader cannot resynchronize mid-line). A torn frame
(EOF before the newline) is a disconnect, not an error; a blank
line is neither — it is skipped, so keepalive-style bare newlines
do not kill the game.

Framing (the reader rules, sorted-key encoding, typed error
frames) is the shared :mod:`rocalphago_tpu.net.protocol` core —
this module pins the gateway's protocol CONTENT on top of it: the
version, the error-code vocabulary, the frame bound and the hello.

Schema and examples: docs/GATEWAY.md.
"""

from __future__ import annotations

import os

from rocalphago_tpu.net import protocol as _net

#: protocol revision carried in every hello; bumped on any frame
#: schema change a deployed client could observe
PROTO_VERSION = 1

#: bound on one wire frame (bytes, newline included); env override
MAX_FRAME_ENV = "ROCALPHAGO_GATEWAY_MAX_FRAME"

#: every error code a frame may carry (docs/GATEWAY.md)
ERROR_CODES = (
    "bad_request",     # unparseable JSON / missing required field
    "bad_proto",       # client hello pinned an unsupported version
    "frame_too_big",   # line crossed the frame bound; connection drops
    "unknown_type",    # message type outside the protocol table
    "bad_board",       # requested size not served by this pool
    "illegal_move",    # play refused; game state untouched
    "no_game",         # play/genmove/komi/close before new_game
    "game_over",       # move requested after the game ended
    "overload",        # shed (admission/conn cap); retry_after_s set
    "draining",        # server is drain-stopping; retry_after_s set
    "internal",        # handler fault; this request failed, game holds
)


#: the shared framing core's exception, re-exported so every
#: existing ``protocol.ProtocolError`` caller keeps working
ProtocolError = _net.ProtocolError

encode_frame = _net.encode_frame


def max_frame_bytes() -> int:
    raw = os.environ.get(MAX_FRAME_ENV, "")
    return int(raw) if raw else 65536


def read_frame(reader, limit: int | None = None):
    """Next frame off a buffered binary reader, bounded at the
    gateway's frame limit by default (shared reader rules:
    :func:`rocalphago_tpu.net.protocol.read_frame`)."""
    return _net.read_frame(
        reader, max_frame_bytes() if limit is None else limit)


def error_frame(code: str, msg: str, id=None,
                retry_after_s: float | None = None) -> dict:
    return _net.error_frame(code, msg, id=id,
                            retry_after_s=retry_after_s,
                            codes=ERROR_CODES)


def hello_frame(boards, default_board: int,
                slo_ms: float | None) -> dict:
    return {"type": "hello", "proto": PROTO_VERSION,
            "name": "rocalphago-gateway",
            "boards": [int(b) for b in boards],
            "default_board": int(default_board),
            "slo_ms": slo_ms}
