"""The gateway wire protocol: newline-delimited JSON, versioned.

One frame = one JSON object on one line (NDJSON). The server speaks
first (a ``hello`` frame carrying ``proto`` and the active board
sizes — or a structured refusal when the gateway sheds the
connection); after that the client drives request/response pairs
correlated by ``id``:

=============  =======================================================
request        response
=============  =======================================================
``hello``      ``ok`` (optional; pins the protocol version — a
               mismatch is ``bad_proto``)
``new_game``   ``ok`` with the admitted ``board``/``komi`` (errors:
               ``bad_board``, ``overload`` + ``retry_after_s``)
``play``       ``ok`` (error ``illegal_move`` leaves the game
               untouched)
``genmove``    ``move`` with the vertex, elapsed wall time and the
               resilience rung that produced it
``komi``       ``ok`` (re-threads the live session's komi)
``close``      ``ok`` (ends the game, releases the session slot; the
               connection may open another game)
=============  =======================================================

Typed error codes (``{"type": "error", "code": …}``) are the
protocol's refusal surface — a shed NEVER looks like a hang:
``overload`` and ``draining`` carry ``retry_after_s`` so clients and
load balancers back off instead of spinning. Frames are bounded at
``ROCALPHAGO_GATEWAY_MAX_FRAME`` bytes (newline included); a line
over the bound is refused with ``frame_too_big`` and the connection
is dropped (the reader cannot resynchronize mid-line). A torn frame
(EOF before the newline) is a disconnect, not an error; a blank
line is neither — it is skipped, so keepalive-style bare newlines
do not kill the game.

Schema and examples: docs/GATEWAY.md.
"""

from __future__ import annotations

import json
import os

#: protocol revision carried in every hello; bumped on any frame
#: schema change a deployed client could observe
PROTO_VERSION = 1

#: bound on one wire frame (bytes, newline included); env override
MAX_FRAME_ENV = "ROCALPHAGO_GATEWAY_MAX_FRAME"

#: every error code a frame may carry (docs/GATEWAY.md)
ERROR_CODES = (
    "bad_request",     # unparseable JSON / missing required field
    "bad_proto",       # client hello pinned an unsupported version
    "frame_too_big",   # line crossed the frame bound; connection drops
    "unknown_type",    # message type outside the protocol table
    "bad_board",       # requested size not served by this pool
    "illegal_move",    # play refused; game state untouched
    "no_game",         # play/genmove/komi/close before new_game
    "game_over",       # move requested after the game ended
    "overload",        # shed (admission/conn cap); retry_after_s set
    "draining",        # server is drain-stopping; retry_after_s set
    "internal",        # handler fault; this request failed, game holds
)


def max_frame_bytes() -> int:
    raw = os.environ.get(MAX_FRAME_ENV, "")
    return int(raw) if raw else 65536


class ProtocolError(Exception):
    """A frame the reader cannot accept; ``code`` names why and
    ``fatal`` says whether the connection can survive it (a torn
    byte stream cannot — the next line boundary is unknowable)."""

    def __init__(self, code: str, msg: str, fatal: bool = False):
        super().__init__(msg)
        self.code = code
        self.fatal = fatal


def encode_frame(msg: dict) -> bytes:
    """One dict → one NDJSON line (sorted keys: byte-stable frames
    make wire-level tests and captures diffable)."""
    return (json.dumps(msg, sort_keys=True) + "\n").encode("utf-8")


def read_frame(reader, limit: int | None = None):
    """Next frame off a buffered binary reader.

    Returns the decoded dict, or None on a clean EOF / torn trailing
    line (both are disconnects). Blank lines are not frames and not
    disconnects — a keepalive-style bare newline is skipped and the
    read continues. Raises :class:`ProtocolError` for a line longer
    than ``limit`` bytes, newline included (fatal) or undecodable
    JSON (non-fatal: the line boundary survived, the connection can
    report and go on).
    """
    limit = max_frame_bytes() if limit is None else limit
    while True:
        line = reader.readline(limit + 1)
        if not line:
            return None
        if len(line) > limit:
            # longer than the bound whether or not the newline made
            # it into the read: a complete limit+1-byte line and a
            # partial read mid-line are both over
            raise ProtocolError(
                "frame_too_big",
                f"frame exceeds {limit} bytes", fatal=True)
        if not line.endswith(b"\n"):
            return None                   # torn frame at EOF
        line = line.strip()
        if line:
            break                         # blank line: keep reading
    try:
        msg = json.loads(line.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ProtocolError("bad_request", f"undecodable frame: {e}")
    if not isinstance(msg, dict):
        raise ProtocolError("bad_request",
                            "frame must be a JSON object")
    return msg


def error_frame(code: str, msg: str, id=None,
                retry_after_s: float | None = None) -> dict:
    assert code in ERROR_CODES, code
    out = {"type": "error", "code": code, "msg": msg}
    if id is not None:
        out["id"] = id
    if retry_after_s is not None:
        out["retry_after_s"] = round(float(retry_after_s), 3)
    return out


def hello_frame(boards, default_board: int,
                slo_ms: float | None) -> dict:
    return {"type": "hello", "proto": PROTO_VERSION,
            "name": "rocalphago-gateway",
            "boards": [int(b) for b in boards],
            "default_board": int(default_board),
            "slo_ms": slo_ms}
