"""Network play gateway: the serve pool behind a wire.

Every entry point before this package was process-local — GTP over
stdin/stdout, :class:`~rocalphago_tpu.serve.sessions.ServePool` /
:class:`~rocalphago_tpu.multisize.pool.MultiSizePool` as in-process
Python APIs. The gateway turns the pool into an actual service:

* :mod:`~rocalphago_tpu.gateway.protocol` — the versioned NDJSON
  wire protocol (``new_game``/``play``/``genmove``/``close`` plus
  typed error codes, ``overload`` carrying a retry-after hint);
* :mod:`~rocalphago_tpu.gateway.server` — a threaded socket server
  mapping one connection to one pool session, with admission-backed
  connection caps (structured refusals, never hangs), per-request
  SLO deadlines, the resilience ladder per session, multi-size
  ``board`` routing, and a SIGTERM graceful drain;
* :mod:`~rocalphago_tpu.gateway.httpapi` — ``/healthz`` (the health
  JSON plus a ``"gateway"`` block) and ``/metrics`` (the obs
  registry's Prometheus rendering);
* :mod:`~rocalphago_tpu.gateway.client` — the client handle + load
  generator driving ``benchmarks/bench_gateway.py`` and
  ``scripts/gateway_soak.py``.

Wire format, probe schema, drain semantics, measured numbers:
docs/GATEWAY.md.
"""

from rocalphago_tpu.gateway.protocol import PROTO_VERSION  # noqa: F401
