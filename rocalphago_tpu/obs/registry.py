"""Process-wide metric registry: counters, gauges, bounded histograms.

Stdlib-only, so the hot paths (device-search chunk loops, the serving
ladder) can record without any logger plumbing and light scripts can
import it without jax. One module-level :data:`REGISTRY` is the
process default — the GTP ``rocalphago-stats`` probe returns its
:func:`snapshot` and trainers log it to ``metrics.jsonl`` at the end
of a run (event ``registry``), which is how histograms reach
``scripts/obs_report.py``.

Design points:

* metrics are keyed by ``name`` plus sorted ``labels`` (Prometheus
  identity: ``name{k="v"}``), get-or-create, thread-safe — audited
  for the serving pool's many-sessions emit pattern: ``_get`` holds
  the registry lock, every mutate holds the metric's own lock, and
  ``Histogram.observe``'s bisect runs lock-free only over the
  immutable ``edges`` tuple (the concurrent-emit test in
  ``tests/test_obs.py`` hammers counters/histograms from N threads
  and pins exact totals);
* histograms are BOUNDED: a fixed ascending edge list (default
  :data:`DEFAULT_EDGES`, latency-shaped) plus one overflow bucket —
  constant memory however many observations arrive; ``observe`` is a
  bisect + two adds. Bucket semantics are Prometheus ``le``
  (cumulative, edge-inclusive) in :meth:`Histogram.snapshot`;
* :func:`snapshot` is DETERMINISTIC: same recorded metrics → the same
  nested dict with the same (sorted) key order, so tests and diffs
  can compare snapshots directly;
* :func:`render_text` emits the Prometheus text exposition shape
  (``# TYPE`` headers, ``_bucket{le=...}``/``_sum``/``_count`` for
  histograms) for operators who want to scrape-and-eyeball.
"""

from __future__ import annotations

import bisect
import threading
import time

#: default histogram edges (seconds): microbenchmark to slow-chunk
#: scale, the range every latency in this stack falls into
DEFAULT_EDGES = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: rate edges (per-second throughputs: sims/sec, positions/sec)
RATE_EDGES = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 100000.0)

#: small-count edges (game plies, retries, queue depths)
COUNT_EDGES = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 350.0,
               500.0, 1000.0)


def _fmt(x) -> str:
    """Short stable float rendering for bucket keys ('0.01', '1')."""
    return format(float(x), "g")


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter. ``inc`` only — resets come from
    ``Registry.reset`` (tests), never production code."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins scalar (deadline margins, rates)."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Bounded-bucket histogram over fixed ascending ``edges``.

    A value lands in the FIRST bucket whose edge is >= it (edge
    inclusive — Prometheus ``le``); values past the last edge land in
    the overflow bucket. ``snapshot`` returns cumulative ``le``
    counts plus ``sum``/``count``.
    """

    __slots__ = ("_lock", "edges", "counts", "count", "sum")

    def __init__(self, edges=DEFAULT_EDGES):
        edges = tuple(float(e) for e in edges)
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(
                f"histogram edges must be non-empty and strictly "
                f"ascending, got {edges}")
        self._lock = threading.Lock()
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)   # + overflow
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.edges, v)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += v

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        buckets, cum = {}, 0
        for edge, c in zip(self.edges, counts):
            cum += c
            buckets[_fmt(edge)] = cum
        buckets["+Inf"] = total
        return {"count": total, "sum": round(s, 6),
                "buckets": buckets}


def quantile_from_buckets(snap: dict, q: float):
    """Upper-edge quantile estimate from a :meth:`Histogram.snapshot`
    dict (nearest-rank over the cumulative ``le`` counts). Returns
    the bucket's upper edge as float, ``float('inf')`` when the rank
    falls in the overflow bucket, None for an empty histogram —
    bounded buckets can't do better than an edge, which is exactly
    enough for a report."""
    total = snap.get("count", 0)
    if not total:
        return None
    rank = max(1, round(q * total))
    for edge, cum in snap["buckets"].items():
        if cum >= rank:
            return float("inf") if edge == "+Inf" else float(edge)
    return float("inf")


class Registry:
    """Get-or-create metric store; see module docstring."""

    def __init__(self):
        # deliberately a PLAIN lock, never a lockcheck wrapper: the
        # lockcheck harness emits ITS metrics through this registry,
        # so instrumenting the registry's own lock would recurse
        self._lock = threading.Lock()
        self._metrics: dict = {}       # guarded-by: self._lock
        self._kinds: dict = {}         # guarded-by: self._lock
        self._families: dict = {}      # guarded-by: self._lock

    def _get(self, kind: str, name: str, labels: dict, make):
        key = _key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if self._kinds[key] != kind:
                    raise ValueError(
                        f"metric {key!r} already registered as "
                        f"{self._kinds[key]}, not {kind}")
                return m
        # miss: construct OUTSIDE the lock (make is caller code — a
        # critical section must not run it), insert with a re-check;
        # a racing creator wins and the spare build is dropped
        built = make()
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = built
                self._metrics[key] = m
                self._kinds[key] = kind
                self._families[key] = (name, dict(labels))
            elif self._kinds[key] != kind:
                raise ValueError(
                    f"metric {key!r} already registered as "
                    f"{self._kinds[key]}, not {kind}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, edges=None, **labels) -> Histogram:
        """Get-or-create; ``edges`` applies only on creation (an
        existing histogram keeps its buckets — callers agree on edges
        per name by convention)."""
        return self._get("histogram", name, labels,
                         lambda: Histogram(edges or DEFAULT_EDGES))

    def snapshot(self) -> dict:
        """Deterministic nested dict:
        ``{"counters": {key: int}, "gauges": {key: float|None},
        "histograms": {key: {count, sum, buckets}}}`` with every
        level sorted by key."""
        with self._lock:
            items = sorted(self._metrics.items())
            kinds = dict(self._kinds)
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, m in items:
            kind = kinds[key]
            if kind == "counter":
                out["counters"][key] = m.value
            elif kind == "gauge":
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.snapshot()
        return out

    def render_text(self) -> str:
        """Prometheus-style text exposition of the current state."""
        with self._lock:
            items = sorted(self._metrics.items())
            kinds = dict(self._kinds)
            families = dict(self._families)
        lines, typed = [], set()
        for key, m in items:
            kind = kinds[key]
            name, labels = families[key]
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} {kind}")
            if kind == "counter":
                lines.append(f"{key} {m.value}")
            elif kind == "gauge":
                lines.append(f"{key} "
                             f"{'NaN' if m.value is None else m.value}")
            else:
                snap = m.snapshot()
                for edge, cum in snap["buckets"].items():
                    lab = dict(labels, le=edge)
                    lines.append(f"{_key(name + '_bucket', lab)} {cum}")
                lines.append(f"{_key(name + '_sum', labels)} "
                             f"{snap['sum']}")
                lines.append(f"{_key(name + '_count', labels)} "
                             f"{snap['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def log_to(self, metrics) -> None:
        """Write the snapshot as one ``registry`` event through a
        ``MetricsLogger``-shaped object (file-only ``write`` when it
        has one — a snapshot is machine food, not console output)."""
        if metrics is None:
            return
        fn = getattr(metrics, "write", None) or metrics.log
        fn("registry", snapshot=self.snapshot())

    def reset(self) -> None:
        """Drop every metric (tests only — production counters are
        process-lifetime by design)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._families.clear()


#: the process-wide default registry
REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
render_text = REGISTRY.render_text
log_to = REGISTRY.log_to
reset = REGISTRY.reset


def timed(iterable, hist: Histogram):
    """Yield from ``iterable`` recording each ``next()`` wait into
    ``hist`` — the data-starvation probe the trainers wrap their
    prefetch iterators with (host wait per batch; near-zero when the
    pipeline keeps up)."""
    it = iter(iterable)
    while True:
        t0 = time.monotonic()
        try:
            x = next(it)
        except StopIteration:
            return
        hist.observe(time.monotonic() - t0)
        yield x
