"""Nested tracing spans over the ``metrics.jsonl`` stream.

``with span("zero.selfplay"):`` wraps a phase of a host loop; on exit
one structured ``span`` record goes through the process's configured
sink (a :class:`~rocalphago_tpu.io.metrics.MetricsLogger` — the SAME
JSONL stream the trainer's scalar metrics use, so one file tells the
whole story and ``scripts/obs_report.py`` renders the per-phase time
breakdown from it).

Record shape (plus the logger's own ``event``/``time`` envelope)::

    {"event": "span", "name": "zero.selfplay",
     "path": "zero.iteration/zero.selfplay",
     "parent": "zero.iteration", "depth": 1,
     "dur_s": 1.234567, "start": <wall clock t0>, "ok": true,
     ...caller tags...}

Durations are ``time.monotonic`` differences; ``start`` is wall
clock (``time.time``) so records correlate with external logs.
Nesting is per-thread (a thread-local stack), but the set of OPEN
spans is visible process-wide through :func:`open_spans`/
:func:`where` — that is what lets the watchdog's ``stall`` events say
*where* the process hung, and it is why the stack is maintained even
with no sink configured (a span without a sink costs two lock'd list
ops and emits nothing).

One process = one sink: trainers and the GTP CLI call
:func:`configure` right after building their ``MetricsLogger``.
Library code just opens spans — unconfigured processes pay ~1µs per
span and write nothing.
"""

from __future__ import annotations

import threading
import time

from rocalphago_tpu.analysis import lockcheck

_lock = lockcheck.make_lock("trace._lock")
_stacks: dict = {}        # guarded-by: _lock — ident -> open frames
_names: dict = {}         # guarded-by: _lock — ident -> thread name
_sink = None
_enabled = True


class _Frame:
    __slots__ = ("name", "path", "t0", "wall0")


def configure(metrics=None, enabled: bool = True) -> None:
    """Install the process sink (``MetricsLogger``-shaped: ``write``
    or ``log``). ``metrics=None`` detaches; ``enabled=False`` keeps
    the sink but mutes emission (the cheap global off-switch)."""
    global _sink, _enabled
    _sink = metrics
    _enabled = enabled


def sink():
    return _sink


def emit(event: str, **fields) -> None:
    """Write one structured event through the configured sink (no-op
    when unconfigured/muted). Used by spans and by
    :mod:`rocalphago_tpu.obs.jaxobs` for ``compile`` events; prefers
    the sink's file-only ``write`` over ``log`` so high-rate
    telemetry never spams the console."""
    s = _sink
    if s is None or not _enabled:
        return
    fn = getattr(s, "write", None) or s.log
    fn(event, **fields)


class span:
    """``with span("name", **tags):`` — one timed, nested phase.

    Reusable but not reentrant: construct one per ``with`` block.
    Exceptions propagate; the record then carries ``ok: false`` and
    an ``error`` string (the exception is NOT swallowed).
    """

    __slots__ = ("name", "tags", "_frame", "_ident")

    def __init__(self, name: str, **tags):
        self.name = name
        self.tags = tags
        self._frame = None

    def __enter__(self) -> "span":
        f = _Frame()
        f.t0 = time.monotonic()
        f.wall0 = time.time()
        f.name = self.name
        ident = threading.get_ident()
        with _lock:
            stack = _stacks.get(ident)
            if stack is None:
                stack = _stacks[ident] = []
                _names[ident] = threading.current_thread().name
            f.path = (self.name if not stack
                      else stack[-1].path + "/" + self.name)
            stack.append(f)
        self._frame = f
        self._ident = ident
        return self

    def __exit__(self, et, ev, tb):
        f = self._frame
        dur = time.monotonic() - f.t0
        with _lock:
            stack = _stacks.get(self._ident)
            if stack and stack[-1] is f:
                stack.pop()
            elif stack and f in stack:      # unbalanced exit: heal
                del stack[stack.index(f):]
            if not stack:
                _stacks.pop(self._ident, None)
                _names.pop(self._ident, None)
        parent, _, _ = f.path.rpartition("/")
        fields = dict(
            name=f.name, path=f.path, parent=parent or None,
            depth=f.path.count("/"), dur_s=round(dur, 6),
            start=round(f.wall0, 6), ok=et is None)
        if et is not None:
            fields["error"] = f"{et.__name__}: {ev}"
        fields.update(self.tags)
        emit("span", **fields)
        return False


def current_path() -> str | None:
    """Innermost open span path of the CALLING thread (None when no
    span is open here)."""
    with _lock:
        stack = _stacks.get(threading.get_ident())
        return stack[-1].path if stack else None


def open_spans() -> dict:
    """``{thread_name: innermost open span path}`` across every
    thread — the process-wide 'what is everyone doing' view."""
    with _lock:
        return {_names[ident]: stack[-1].path
                for ident, stack in _stacks.items() if stack}


def where() -> str | None:
    """Best one-string answer to 'where is this process right now':
    the DEEPEST open span path across all threads (a hung worker's
    rung span beats the engine's outer genmove span); ties prefer
    MainThread, then thread-name order — deterministic, so stall
    logs are assertable."""
    spans = open_spans()
    if not spans:
        return None

    def rank(item):
        tname, path = item
        return (-path.count("/"), tname != "MainThread", tname)

    return sorted(spans.items(), key=rank)[0][1]
