"""JAX-side observability: compile tracking + opt-in profiler capture.

COMPILE TRACKING — on accelerators the difference between a healthy
run and a pathological one is often invisible recompiles (a shape
drifting per iteration recompiles a trainer step every time; KataGo/
Pgx-style throughput work lives on exactly this distinction).
:func:`track` wraps a jitted entry point; every call that grows the
function's executable cache (``PjitFunction._cache_size`` — exact,
not a heuristic) is recorded as:

* counter ``jax_compiles_total{entry=...}`` + histogram
  ``jax_compile_seconds{entry=...}`` in the default registry;
* one ``compile`` event through the trace sink (``recompile: true``
  from the second compile on), so ``metrics.jsonl`` names the entry
  point and the wall cost.

On runtimes without ``_cache_size`` the first call counts as the
compile (first-call-vs-steady heuristic). Steady-state dispatch time
is kept as an EMA on the wrapper (``.steady_ema_s``) so first-call vs
steady timing per entry point is one attribute read. The wrapper
delegates unknown attributes to the wrapped function, so
``.lower()``/``.clear_cache()`` and the chunk-program attribute
conventions (``search.run_sims``) keep working.

PROFILER CAPTURE — ``maybe_start_profiler()`` starts a
``jax.profiler`` trace into a directory given explicitly (trainer
``--profile-dir`` flags) or via :data:`PROFILE_ENV`; no-op otherwise,
so it is safe to call unconditionally. ``stop_profiler`` is
idempotent and also registered via ``atexit`` (a crashed run still
flushes its trace). ``jax`` is imported lazily — importing this
module stays stdlib-cheap.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import sys
import time

from rocalphago_tpu.obs import registry as _registry
from rocalphago_tpu.obs import trace as _trace

PROFILE_ENV = "ROCALPHAGO_JAX_PROFILE"


def _cache_size(fn):
    f = getattr(fn, "_cache_size", None)
    if f is None:
        return None
    try:
        return int(f())
    except Exception:  # noqa: BLE001 — introspection is best-effort
        return None


class TrackedFunction:
    """Callable wrapper; see module docstring. Attributes:
    ``entry`` (name), ``calls``, ``compiles``, ``first_call_s``,
    ``steady_ema_s``; everything else delegates to the wrapped fn."""

    def __init__(self, entry: str, fn, registry=None):
        self._fn = fn
        self.entry = entry
        self.registry = registry or _registry.REGISTRY
        self.calls = 0
        self.compiles = 0
        self.first_call_s = None
        self.steady_ema_s = None

    def __call__(self, *args, **kwargs):
        n0 = _cache_size(self._fn)
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        dt = time.monotonic() - t0
        self.calls += 1
        n1 = _cache_size(self._fn)
        compiled = (n1 > n0 if n1 is not None and n0 is not None
                    else self.calls == 1)
        if compiled:
            self.compiles += 1
            if self.first_call_s is None:
                self.first_call_s = dt
            self.registry.counter("jax_compiles_total",
                                  entry=self.entry).inc()
            self.registry.histogram("jax_compile_seconds",
                                    entry=self.entry).observe(dt)
            _trace.emit("compile", entry=self.entry,
                        dur_s=round(dt, 6), calls=self.calls,
                        recompile=self.compiles > 1)
        else:
            ema = self.steady_ema_s
            self.steady_ema_s = (dt if ema is None
                                 else 0.9 * ema + 0.1 * dt)
        return out

    def __getattr__(self, item):
        # only reached for names NOT on the wrapper; '_fn' is set
        # first in __init__ so delegation can never recurse
        return getattr(self._fn, item)

    def stats(self) -> dict:
        return {"entry": self.entry, "calls": self.calls,
                "compiles": self.compiles,
                "first_call_s": self.first_call_s,
                "steady_ema_s": self.steady_ema_s}

    def __repr__(self) -> str:
        return (f"TrackedFunction({self.entry!r}, calls={self.calls}, "
                f"compiles={self.compiles})")


def track(entry: str, fn=None, registry=None):
    """Wrap a (jitted) callable with compile-event tracking —
    ``track("name", fn)`` or as a decorator ``@track("name")``."""
    if fn is None:
        return lambda f: TrackedFunction(entry, f, registry)
    return TrackedFunction(entry, fn, registry)


# ------------------------------------------------ profiler capture

_profiling = {"dir": None}


def maybe_start_profiler(out_dir: str | None = None) -> bool:
    """Start a ``jax.profiler`` trace into ``out_dir`` (or
    ``$ROCALPHAGO_JAX_PROFILE``); returns whether a capture started.
    Safe to call unconditionally — no directory means no-op; a second
    start while one is active is a no-op too."""
    out = out_dir or os.environ.get(PROFILE_ENV)
    if not out or _profiling["dir"] is not None:
        return False
    import jax

    jax.profiler.start_trace(out)
    _profiling["dir"] = out
    atexit.register(stop_profiler)
    _trace.emit("profiler", action="start", out_dir=out)
    print(f"jaxobs: profiler capture -> {out}", file=sys.stderr)
    return True


def stop_profiler() -> None:
    """Stop an active capture (idempotent; also runs via atexit)."""
    if _profiling["dir"] is None:
        return
    import jax

    out, _profiling["dir"] = _profiling["dir"], None
    jax.profiler.stop_trace()
    _trace.emit("profiler", action="stop", out_dir=out)


@contextlib.contextmanager
def profiler_session(out_dir: str | None = None):
    """Context-manager form of the start/stop pair."""
    started = maybe_start_profiler(out_dir)
    try:
        yield started
    finally:
        if started:
            stop_profiler()
