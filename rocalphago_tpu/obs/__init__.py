"""Unified observability: tracing spans, metrics registry, reports.

The resilience layers (``rocalphago_tpu.runtime``, PR 1/2) made the
stack survive faults; this package makes its behavior *visible*.
Three stdlib-only pieces share one output channel — the existing
``metrics.jsonl`` stream written by
:class:`~rocalphago_tpu.io.metrics.MetricsLogger`:

* :mod:`.trace` — nested wall-clock ``span(name)`` context managers
  emitting structured ``span`` records (duration, parent path, tags).
  Every trainer wraps its iteration phases (data/step/eval/
  checkpoint), so a run directory's ``metrics.jsonl`` carries a full
  per-phase time breakdown that ``scripts/obs_report.py`` renders.
* :mod:`.registry` — process-wide counters, gauges, and
  bounded-bucket histograms with a deterministic snapshot API and
  Prometheus-style text rendering. The hot paths (device search
  chunks, self-play, the serving ladder) record here with no logger
  plumbing; the GTP ``rocalphago-stats`` probe returns the live
  snapshot.
* :mod:`.jaxobs` — compile-event tracking for jitted entry points
  (recompiles surface as named ``compile`` events + counters) and an
  opt-in ``jax.profiler`` trace capture gated by env var/flag.

Record schema and report format: docs/OBSERVABILITY.md.
"""

from rocalphago_tpu.obs import registry, trace  # noqa: F401
from rocalphago_tpu.obs.registry import (  # noqa: F401
    REGISTRY,
    counter,
    gauge,
    histogram,
    render_text,
    reset,
    snapshot,
    timed,
)
from rocalphago_tpu.obs.trace import (  # noqa: F401
    configure,
    current_path,
    emit,
    span,
    where,
)
