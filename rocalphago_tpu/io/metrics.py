"""Structured training metrics: JSONL stream + stdout.

Parity: the reference's observability is the Keras progress bar plus
``metadata.json`` (SURVEY.md §5 "Metrics / logging"). The rebuild logs
one JSON object per event to ``metrics.jsonl`` (step, loss, accuracy,
games/min, …) — greppable, plottable, and the format ``bench.py``
reuses. TensorBoard is intentionally not a dependency; the JSONL is
trivially convertible.

The same stream carries the observability subsystem's records
(``span``/``compile``/``registry`` events — see
:mod:`rocalphago_tpu.obs` and docs/OBSERVABILITY.md), emitted through
:meth:`MetricsLogger.write` (file-only: high-rate telemetry must not
spam the console ``log`` echoes).

Strict-parser contract: non-finite floats (NaN/Inf — e.g. the
``evaluate`` path's empty-split NaN) are sanitized to JSON ``null``
before serialization, so no line ever contains a bare ``NaN``/
``Infinity`` token (valid for ``json.loads`` only by a non-standard
extension many parsers reject). ``json.dumps`` runs with
``allow_nan=False`` to make the guarantee load-bearing.
"""

from __future__ import annotations

import json
import math
import os
import time


# the crash-tolerant reader matching this module's writer; it lives
# in runtime (stdlib-only) so light scripts can import it without
# pulling this package's jax/orbax dependencies
from rocalphago_tpu.runtime.jsonl import read_jsonl  # noqa: F401
# instrumented-lock factory (plain threading.Lock unless
# ROCALPHAGO_LOCKCHECK=1) — also stdlib-only
from rocalphago_tpu.analysis import lockcheck


def sanitize(value):
    """Recursively replace non-finite floats with None (JSON null);
    tuples become lists (their JSON form anyway)."""
    if isinstance(value, float):           # incl. np.float64 subclass
        return value if math.isfinite(value) else None
    if isinstance(value, dict):
        return {k: sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [sanitize(v) for v in value]
    return value


class MetricsLogger:
    """Line-buffered JSONL event stream (``with``-able: closing is
    ``close``; a crashed process that never exits the ``with`` loses
    at most the in-flight line — tests/test_runtime.py pins that).

    THREAD-SAFE: one logger is shared by every session of a serving
    pool (degradation events, watchdog stalls, spans from N session
    threads plus the evaluator's dispatcher), so emission is a single
    ``write()`` call under a lock — interleaved events can never tear
    each other's lines, and ``close`` can race an emit without
    writing to a closed file (pinned by the concurrent-emit test in
    ``tests/test_obs.py``). Serialization happens OUTSIDE the lock;
    only the file write is held."""

    def __init__(self, path: str | None, echo: bool = True):
        self.path = path
        self.echo = echo
        self._lock = lockcheck.make_lock("MetricsLogger._lock")
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a", buffering=1)  # guarded-by: self._lock
        else:
            self._f = None                          # guarded-by: self._lock

    def write(self, event: str, **fields) -> None:
        """File-only emission (no console echo) — the channel for
        high-rate telemetry (spans, compile events, registry
        snapshots)."""
        rec = sanitize({"event": event, "time": time.time(), **fields})
        line = json.dumps(rec, allow_nan=False) + "\n"
        with self._lock:
            if self._f:
                self._f.write(line)

    def log(self, event: str, **fields) -> None:
        fields = sanitize(fields)
        self.write(event, **fields)
        if self.echo:
            shown = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items())
            print(f"[{event}] {shown}", flush=True)

    def close(self) -> None:
        with self._lock:
            if self._f:
                self._f.close()
                self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
