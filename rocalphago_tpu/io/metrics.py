"""Structured training metrics: JSONL stream + stdout.

Parity: the reference's observability is the Keras progress bar plus
``metadata.json`` (SURVEY.md §5 "Metrics / logging"). The rebuild logs
one JSON object per event to ``metrics.jsonl`` (step, loss, accuracy,
games/min, …) — greppable, plottable, and the format ``bench.py``
reuses. TensorBoard is intentionally not a dependency; the JSONL is
trivially convertible.
"""

from __future__ import annotations

import json
import os
import time


# the crash-tolerant reader matching this module's writer; it lives
# in runtime (stdlib-only) so light scripts can import it without
# pulling this package's jax/orbax dependencies
from rocalphago_tpu.runtime.jsonl import read_jsonl  # noqa: F401


class MetricsLogger:
    def __init__(self, path: str | None, echo: bool = True):
        self.path = path
        self.echo = echo
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a", buffering=1)
        else:
            self._f = None

    def log(self, event: str, **fields) -> None:
        rec = {"event": event, "time": time.time(), **fields}
        if self._f:
            self._f.write(json.dumps(rec) + "\n")
        if self.echo:
            shown = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items())
            print(f"[{event}] {shown}", flush=True)

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None
