"""Checkpointing (Orbax), metadata.json, and metrics (SURVEY.md §5)."""

from rocalphago_tpu.io.checkpoint import (  # noqa: F401
    MetadataWriter,
    TrainCheckpointer,
    pack_rng,
    unpack_rng,
)
from rocalphago_tpu.io.metrics import MetricsLogger  # noqa: F401
