"""Checkpoint / resume via Orbax.

Parity: the reference's per-epoch Keras weight dumps
(``weights.NNNNN.hdf5``) + ``metadata.json`` progress file +
``shuffle.npz`` persisted split (SURVEY.md §5 "Checkpoint / resume").
Here a checkpoint is one Orbax step directory holding the full training
pytree — params, optimizer state, step, PRNG key bits, data cursor — so
resume is exact (same shuffle order, same augmentation stream), and
saves are async so the TPU never idles on serialization.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np
import orbax.checkpoint as ocp

from rocalphago_tpu.runtime.atomic import atomic_write_json
from rocalphago_tpu.runtime.retries import retry


def pack_rng(key: jax.Array) -> jax.Array:
    """New-style PRNG key → raw uint32 bits (checkpointable)."""
    return jax.random.key_data(key)


def unpack_rng(bits) -> jax.Array:
    import jax.numpy as jnp
    return jax.random.wrap_key_data(jnp.asarray(bits, jnp.uint32))


class TrainCheckpointer:
    """Orbax ``CheckpointManager`` with a pytree per step."""

    def __init__(self, directory: str, max_to_keep: int | None = None):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True))

    # transient-failure backoff around the filesystem/RPC surface:
    # Orbax writes are atomic (tmp dir + rename at finalize, so an
    # interrupted save is invisible to latest_step) but a flaky
    # shared filesystem can still fail the dispatch itself
    @retry(max_attempts=3, base_delay=0.5)
    def save(self, step: int, tree, wait: bool = False) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(tree))
        if wait:
            self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    @retry(max_attempts=3, base_delay=0.5)
    def _restore_step(self, template, step: int):
        return self.manager.restore(
            step, args=ocp.args.StandardRestore(template))

    def restore(self, template, step: int | None = None):
        """Restore into the structure/shardings of ``template``
        (pass the freshly-initialized training pytree).

        Fallback: a finalized-then-damaged newest step (a torn
        directory on a flaky shared filesystem — files missing or
        truncated AFTER Orbax's atomic rename) would otherwise
        exhaust the transient retries and kill the resume. With no
        explicit ``step`` requested, each failing step logs a warning
        and restore falls back to the next-older retained step; an
        explicitly requested step still raises (the caller asked for
        THAT step, silently serving another would be a lie)."""
        if step is not None:
            return self._restore_step(template, step), step
        steps = sorted(self.manager.all_steps(), reverse=True)
        if not steps:
            return None, None
        last_exc = None
        for i, s in enumerate(steps):
            try:
                return self._restore_step(template, s), s
            except Exception as e:  # noqa: BLE001 — warned + fall back
                last_exc = e
                older = steps[i + 1] if i + 1 < len(steps) else None
                tail = (f"; falling back to step {older}"
                        if older is not None
                        else "; no older step retained")
                print(f"checkpoint: step {s} failed to restore "
                      f"({type(e).__name__}: {e}){tail}",
                      file=sys.stderr)
        raise last_exc

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


class MetadataWriter:
    """Append-per-epoch ``metadata.json`` (reference
    ``MetadataWriterCallback`` parity — tooling reads this file).

    ``enabled=False`` (non-coordinator processes in a multi-host run)
    keeps the in-memory record but never touches the filesystem."""

    def __init__(self, path: str, header: dict | None = None,
                 enabled: bool = True):
        self.path = path
        self.enabled = enabled
        self.data = None
        if enabled and os.path.exists(path):
            try:
                with open(path) as f:
                    self.data = json.load(f)
            except ValueError:
                # a torn file from a pre-atomic-writes crash; the new
                # writes go through atomic_write_json so this can only
                # be legacy damage — start a fresh record rather than
                # poisoning the resumed run
                print(f"metadata: {path} is corrupt, starting fresh",
                      file=sys.stderr)
        if self.data is None:
            self.data = dict(header or {})
            self.data.setdefault("epochs", [])
            self._flush()
        self.data.setdefault("epochs", [])

    def record_epoch(self, entry: dict) -> None:
        entry = dict(entry, wall_time=time.time())
        # resume overwrite semantics: re-running an iteration/epoch
        # after a crash REPLACES its provisional record, so a resumed
        # run's metadata converges to the uninterrupted run's (the
        # chaos tests compare the two)
        for key in ("iteration", "epoch"):
            if key in entry:
                self.data["epochs"] = [
                    e for e in self.data["epochs"]
                    if e.get(key) != entry[key]]
                break
        self.data["epochs"].append(entry)
        self._flush()

    def update(self, **fields) -> None:
        self.data.update(fields)
        self._flush()

    def _flush(self) -> None:
        if not self.enabled:
            return
        atomic_write_json(self.path, self.data)
