#!/bin/bash
# TPU window hunter v2 (round 3). The v1 hunter ran the expensive
# headline bench FIRST in every healthy window; with the tunnel
# flapping (minutes of health between ~25-min init-hang outages) that
# starves every other measurement: the 03:16 window was spent on a
# headline attempt whose seeding/probe programs each paid a fresh
# compile, hung when the window closed mid-run, and banked nothing.
# v2 fixes the ordering and the granularity:
#  - steps are COST-ASCENDING and fine-grained (one batch size per
#    step), so even a 2-minute window banks a number;
#  - the headline runs LAST, first with a FIXED batch/chunk config
#    (one compiled program; batch picked from the day's on-chip
#    self-play rates in results.jsonl), then — stretch goal — the
#    driver-equivalent adaptive run;
#  - same kill-safety protocol as v1: a 90s-bounded init+matmul probe
#    gates every step (a timeout-kill can only land on a client hung
#    in backend init — nothing in flight, cannot wedge the tunnel);
#    no step is ever killed past its probe; completed steps checkpoint
#    to $STATE so restarts resume.
#
# Usage: bash scripts/tpu_window_hunter2.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-benchmarks/tpu_hunt2_r3}
STATE="$LOG/done"
mkdir -p "$LOG" "$STATE"

probe() {
    # the shared kill-safe probe: rc 0 = healthy TPU, 3 = backend
    # alive but startup ate the dispatch window (counts as alive),
    # 4 = matmul ran on the WRONG platform (silent CPU fallback —
    # must NOT open the window, or every step would bank host-CPU
    # numbers as TPU results), timeout/other = down. The timeout-kill
    # is safe: tpu_probe refuses to dispatch after a slow startup, so
    # a kill can only land on a client with nothing in flight.
    timeout 90 python scripts/tpu_probe.py >>"$LOG/probe.log" 2>&1
    rc=$?
    echo "probe rc=$rc [$(date +%H:%M:%S)]" >>"$LOG/probe.log"
    [ $rc -eq 0 ] || [ $rc -eq 3 ]
}

# a step that keeps failing must not starve everything behind it
# (cost-ascending order means the headline is LAST): after FAILCAP
# consecutive failures a step is skipped for the rest of the hunt.
FAILCAP=${FAILCAP:-4}

fails() { cat "$STATE/fail_$1" 2>/dev/null || echo 0; }

skippable() {     # done, or failed out
    [ -e "$STATE/$1" ] && return 0
    [ "$(fails "$1")" -ge "$FAILCAP" ]
}

run() {
    name=$1; shift
    skippable "$name" && return 0
    echo "=== $name: $* [$(date +%H:%M:%S)]" >>"$LOG/hunt.log"
    "$@" >>"$LOG/hunt.log" 2>&1
    step_rc=$?      # probe() below clobbers the shared rc variable
    echo "    rc=$step_rc [$(date +%H:%M:%S)]" >>"$LOG/hunt.log"
    if [ $step_rc -eq 0 ]; then
        touch "$STATE/$name"
        sleep 15
        return 0
    fi
    sleep 15
    # count the failure ONLY if the tunnel is still alive — a step
    # that died because the window closed (the common case: bench.py
    # falls back to CPU, the platform grep fails) must not burn the
    # step's FAILCAP; outage failures retry in later windows. The
    # probe doubles as the loop's post-step health check (the caller
    # breaks on our nonzero rc and reprobes at the top).
    if probe; then
        echo $(( $(fails "$name") + 1 )) >"$STATE/fail_$name"
        if [ "$(fails "$name")" -ge "$FAILCAP" ]; then
            echo "    $name failed out after $FAILCAP tries" \
                >>"$LOG/hunt.log"
        fi
    else
        echo "    $name failure not counted (tunnel down)" \
            >>"$LOG/hunt.log"
    fi
    return $step_rc
}

# NOTE: the devmcts*/selfplay*/headline steps now emit the pipelined
# -vs-sync dispatch A/B (pipeline_depth + host_gap_frac fields in
# results.jsonl / the headline JSON line; docs/PERFORMANCE.md) — no
# extra steps needed, the A/B shares each step's compiled programs.
# ROCALPHAGO_PIPELINE_DEPTH=0 forces the old sync dispatch hunt-wide.

SPECS=benchmarks/tpu_extra_r3   # tiny 9x9 nets for the tournament smoke

# spec JSONs reference sibling .flax.msgpack weight files — regenerate
# unless all four exist (generation is host-side CPU; never touches
# the tunnel)
make_specs() {
    [ -f "$SPECS/p9.json" ] && [ -f "$SPECS/p9.flax.msgpack" ] && \
    [ -f "$SPECS/v9.json" ] && [ -f "$SPECS/v9.flax.msgpack" ] && return 0
    mkdir -p "$SPECS"
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m \
        rocalphago_tpu.models.specs policy --out "$SPECS/p9.json" \
        --board 9 --layers 3 --filters 32 >>"$LOG/hunt.log" 2>&1 && \
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m \
        rocalphago_tpu.models.specs value --out "$SPECS/v9.json" \
        --board 9 --layers 3 --filters 32 >>"$LOG/hunt.log" 2>&1
}
make_specs

# encode_* steps: the encode-path A/B (benchmarks/bench_encode.py;
# docs/PERFORMANCE.md "Encode path") — one config per step so a short
# window still banks a decidable pair. They supersede the old
# ladder1-8 bench_preprocess steps: bench_encode records the same
# sweep WITH the gating axis and a per-position-µs field that
# bench_report renders. The CPU sides are already in results.jsonl;
# these rows decide the TPU defaults.
STEPS="train64 train256 train1024 engine_dense engine_scatter rollout \
preprocess chase_xla chase_pls encode_base encode_shared4 \
encode_shared1 encode_shared2 encode_shared8 encode_split4 \
encode_pallas encode_incr_seq encode_incr_batch encode_incr_selfplay \
encode_incr_tight encode_noladder_net \
devmcts9 devmcts_gumbel serve_small serve_cache serve_fleet \
multisize_serve \
zero_actor_learner zero_econ \
selfplay16 \
selfplay64 selfplay256 bisect mcts19 mcts19r rl engine_trace \
train_trace preprocess_trace tournament headline_sized headline"
n_steps=$(echo $STEPS | wc -w)
deadline=$(( $(date +%s) + ${HUNT_BUDGET_S:-36000} ))

while [ "$(date +%s)" -lt "$deadline" ]; do
    n_done=0; remaining=0
    for s in $STEPS; do
        [ -e "$STATE/$s" ] && n_done=$((n_done + 1))
        skippable "$s" || remaining=$((remaining + 1))
    done
    if [ "$remaining" -eq 0 ]; then
        echo "hunt complete: $n_done/$n_steps done (rest failed out)" \
            "[$(date +%H:%M:%S)]" >>"$LOG/hunt.log"
        break
    fi
    if ! probe; then
        sleep 45
        continue
    fi
    echo "--- window open ($n_done/$n_steps done) [$(date +%H:%M:%S)]" \
        >>"$LOG/hunt.log"
    for s in $STEPS; do
        skippable "$s" && continue
        case $s in
            train64)     run train64     python benchmarks/bench_train.py --batch 64 --reps 3 ;;
            train256)    run train256    python benchmarks/bench_train.py --batch 256 --reps 3 ;;
            train1024)   run train1024   python benchmarks/bench_train.py --batch 1024 --reps 3 ;;
            engine_dense)   run engine_dense   env ROCALPHAGO_ENGINE_DENSE=1 python benchmarks/bench_engine.py --batch 1024 --moves 64 --reps 2 ;;
            engine_scatter) run engine_scatter env ROCALPHAGO_ENGINE_DENSE=0 python benchmarks/bench_engine.py --batch 1024 --moves 64 --reps 2 ;;
            rollout)     run rollout     python benchmarks/bench_rollout.py --reps 3 ;;
            preprocess)  run preprocess  python benchmarks/bench_preprocess.py --reps 2 ;;
            chase_xla)   run chase_xla   python benchmarks/bench_chase.py --reps 2 ;;
            chase_pls)   run chase_pls   env ROCALPHAGO_PALLAS_CHASE=1 python benchmarks/bench_chase.py --reps 2 ;;
            encode_base)    run encode_base    python benchmarks/bench_encode.py --gating split --phase1 40 --reps 2 ;;
            encode_shared4) run encode_shared4 python benchmarks/bench_encode.py --gating shared --phase1 4 --skip-noladder --reps 2 ;;
            encode_shared1) run encode_shared1 python benchmarks/bench_encode.py --gating shared --phase1 1 --skip-noladder --reps 2 ;;
            encode_shared2) run encode_shared2 python benchmarks/bench_encode.py --gating shared --phase1 2 --skip-noladder --reps 2 ;;
            encode_shared8) run encode_shared8 python benchmarks/bench_encode.py --gating shared --phase1 8 --skip-noladder --reps 2 ;;
            encode_split4)  run encode_split4  python benchmarks/bench_encode.py --gating split --phase1 4 --skip-noladder --reps 2 ;;
            encode_pallas)  run encode_pallas  python benchmarks/bench_encode.py --gating shared --phase1 4 --impl pallas --skip-noladder --reps 2 ;;
            # encode_incr*: the PR-6 incremental-encode A/B on chip —
            # sequential real-game-tail µs/pos (encode_incr vs
            # encode_scratch rows), the batched-lockstep pair that
            # decides selfplay.incremental_default for TPU, and the
            # fused self-play segment with the cache carry threaded
            # (ROCALPHAGO_ENCODE_INCR=1 forces the delta path)
            encode_incr_seq)   run encode_incr_seq   python benchmarks/bench_encode.py --trajectory --traj-plies 100 --traj-skip 60 --reps 2 ;;
            encode_incr_batch) run encode_incr_batch python benchmarks/bench_encode.py --trajectory --traj-plies 30 --traj-skip 60 --traj-batch 256 --reps 2 ;;
            encode_incr_selfplay) run encode_incr_selfplay env ROCALPHAGO_ENCODE_INCR=1 python benchmarks/bench_selfplay.py --batch-sweep 64 --reps 2 ;;
            # encode_incr_tight: the tightened-invalidation A/B —
            # tight footprints + region keys (the default) vs the
            # legacy wide-blanket footprint, same sequential tail;
            # the encode_incr_cascade rows carry the per-ply
            # invalidation/flip counts each side. encode_noladder_net:
            # the ladder-free feature-spec path's floor on chip.
            encode_incr_tight) run encode_incr_tight sh -c 'python benchmarks/bench_encode.py --trajectory --traj-plies 100 --traj-skip 60 --reps 2 && ROCALPHAGO_LADDER_FOOT=wide python benchmarks/bench_encode.py --trajectory --traj-plies 100 --traj-skip 60 --reps 2' ;;
            encode_noladder_net) run encode_noladder_net python benchmarks/bench_encode.py --gating shared --phase1 4 --reps 2 ;;
            devmcts9)    run devmcts9    python benchmarks/bench_device_mcts.py --board 9 --sims 32 --reps 2 ;;
            devmcts_gumbel) run devmcts_gumbel python benchmarks/bench_device_mcts.py --board 9 --sims 32 --gumbel --reps 2 ;;
            # serve_*: the cross-game serving sweep (bench_serve.py;
            # docs/SERVING.md) — aggregate moves/sec + p99 genmove
            # latency vs concurrent sessions, batched evaluator vs
            # the per-session unbatched A/B. Split small/fleet so a
            # short window still banks the decidable low-count pair;
            # serve_fleet is the 64→256 continuation the 1-core CPU
            # host saturates out of; the threaded latency arm is
            # host-bound, skip on chip time.
            serve_small) run serve_small python benchmarks/bench_serve.py --sessions 1,8 --reps 2 --skip-threaded ;;
            # serve_cache: the transposition-cache A/B on chip
            # (bench_serve.py --cache-ab; docs/SERVING.md "Evaluation
            # cache") — opening-replay fleet moves/s cache off vs on
            # with the measured hit rate; bench_report keys the rows
            # by the cache field
            serve_cache) run serve_cache python benchmarks/bench_serve.py --cache-ab --sessions 16 --reps 3 ;;
            serve_fleet) run serve_fleet python benchmarks/bench_serve.py --sessions 64,256 --reps 2 --skip-threaded ;;
            # multisize_serve: the PR-12 one-checkpoint ladder
            # (bench_multisize.py; docs/MULTISIZE.md) — per-size
            # moves/s through one MultiSizePool plus the
            # pool-per-size A/B (params ×N, compiles delta).
            multisize_serve) run multisize_serve python benchmarks/bench_multisize.py --sizes 9,13,19 --sessions 8 --reps 2 --ab ;;
            # zero_actor_learner: the PR-11 actor/learner split on
            # chip (bench_zero_scale.py; docs/SCALE.md) — ingest
            # games/min, learner steps/s and learner-idle fraction vs
            # actor count, against the sync baseline's selfplay_frac.
            # --no-force-host-devices keeps the real TPU mesh.
            zero_actor_learner) run zero_actor_learner python benchmarks/bench_zero_scale.py --no-force-host-devices --actors 1,2,4 --steps 4 --reps 2 ;;
            # zero_econ: the PR-13 self-play economics A/B on chip
            # (bench_selfplay.py --cap-ab; docs/PERFORMANCE.md
            # "Self-play economics") — MCTS self-play games/min at
            # cap_p 1.0 (all-full baseline) vs 0.25 with the cheap
            # cap at sims/4; bench_report keys the rows by cap_p.
            zero_econ) run zero_econ python benchmarks/bench_selfplay.py --cap-ab --board 9 --batch 64 --sims 64 --move-limit 40 --reps 2 ;;
            bisect)      run bisect      python scripts/tpu_crash_bisect.py --log "$LOG/bisect.jsonl" ;;
            selfplay16)  run selfplay16  python benchmarks/bench_selfplay.py --batch-sweep 16 --reps 2 ;;
            selfplay64)  run selfplay64  python benchmarks/bench_selfplay.py --batch-sweep 64 --reps 2 ;;
            selfplay256) run selfplay256 python benchmarks/bench_selfplay.py --batch-sweep 256 --reps 2 ;;
            mcts19)      run mcts19      python benchmarks/bench_mcts.py --board 19 --playouts 48 --reps 2 ;;
            mcts19r)     run mcts19r     python benchmarks/bench_mcts.py --board 19 --playouts 48 --lmbda 0.5 --device-rollout --reps 2 ;;
            rl)          run rl          python benchmarks/bench_rl.py --batch 16 --moves 100 --chunk 10 --reps 1 ;;
            engine_trace)     run engine_trace     python benchmarks/bench_engine.py --batch 1024 --moves 64 --reps 1 --profile "$LOG/trace_engine" ;;
            train_trace)      run train_trace      python benchmarks/bench_train.py --batch 1024 --reps 1 --profile "$LOG/trace_train" ;;
            preprocess_trace) run preprocess_trace python benchmarks/bench_preprocess.py --reps 1 --profile "$LOG/trace_preprocess" ;;
            tournament)  run tournament  python -m rocalphago_tpu.interface.tournament "mcts:$SPECS/p9.json:$SPECS/v9.json" "greedy:$SPECS/p9.json" --games 1 --board 9 --playouts 16 --move-limit 60 --log "$LOG/tournament.jsonl" ;;
            headline_sized)
                # bench.py self-sizes batch/chunk from the same-day
                # selfplay_ply_program records the selfplay* steps
                # above banked (one compiled program, no probe)
                run headline_sized env _GRAFT_BENCH_BUDGET_S=420 \
                    bash -c 'python bench.py | tail -1 | tee -a '"$LOG"'/hunt.log | grep -q "\"platform\": \"tpu\""' ;;
            headline)
                # the driver-equivalent ADAPTIVE run (stretch goal):
                # self-sizing off so the probe path itself gets
                # exercised on hardware
                run headline env _GRAFT_BENCH_MAX_MOVES=300 _GRAFT_BENCH_NO_SELF_SIZE=1 \
                    bash -c 'python bench.py | tail -1 | tee -a '"$LOG"'/hunt.log | grep -q "\"platform\": \"tpu\""' ;;
        esac || break   # step failed -> backend likely died -> reprobe
        probe || break
    done
done
echo "hunter v2 exiting: $(ls "$STATE" | grep -cv '^fail_')/$n_steps done [$(date +%H:%M:%S)]" >>"$LOG/hunt.log"
