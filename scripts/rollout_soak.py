"""Rollout soak: the zero-downtime proof for live model rollout.

Runs N in-process :class:`~rocalphago_tpu.gateway.server.
GatewayServer` replicas (tiny nets; every pool shares ONE compiled
searcher) behind a :class:`~rocalphago_tpu.rollout.router.
RolloutRouter` and proves the subsystem's headline claims
(docs/ROLLOUT.md) under storm traffic:

* **promotions land mid-storm with zero downtime** — every round a
  new version goes through the REAL promotion pipe
  (``ParamsPublisher`` spill → ``SpillWatcher`` → ``HotSwapper`` →
  every replica pool) while games are in flight; live games keep
  playing and ``jax_compiles_total`` stays FLAT across every swap
  (params are jit arguments at fixed shapes — a swap is a pointer
  flip, never a compile);
* **kills stay inside the fault wall** — a ``kill@gateway.conn``
  plan aborts backend connections mid-conversation; every abort is
  a typed error, ``requests.unhandled`` stays ZERO fleet-wide;
* **drain-aware failover is transparent** — each round one replica
  is drained and restarted UNDER LOAD; its routed games fail over
  (reconnect, game-log replay, ≤ 1 retried genmove per failover)
  and the fleet converges back to one params version;
* **the Wilson gate rejects a weak canary** — a deliberately weak
  candidate is staged on the canary pool, loses its decided games,
  and is auto-rolled-back (lb < 0.5) with the incumbent's pointer
  untouched;
* **sheds reconcile exactly** — router-cap refusals counted
  client-side == ``router.stats()`` == the
  ``router_connections_total{result="shed"}`` delta scraped off the
  router's ``/metrics``;
* **after the storm a fault-free GATE round runs clean**, and
  **SIGTERM drains the whole federation** (router + every replica +
  every pool) to zero live conns, exit 0.

Kill rounds and bounce rounds alternate: kills make a replica's
typed fault wall observable, bounces make failover deterministic
(no fault plan racing the failover replay).

Tier-1 smoke: ``tests/test_rollout.py`` runs this with
``--min-kills 1 --swaps 1``; the @slow soak runs the defaults.

Usage::

    JAX_PLATFORMS=cpu python scripts/rollout_soak.py --out /tmp/soak
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile

sys.path.insert(0, ".")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="run dir for metrics.jsonl + spill + "
                    "summary.json (default: a fresh temp dir)")
    ap.add_argument("--board", type=int, default=5)
    ap.add_argument("--sims", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=2,
                    help="federated gateway replicas (>= 2 so a "
                    "bounce always has a failover destination)")
    ap.add_argument("--conns", type=int, default=6,
                    help="concurrent connections per storm round "
                    "(keep it above --max-conns so rounds shed)")
    ap.add_argument("--max-conns", type=int, default=3,
                    help="the ROUTER's connection cap (each replica "
                    "gets ample headroom above it)")
    ap.add_argument("--moves", type=int, default=4,
                    help="genmoves per connection per round")
    ap.add_argument("--seed", type=int, default=7,
                    help="kill-schedule seed (per-barrier draws)")
    ap.add_argument("--p-kill", type=float, default=0.15,
                    help="per-request kill probability at the "
                    "gateway.conn barrier (kill rounds only)")
    ap.add_argument("--plan", default=None,
                    help="override the kill-round fault plan")
    ap.add_argument("--min-kills", type=int, default=3,
                    help="soak until at least this many backend "
                    "connections were kill-aborted")
    ap.add_argument("--swaps", type=int, default=2,
                    help="minimum mid-storm promotions to land")
    ap.add_argument("--canary-games", type=int, default=6,
                    help="decided games before the Wilson gate "
                    "decides the weak canary")
    ap.add_argument("--deadline-s", type=float, default=240.0,
                    help="hard wall-clock bound on the whole soak")
    return ap


def main() -> int:
    args = build_parser().parse_args()
    if args.replicas < 2:
        print("rollout_soak: --replicas must be >= 2",
              file=sys.stderr)
        return 2
    out_dir = args.out or tempfile.mkdtemp(prefix="rollout_soak_")
    os.makedirs(out_dir, exist_ok=True)
    spill_dir = os.path.join(out_dir, "spill")
    os.makedirs(spill_dir, exist_ok=True)

    import threading
    import time
    import urllib.request

    import jax

    from rocalphago_tpu.gateway.client import run_load
    from rocalphago_tpu.gateway.server import GatewayServer
    from rocalphago_tpu.io.metrics import MetricsLogger
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.obs import registry as obs_registry
    from rocalphago_tpu.rollout.canary import CanaryController
    from rocalphago_tpu.rollout.hotswap import HotSwapper, SpillWatcher
    from rocalphago_tpu.rollout.router import (
        Replica,
        RolloutRouter,
        RouterHTTP,
    )
    from rocalphago_tpu.runtime import faults
    from rocalphago_tpu.runtime.supervisor import Supervisor
    from rocalphago_tpu.serve.sessions import ServePool
    from rocalphago_tpu.training.actor import ParamsPublisher

    plan = (args.plan if args.plan is not None else
            f"kill@gateway.conn:p={args.p_kill},seed={args.seed}")
    metrics = MetricsLogger(os.path.join(out_dir, "metrics.jsonl"),
                            echo=False)
    metrics.log("rollout_soak", phase="start", plan=plan,
                replicas=args.replicas, conns=args.conns,
                max_conns=args.max_conns, min_kills=args.min_kills,
                swaps=args.swaps, seed=args.seed)
    # compile events into metrics.jsonl: a red compiles_flat check
    # then NAMES the entry that compiled mid-storm
    from rocalphago_tpu.obs import trace
    trace.configure(metrics)

    def compiles() -> int:
        return sum(v for k, v in obs_registry.REGISTRY.snapshot()
                   ["counters"].items()
                   if k.startswith("jax_compiles_total"))

    def shed_counter() -> int:
        return int(obs_registry.REGISTRY.snapshot()["counters"].get(
            'router_connections_total{result="shed"}', 0))

    def scale(params, s):
        return jax.tree.map(lambda x: x * s, params)

    # ------------------------------------------------- the tiny rig
    feats = ("board", "ones")
    pol = CNNPolicy(feats, board=args.board, layers=1,
                    filters_per_layer=2)
    val = CNNValue(feats + ("color",), board=args.board, layers=1,
                   filters_per_layer=2)
    backend_cap = max(args.conns, args.max_conns) + 2
    pools = [ServePool(val, pol, n_sim=args.sims,
                       max_sessions=backend_cap,
                       batch_sizes=(1, 2), max_wait_us=2000.0,
                       metrics=metrics)]
    pools[0].warm()
    for _ in range(1, args.replicas):
        pools.append(ServePool(val, pol, n_sim=args.sims,
                               max_sessions=backend_cap,
                               batch_sizes=(1, 2),
                               max_wait_us=2000.0,
                               searcher=pools[0].search))
    canary = CanaryController(pools[0], fraction=0.5,
                              min_games=args.canary_games,
                              metrics=metrics)
    servers = [GatewayServer(pools[0], max_conns=backend_cap,
                             metrics=metrics, canary=canary).start()]
    for p in pools[1:]:
        servers.append(GatewayServer(p, max_conns=backend_cap,
                                     metrics=metrics).start())
    reps = [Replica("127.0.0.1", s.port, gateway=s, name=f"r{i}")
            for i, s in enumerate(servers)]
    router = RolloutRouter(reps, max_conns=args.max_conns,
                           metrics=metrics).start()
    http = RouterHTTP(router).start()
    sup = Supervisor(metrics=metrics)
    sigterm_installed = sup.install_sigterm()

    # the real promotion pipe: publisher spill -> watcher -> swapper
    swapper = HotSwapper(*pools, metrics=metrics)
    publisher = ParamsPublisher(spill_dir=spill_dir)
    watcher = SpillWatcher(spill_dir, swapper, pol.params,
                           val.params, metrics=metrics)

    # stats lost when a bounced server instance is replaced
    retired = {"kills": 0, "unhandled": 0}

    def fleet(key_a: str, key_b: str) -> int:
        live = sum(s.stats()[key_a][key_b] for s in servers)
        return live + retired.get(key_b, 0)

    def settle(timeout_s: float = 10.0) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if (router.stats()["conns"]["live"] == 0
                    and all(s.stats()["conns"]["live"] == 0
                            for s in servers)):
                return
            time.sleep(0.05)

    def load(conns: int) -> dict:
        return run_load("127.0.0.1", router.port, conns=conns,
                        moves=args.moves, timeout=60.0)

    def bounce(box: dict) -> None:
        """Drain + restart the LAST replica under load: its routed
        games must fail over; the restarted instance rejoins."""
        idx = len(servers) - 1
        old = servers[idx]
        port = old.port
        old.drain(reason="soak_bounce", timeout=2.0)
        st = old.stats()
        retired["kills"] += st["faults"]["kills"]
        retired["unhandled"] += st["requests"]["unhandled"]
        old.close()
        new = GatewayServer(pools[idx], port=port,
                            max_conns=backend_cap,
                            metrics=metrics).start()
        servers[idx] = new
        reps[idx].gateway = new
        router.poll_health_once()
        box["bounces"] = box.get("bounces", 0) + 1

    # --------------------------------------------------- the storm
    # priming round (fault-free, at the router cap — nothing sheds)
    # PLUS one warm-up trip through the whole promotion pipe, so
    # every code path — serving AND the eager param-perturbation
    # multiply — is compiled before the flatness baseline
    faults.install("")
    load(args.max_conns)
    settle()
    publisher.publish(scale(pol.params, 1.001),
                      scale(val.params, 1.001))
    watcher.poll_once()
    load(args.max_conns)
    settle()
    warm_swaps = swapper.swaps
    compiles_base = compiles()
    shed_base = shed_counter()

    totals = {"moves": 0, "sheds": 0, "disconnects": 0, "errors": 0}
    box: dict = {}
    rounds = 0
    t0 = time.monotonic()
    rc = 0
    gate = None
    convergence_ok = False
    canary_incumbent = None
    try:
        while time.monotonic() - t0 < args.deadline_s:
            if (totals["moves"] > 0 and totals["sheds"] > 0
                    and fleet("faults", "kills") >= args.min_kills
                    and swapper.swaps - warm_swaps >= args.swaps
                    and router.stats()["failovers"] >= 1):
                break
            # kill round: the typed fault wall, no bounce racing it.
            # install() re-parses the spec (hit count resets), so the
            # seed varies per round — otherwise every round would
            # replay the same dozen draws and a low p might never
            # fire no matter how long the soak runs
            round_plan = (args.plan if args.plan is not None else
                          f"kill@gateway.conn:p={args.p_kill},"
                          f"seed={args.seed + rounds}")
            faults.install(round_plan)
            out = run_load("127.0.0.1", router.port,
                           conns=args.conns, moves=args.moves,
                           timeout=60.0)
            for k in totals:
                totals[k] += out[k]
            faults.install("")
            settle()
            # bounce round: promotion + drain/restart UNDER load —
            # games long enough that the drain lands mid-flight
            result: dict = {}
            bounce_moves = max(args.moves, 12)

            def run(res=result):
                res.update(run_load("127.0.0.1", router.port,
                                    conns=args.conns,
                                    moves=bounce_moves,
                                    timeout=60.0))

            t = threading.Thread(target=run, name="soak-load")
            t.start()
            time.sleep(0.05)         # let games get in flight
            publisher.publish(
                scale(pol.params, 1.0 + 0.002 * (rounds + 1)),
                scale(val.params, 1.0 + 0.002 * (rounds + 1)))
            if not watcher.poll_once():
                metrics.log("rollout_soak", phase="swap_miss",
                            round=rounds)
            bounce(box)
            t.join(timeout=90.0)
            for k in totals:
                totals[k] += result.get(k, 0)
            rounds += 1
            settle()
    finally:
        faults.install("")
        settle()
        # fleet convergence: every replica serves the same version
        router.poll_health_once()
        versions = [r.params_version for r in reps]
        target = max((v for v in versions if v is not None),
                     default=None)
        convergence_ok = (target is not None
                          and router.await_convergence(target,
                                                       timeout=10.0))

        # ------------------------------- the weak canary, rejected
        canary_incumbent = pools[0].params_version
        try:
            canary.stage(scale(pol.params, 0.5),
                         scale(val.params, 0.5))
            metrics.log("rollout_soak", phase="canary_staged")
        except Exception as e:  # noqa: BLE001 — a red check, not a
            #                     harness crash
            metrics.log("rollout_soak", phase="canary_error",
                        error=f"{type(e).__name__}: {e}")

        # ------------------------------------------- the clean gate
        metrics.log("rollout_soak", phase="gate")
        try:
            gate = load(args.max_conns)
        except Exception as e:  # noqa: BLE001 — a red gate is a
            #                     verdict, not a harness crash
            metrics.log("rollout_soak", phase="gate_error",
                        error=f"{type(e).__name__}: {e}")
        settle()
        # the weak candidate loses its decided games -> the Wilson
        # gate must roll it back on its own (no manual rollback)
        if canary.stats()["state"] == "running":
            for i in range(args.canary_games):
                canary.record("candidate", won=(i == 0))
        canary_final = canary.stats()
        compiles_after = compiles()

        # -------------------------- scrape the sheds off /metrics
        metrics_shed = None
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/metrics",
                timeout=10.0).read().decode()
            for line in body.splitlines():
                if line.startswith(
                        'router_connections_total{result="shed"}'):
                    metrics_shed = int(float(line.split()[-1])) \
                        - shed_base
        except Exception as e:  # noqa: BLE001 — counted as a miss
            metrics.log("rollout_soak", phase="scrape_error",
                        error=f"{type(e).__name__}: {e}")

        # ------------------------------------- the SIGTERM drain
        if sigterm_installed:
            os.kill(os.getpid(), signal.SIGTERM)
            drain_t0 = time.monotonic()
            while (not sup.draining
                   and time.monotonic() - drain_t0 < 10.0):
                time.sleep(0.02)
        else:                  # not the main thread (test harness)
            sup.request_drain(reason="sigterm")
        router.drain(reason="sigterm")
        for s in servers:
            s.drain(reason="sigterm")
        router_final = router.stats()
        fleet_live = sum(s.stats()["conns"]["live"] for s in servers)
        fleet_unhandled = fleet("requests", "unhandled")
        kills = fleet("faults", "kills")
        pool_live = sum(p.stats()["sessions"]["live"] for p in pools)
        http.close()
        router.close()
        for s in servers:
            s.close()
        for p in pools:
            p.close()
        sup.restore_sigterm()
        faults.install(None)

    # ------------------------------------------------- the verdict
    failovers = router_final["failovers"]
    retried = router_final["retried_genmoves"]
    summary = {
        "plan": plan,
        "rounds": rounds,
        "replicas": args.replicas,
        "bounces": box.get("bounces", 0),
        "moves": totals["moves"],
        "sheds_client": totals["sheds"],
        "sheds_router": router_final["conns"]["shed"],
        "sheds_metrics": metrics_shed,
        "disconnects": totals["disconnects"],
        "client_errors": totals["errors"],
        "kills": kills,
        "unhandled": fleet_unhandled,
        "swaps": swapper.swaps,
        "storm_swaps": swapper.swaps - warm_swaps,
        "rollout_version": swapper.version,
        "converged": convergence_ok,
        "failovers": failovers,
        "spillovers": router_final["spillovers"],
        "retried_genmoves": retried,
        "compiles_base": compiles_base,
        "compiles_delta": compiles_after - compiles_base,
        "canary": canary_final,
        "canary_incumbent": canary_incumbent,
        "gate": gate,
        "drained": router_final["draining"],
        "live_conns_after_drain": router_final["conns"]["live"],
        "fleet_conns_after_drain": fleet_live,
        "pool_sessions_after_drain": pool_live,
        "sigterm_installed": sigterm_installed,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    checks = {
        "moves_landed": totals["moves"] > 0,
        "sheds_observed": totals["sheds"] > 0,
        "sheds_reconciled": (metrics_shed is not None
                             and totals["sheds"]
                             == router_final["conns"]["shed"]
                             == metrics_shed > 0),
        "min_kills": kills >= args.min_kills,
        "no_unhandled": fleet_unhandled == 0,
        "swaps_applied": swapper.swaps - warm_swaps >= args.swaps,
        "compiles_flat": compiles_after == compiles_base,
        "fleet_converged": convergence_ok,
        "failover_exercised": failovers >= 1,
        "retried_genmoves_bounded": retried <= failovers,
        "canary_rolled_back": (
            canary_final["state"] == "rolled_back"
            and canary_final["rollbacks"] == 1
            and canary_final["incumbent_version"]
            == canary_incumbent),
        "gate_green": (gate is not None and gate["sheds"] == 0
                       and gate["disconnects"] == 0
                       and gate["errors"] == 0
                       and gate["moves"]
                       == args.max_conns * args.moves),
        "drain_clean": (router_final["draining"]
                        and router_final["conns"]["live"] == 0
                        and fleet_live == 0
                        and pool_live == 0),
    }
    summary["checks"] = checks
    metrics.log("rollout_soak", phase="done", **{
        k: v for k, v in summary.items()
        if k not in ("checks", "canary", "gate")})
    metrics.close()
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))
    if rc == 0 and not all(checks.values()):
        rc = 1
    if rc:
        failed = [k for k, v in checks.items() if not v]
        print(f"rollout_soak: FAILED checks: {failed}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
