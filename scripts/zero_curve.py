"""Summarize a training.zero run's learning curves (VERDICT r3 #7).

Reads the run's ``metrics.jsonl`` and writes/prints a summary with
the value-head evidence the round-3 verdict asked for: the
win-prediction accuracy (``value_acc``) and per-ply MSE
(``value_mse``) trajectories, smoothed head/tail means, and a
flat-curve verdict. AlphaGo paper context: the published value net
reports MSE 0.226 (train) / 0.234 (test) on expert games — a
from-scratch toy run will not reach that, the question here is
whether the curve MOVES.

Usage: python scripts/zero_curve.py results/zero_scale_r4/run
       [--window 5] [--out summary.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rocalphago_tpu.runtime.jsonl import iter_jsonl  # noqa: E402


def load(run_dir: str) -> dict[str, list[dict]]:
    """One pass over metrics.jsonl → rows bucketed by event type.

    Tolerant reader: a run killed mid-write leaves at most one torn
    trailing line, which is skipped instead of crashing the summary
    (the whole point is summarizing interrupted runs)."""
    path = os.path.join(run_dir, "metrics.jsonl")
    by_event: dict[str, list[dict]] = {}
    try:
        with open(path) as f:
            for r in iter_jsonl(f):
                if isinstance(r.get("event"), str):
                    by_event.setdefault(r["event"], []).append(r)
    except OSError as e:
        raise SystemExit(f"cannot read {path}: {e}")
    return by_event


def curve(rows, key, window):
    xs = [float(r[key]) for r in rows if key in r]
    if not xs:
        return None
    w = max(1, min(window, len(xs) // 2 or 1))
    head = sum(xs[:w]) / w
    tail = sum(xs[-w:]) / w
    return {"first": round(xs[0], 4), "last": round(xs[-1], 4),
            "head_mean": round(head, 4), "tail_mean": round(tail, 4),
            "delta": round(tail - head, 4), "n": len(xs),
            "min": round(min(xs), 4), "max": round(max(xs), 4)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir")
    ap.add_argument("--window", type=int, default=5,
                    help="head/tail smoothing window (iterations)")
    ap.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    a = ap.parse_args(argv)
    by_event = load(a.run_dir)
    rows = by_event.get("iteration", [])
    if not rows:
        raise SystemExit(f"no iteration records in {a.run_dir}")

    summary = {"iterations": len(rows), "curves": {}}
    try:
        with open(os.path.join(a.run_dir, "metadata.json")) as f:
            cfg = json.load(f).get("config", {})
        summary["config"] = {k: cfg.get(k) for k in (
            "game_batch", "sims", "move_limit", "learning_rate",
            "gumbel", "dirichlet_alpha", "seed")}
        if cfg.get("game_batch"):
            summary["games"] = len(rows) * int(cfg["game_batch"])
    except (OSError, ValueError):
        pass
    for key in ("value_acc", "value_mse", "policy_loss",
                "black_win_rate", "mean_moves", "finished_rate"):
        c = curve(rows, key, a.window)
        if c is not None:
            summary["curves"][key] = c

    # evaluator-gate evidence (round-5 gated runs): promotion history
    # and the incumbent-vs-sampled-past ladder probes — the
    # monotonicity story VERDICT r4 #2 asked for, machine-readable
    gates = by_event.get("gate", [])
    ladders = by_event.get("ladder", [])
    if gates:
        summary["gate"] = {
            "matches": len(gates),
            "promotions": sum(bool(g.get("promoted")) for g in gates),
            "last": {k: gates[-1].get(k) for k in (
                "iteration", "promoted", "win_rate_a")},
        }
    if ladders:
        wins = [l for l in ladders
                if l.get("win_rate_a", 0.0) >= 0.5]
        summary["ladder"] = {
            "probes": len(ladders),
            "incumbent_wins": len(wins),
            "monotone_fraction": round(len(wins) / len(ladders), 4),
            "probe_rows": [{k: l.get(k) for k in (
                "iteration", "opponent", "win_rate_a")}
                for l in ladders],
        }

    acc = summary["curves"].get("value_acc")
    if acc:
        # the round-3 defect was a FLAT value curve; call it by number
        summary["value_head_verdict"] = (
            "learning" if acc["tail_mean"] - acc["head_mean"] > 0.03
            and acc["tail_mean"] > 0.55 else "flat")
    print(json.dumps(summary, indent=2))
    if a.out:
        with open(a.out, "w") as f:
            json.dump(summary, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
