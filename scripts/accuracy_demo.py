"""Accuracy-pipeline demonstration at scale on self-generated data.

BASELINE.md metric 1 (SL top-1 on held-out KGS) is structurally
unevidenceable in this environment — no KGS corpus exists here — so
this script proves the measurement PATH end-to-end instead (VERDICT r2
"next round" #9): self-play games from a fixed teacher policy → SGF
corpus (≥100k positions by default) → converter → sharded store → SL
training → per-epoch HELD-OUT accuracy strictly improving, final
test-split number from the standalone evaluator. When a real corpus
arrives, the 55% measurement is exactly these commands with the SGF
directory swapped.

Writes ``<out>/accuracy_demo.json`` with the per-epoch held-out
accuracies and asserts they strictly improve.

Usage::

    python scripts/accuracy_demo.py --out /tmp/acc_demo \
        [--board 9] [--games 1536] [--epochs 3] [--chunk 60]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(mod: str, *args: str) -> None:
    cmd = [sys.executable, "-m", mod, *args]
    print("+", " ".join(cmd), file=sys.stderr, flush=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(cmd, check=True, env=env, cwd=REPO)


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--board", type=int, default=9)
    ap.add_argument("--games", type=int, default=1536,
                    help="self-play games (9x9 games average ~70 "
                    "positions each; 1536 games ≈ 100k+ positions)")
    ap.add_argument("--game-batch", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--minibatch", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.5,
                    help="teacher sampling temperature (lower = more "
                    "deterministic teacher = more learnable signal)")
    ap.add_argument("--chunk", type=int, default=60,
                    help="self-play plies per compiled segment")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--filters", type=int, default=32)
    ap.add_argument("--learning-rate", type=float, default=0.003)
    ap.add_argument("--epoch-length", type=int, default=None,
                    help="steps per epoch (default: one full pass). "
                    "A toy teacher's predictability saturates within "
                    "~1 full pass; shorter epochs keep the held-out "
                    "curve inside its improving regime so the "
                    "per-epoch measurement is demonstrable")
    a = ap.parse_args(argv)

    os.makedirs(a.out, exist_ok=True)
    teacher = os.path.join(a.out, "teacher.json")
    student = os.path.join(a.out, "student.json")
    sgf_dir = os.path.join(a.out, "games")
    corpus = os.path.join(a.out, "corpus")
    train_dir = os.path.join(a.out, "sl")

    # 1. a fixed random-init teacher (its sampled moves are the
    #    expert corpus) and an identically-shaped student
    for path, seed in ((teacher, 1), (student, 2)):
        run("rocalphago_tpu.models.specs", "policy", "--out", path,
            "--board", str(a.board), "--layers", str(a.layers),
            "--filters", str(a.filters), "--seed", str(seed))

    # 2+3. self-play corpus → sharded arrays (chunked — watchdog-safe
    # on the TPU tunnel); actual game count is n_batches × game_batch
    # (recorded below — never the possibly-unround --games request).
    # Resumable: an existing converted corpus is reused as-is, so a
    # training-stage rerun does not replay hours of self-play.
    n_batches = max(1, round(a.games / a.game_batch))
    actual_games = n_batches * a.game_batch
    # the manifest is the converter's completion marker (written after
    # every shard) — shard files alone may be a half-finished run
    if os.path.exists(corpus + "-manifest.json"):
        print(f"+ reusing existing corpus {corpus}*", file=sys.stderr)
    else:
        for b in range(n_batches):
            run("rocalphago_tpu.interface.selfplay_cli",
                "--policy", teacher, "--games", str(a.game_batch),
                "--out", os.path.join(sgf_dir, f"b{b:03d}"),
                "--max-moves", str(3 * a.board * a.board),
                "--temperature", str(a.temperature),
                "--chunk", str(a.chunk), "--seed", str(b))
        run("rocalphago_tpu.data.convert",
            "--directory", sgf_dir, "--recurse", "--outfile", corpus,
            "--size", str(a.board))

    # 4. SL training; per-epoch held-out (val) accuracy + final test
    train_args = [student, corpus, train_dir,
                  "--epochs", str(a.epochs),
                  "--minibatch", str(a.minibatch),
                  "--learning-rate", str(a.learning_rate)]
    if a.epoch_length:
        train_args += ["--epoch-length", str(a.epoch_length)]
    run("rocalphago_tpu.training.sl", *train_args)

    with open(os.path.join(train_dir, "metadata.json")) as f:
        meta = json.load(f)
    epochs = meta["epochs"]
    val_accs = [e["val_accuracy"] for e in epochs]

    result = {
        "board": a.board,
        "games": actual_games,
        "corpus_positions": meta.get("dataset_positions"),
        "val_accuracy_per_epoch": val_accs,
        "test_accuracy": meta.get("test_accuracy"),
        "strictly_improving": all(
            b > x for x, b in zip(val_accs, val_accs[1:])),
    }
    out_path = os.path.join(a.out, "accuracy_demo.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if not result["strictly_improving"]:
        raise SystemExit(
            "held-out accuracy did not strictly improve: "
            f"{val_accs}")
    return result


if __name__ == "__main__":
    main()
