"""Summarize a ``jax.profiler`` trace: top device ops by time.

Every benchmark in this repo takes ``--profile DIR`` and drops a
Perfetto ``*.trace.json.gz`` under ``DIR/plugins/profile/<ts>/``; this
tool turns that into the flat answer perf work actually needs — which
ops own the wall time — without hauling the trace into a GUI (this
environment has no browser; VERDICT r3 item 6 asks for trace-backed
bottleneck analysis).

Usage:
    python scripts/analyze_trace.py DIR [--top 25] [--lane SUBSTR]
        [--json]

``DIR`` may be the profile dir itself or any ancestor (the newest
trace under it is picked). Events are grouped by the thread lane they
run on (XLA device traces put compiled ops on an "XLA Ops" lane,
module launches on "XLA Modules"; host Python frames land on a
"python" lane). By default every lane except host-Python is
summarized; ``--lane`` filters to lanes whose name contains SUBSTR
(e.g. ``--lane "XLA Ops"``).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def newest_trace(root: str) -> str:
    direct = glob.glob(os.path.join(root, "*.trace.json.gz"))
    nested = glob.glob(os.path.join(root, "**", "*.trace.json.gz"),
                       recursive=True)
    paths = direct or nested
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {root}")
    return max(paths, key=os.path.getmtime)


def load_events(path: str) -> list[dict]:
    with gzip.open(path, "rt") as f:
        return json.load(f).get("traceEvents", [])


def summarize(events: list[dict], lane_filter: str | None = None,
              include_python: bool = False):
    """-> {lane_name: {"total_us", "span_us", "ops": [(name, us, n)]}}.

    Total is the plain sum of event durations per lane; span is the
    first-start→last-end extent (overlap/nesting makes total > span on
    busy lanes — both are reported so neither misleads alone).
    """
    proc = {}
    thread = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            proc[e.get("pid")] = str(args.get("name", e.get("pid")))
        elif e.get("name") == "thread_name":
            thread[(e.get("pid"), e.get("tid"))] = str(
                args.get("name", e.get("tid")))

    lanes: dict = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        key = (e.get("pid"), e.get("tid"))
        lane = f"{proc.get(e.get('pid'), e.get('pid'))}/" \
               f"{thread.get(key, e.get('tid'))}"
        if not include_python and thread.get(key, "") == "python":
            continue
        if lane_filter and lane_filter.lower() not in lane.lower():
            continue
        d = lanes.setdefault(lane, {
            "ops": collections.defaultdict(lambda: [0.0, 0]),
            "t0": float("inf"), "t1": 0.0, "total": 0.0})
        dur = float(e["dur"])
        ts = float(e.get("ts", 0.0))
        agg = d["ops"][e.get("name", "?")]
        agg[0] += dur
        agg[1] += 1
        d["total"] += dur
        d["t0"] = min(d["t0"], ts)
        d["t1"] = max(d["t1"], ts + dur)

    out = {}
    for lane, d in lanes.items():
        ops = sorted(((n, v[0], v[1]) for n, v in d["ops"].items()),
                     key=lambda x: -x[1])
        out[lane] = {"total_us": d["total"],
                     "span_us": max(d["t1"] - d["t0"], 0.0),
                     "ops": ops}
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dir", help="profile dir (or any ancestor)")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--lane", default=None,
                    help="only lanes whose name contains this")
    ap.add_argument("--python", action="store_true",
                    help="include host-Python frame lanes")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    a = ap.parse_args(argv)

    path = newest_trace(a.dir)
    lanes = summarize(load_events(path), a.lane, a.python)
    if a.json:
        print(json.dumps({
            "trace": path,
            "lanes": {k: {"total_us": v["total_us"],
                          "span_us": v["span_us"],
                          "top": v["ops"][:a.top]}
                      for k, v in lanes.items()}}))
        return 0

    print(f"trace: {path}")
    # busiest lanes first
    for lane, d in sorted(lanes.items(),
                          key=lambda kv: -kv[1]["total_us"]):
        if not d["ops"]:
            continue
        print(f"\n== {lane}  (sum {d['total_us'] / 1e3:.1f} ms, "
              f"span {d['span_us'] / 1e3:.1f} ms, "
              f"{len(d['ops'])} distinct)")
        for name, us, n in d["ops"][:a.top]:
            pct = 100.0 * us / d["total_us"] if d["total_us"] else 0.0
            print(f"  {us / 1e3:10.2f} ms {pct:5.1f}% x{n:<6d} {name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
