"""Chaos soak: the supervised fleet under randomized fire.

Runs the full actor/learner rig — free-running self-play actors, the
sharded learner, and a :class:`~rocalphago_tpu.serve.evaluator.
BatchingEvaluator` leg with its own request stream — under a
probabilistic kill plan (``kill@…:p=`` specs, docs/RESILIENCE.md
"Fault injection") and proves the supervision layer's headline
claims (docs/RESILIENCE.md "Fleet supervision"):

* the learner keeps making progress (``learner_steps_total`` is
  monotonic and reaches the target) while actors, the learner step
  itself, and the serving dispatcher are killed at random;
* nothing wedges: the watchdog (logging mode) records ZERO stall
  events over the whole soak;
* nobody parks: every death is absorbed by a restart/failover, and
  the lifecycle record (``worker_restart`` / ``worker_recovered`` /
  ``learner_failover``) lands in ``metrics.jsonl``;
* after the storm a fault-free GATE round runs clean — one learner
  step and one served eval with finite outputs.

Kill schedules are deterministic per seed at each barrier (the draw
is a pure hash of seed/barrier/hit-count), but the interleaving of
barrier hits across threads is not — so the harness asserts a
MINIMUM kill count (``--min-kills``), not an exact schedule, and
keeps soaking until both the step target and the kill floor are met
(bounded by ``--deadline-s``).

Tier-1 smoke: ``tests/test_fleet_chaos.py`` runs this with
``--steps 3 --min-kills 2``; the @slow soak runs the default
``--steps 12 --min-kills 6``.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_soak.py --out /tmp/soak
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, ".")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="run dir for metrics.jsonl + summary.json "
                    "(default: a fresh temp dir)")
    ap.add_argument("--board", type=int, default=5)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--actors", type=int, default=3)
    ap.add_argument("--steps", type=int, default=12,
                    help="learner steps the soak must reach")
    ap.add_argument("--sims", type=int, default=2)
    ap.add_argument("--move-limit", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7,
                    help="kill-schedule seed (per-barrier draws)")
    ap.add_argument("--p-actor", type=float, default=0.3)
    ap.add_argument("--p-learner", type=float, default=0.2)
    ap.add_argument("--p-serve", type=float, default=0.3)
    ap.add_argument("--plan", default=None,
                    help="override the whole fault plan verbatim")
    ap.add_argument("--min-kills", type=int, default=6,
                    help="soak until at least this many injected "
                    "kills landed across the fleet")
    ap.add_argument("--deadline-s", type=float, default=300.0,
                    help="hard wall-clock bound on the whole soak")
    ap.add_argument("--serve-requests", type=int, default=40,
                    help="eval requests the serving leg submits")
    return ap


def main() -> int:
    args = build_parser().parse_args()
    out_dir = args.out or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(out_dir, exist_ok=True)

    import threading
    import time

    import jax
    import numpy as np
    import optax

    from rocalphago_tpu.data.replay import ReplayBuffer
    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.io.checkpoint import pack_rng, unpack_rng
    from rocalphago_tpu.io.metrics import MetricsLogger
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.obs import registry
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.runtime import faults, watchdog
    from rocalphago_tpu.runtime.supervisor import (
        RestartPolicy,
        Supervisor,
    )
    from rocalphago_tpu.serve.evaluator import BatchingEvaluator
    from rocalphago_tpu.training.actor import (
        DispatchGang,
        ParamsPublisher,
        SelfplayActor,
    )
    from rocalphago_tpu.training.learner import ZeroLearner
    from rocalphago_tpu.training.zero import (
        init_zero_state,
        make_zero_iteration,
    )

    plan = args.plan if args.plan is not None else ",".join(
        f"kill@{barrier}:p={p},seed={args.seed + i}"
        for i, (barrier, p) in enumerate([
            ("actor.game", args.p_actor),
            ("learner.step", args.p_learner),
            ("serve.dispatch", args.p_serve)])
        if p > 0)
    metrics = MetricsLogger(os.path.join(out_dir, "metrics.jsonl"),
                            echo=False)
    metrics.log("chaos_soak", phase="start", plan=plan,
                steps=args.steps, actors=args.actors,
                min_kills=args.min_kills, seed=args.seed)

    # ------------------------------------------------- the tiny rig
    feats = ("board", "ones")
    vfeats = feats + ("color",)
    pol = CNNPolicy(feats, board=args.board, layers=1,
                    filters_per_layer=2)
    val = CNNValue(vfeats, board=args.board, layers=1,
                   filters_per_layer=2)
    cfg = GoConfig(size=args.board)
    n_dev = len(jax.devices())
    while args.batch % n_dev:
        n_dev -= 1
    mesh = meshlib.make_mesh(n_dev)
    iteration = make_zero_iteration(
        cfg, feats, vfeats, pol.module.apply, val.module.apply,
        optax.sgd(0.01), optax.sgd(0.01), batch=args.batch,
        move_limit=args.move_limit, n_sim=args.sims, max_nodes=16,
        sim_chunk=2, replay_chunk=4, mesh=mesh)
    state0 = meshlib.replicate(mesh, init_zero_state(
        pol.params, val.params, optax.sgd(0.01), optax.sgd(0.01),
        seed=args.seed))

    buf = ReplayBuffer(capacity=max(2 * args.actors, 4))
    pub = ParamsPublisher()
    gang = DispatchGang()
    # quick restarts, no parks expected: the soak's kill rate is far
    # below any honest crash-loop threshold at this window
    policy = RestartPolicy(max_deaths=50, window_s=60.0,
                           base_delay=0.05, max_delay=0.5,
                           seed=args.seed)
    sup = Supervisor(metrics=metrics, policy=policy, poll_s=0.05,
                     heartbeat_s=60.0)
    base_rng = state0.rng

    def actor_factory(i):
        def make(attempt, beat):
            key = jax.random.fold_in(unpack_rng(base_rng), i + 1)
            if attempt:
                key = jax.random.fold_in(key, attempt)
            return SelfplayActor(
                iteration.play, pub, buf, pack_rng(key),
                name=f"a{i}", lockstep=False, pace=False,
                poll_s=0.1, gang=gang, metrics=metrics,
                on_progress=beat)
        return make

    for i in range(args.actors):
        sup.add(actor_factory(i), name=f"actor:{i}")
    learner = ZeroLearner(iteration.learn, buf, sample=True,
                          gang=gang, metrics=metrics)

    # --------------------------------------------- the serving leg
    # a pure-host eval program: the serving dispatcher's deaths and
    # restarts are what the soak measures, not device throughput
    def fake_eval(_pp, _vv, states):
        b = states.shape[0]
        return (np.full((b, args.board ** 2 + 1),
                        1.0 / (args.board ** 2 + 1), np.float32),
                np.zeros((b,), np.float32))

    ev = BatchingEvaluator(fake_eval, None, None, batch_sizes=(2,),
                           max_wait_us=100.0, metrics=metrics,
                           restart_policy=policy)
    serve_ok = [0]
    serve_failed = [0]
    serve_stop = threading.Event()

    def submitter():
        states = np.zeros((2, 4), np.float32)
        for _ in range(args.serve_requests):
            if serve_stop.is_set():
                return
            try:
                priors, values = ev.evaluate(states, rows=2,
                                             timeout=30.0)
                assert np.isfinite(priors).all()
                serve_ok[0] += 1
            except Exception:  # noqa: BLE001 — counted, soak goes on
                serve_failed[0] += 1
            time.sleep(0.02)

    sub_thread = threading.Thread(target=submitter,
                                  name="soak-submitter", daemon=True)

    def kill_count() -> int:
        snap = registry.snapshot()["counters"]
        return sum(v for k, v in snap.items()
                   if k.startswith("supervisor_restarts_total"))

    # --------------------------------------------------- the storm
    faults.install(plan)
    wd = watchdog.Watchdog(60.0, metrics=metrics, exit=False,
                           name="soak").start()
    pub.publish(state0.policy_params, state0.value_params, version=0)
    sup.start()
    sub_thread.start()

    state = state0
    learner_failovers = 0
    steps_seen: list[int] = []
    t0 = time.monotonic()
    rc = 0
    try:
        while time.monotonic() - t0 < args.deadline_s:
            done_steps = learner.steps >= args.steps
            if done_steps and kill_count() >= args.min_kills:
                break
            if done_steps and not sub_thread.is_alive():
                break           # kill floor unreachable: plan too mild
            try:
                out = learner.step(state, timeout=5.0)
            except Exception as e:  # noqa: BLE001 — soak failover
                learner_failovers += 1
                metrics.log("learner_failover",
                            error=f"{type(e).__name__}: {e}",
                            restored_step=learner.steps,
                            target=learner.steps + 1)
                registry.counter(
                    "supervisor_restarts_total", worker="learner",
                    reason="transient").inc()
                continue        # pre-step state is intact: re-step
            if out is None:
                if sup.parked():
                    rc = 2
                    break
                continue
            state, m, _ = out
            pub.publish(state.policy_params, state.value_params,
                        version=learner.steps)
            steps_seen.append(learner.steps)
            wd.beat()
    finally:
        serve_stop.set()
        sub_thread.join(timeout=30.0)

        # ------------------------------------------- the clean gate
        faults.install("")
        metrics.log("chaos_soak", phase="gate")
        gate_ok = False
        gate_loss = None
        try:
            out = None
            gate_t0 = time.monotonic()
            while out is None and time.monotonic() - gate_t0 < 60.0:
                out = learner.step(state, timeout=5.0)
            if out is not None:
                _, m, _ = out
                gate_loss = m.get("policy_loss")
                priors, _ = ev.evaluate(
                    np.zeros((2, 4), np.float32), rows=2,
                    timeout=30.0)
                gate_ok = (gate_loss is not None
                           and np.isfinite(gate_loss)
                           and np.isfinite(priors).all()
                           and not ev._thread.parked)
        except Exception as e:  # noqa: BLE001 — a red gate is a
            #                     verdict, not a harness crash
            metrics.log("chaos_soak", phase="gate_error",
                        error=f"{type(e).__name__}: {e}")
        finally:
            buf.close()
            sup.stop()
            ev.close()
            wd.stop()
            faults.install(None)

    # ------------------------------------------------- the verdict
    kills = kill_count()
    restarts = sum(h.restarts for h in sup.handles())
    parked = [h.name for h in sup.parked()]
    if ev._thread.parked:
        parked.append(ev._thread.name)
    mttrs = [h.last_mttr_s for h in sup.handles()
             if h.last_mttr_s is not None]
    stalls = sum(1 for line in open(metrics.path)
                 if json.loads(line).get("event") == "stall")
    events = {json.loads(line).get("event")
              for line in open(metrics.path)}
    monotonic_steps = all(b > a for a, b in
                          zip(steps_seen, steps_seen[1:]))
    summary = {
        "plan": plan,
        "learner_steps": learner.steps,
        "monotonic": monotonic_steps,
        "kills_total": kills,
        "actor_restarts": restarts,
        "dispatcher_restarts": ev._thread.restarts,
        "learner_failovers": learner_failovers,
        "parked": parked,
        "serve_ok": serve_ok[0],
        "serve_failed": serve_failed[0],
        "mttr_mean_s": (round(sum(mttrs) / len(mttrs), 3)
                        if mttrs else None),
        "mttr_max_s": round(max(mttrs), 3) if mttrs else None,
        "stall_events": stalls,
        "gate_ok": gate_ok,
        "gate_policy_loss": gate_loss,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    checks = {
        "steps_reached": learner.steps >= args.steps,
        "monotonic": monotonic_steps,
        "min_kills": kills >= args.min_kills,
        "no_parks": not parked,
        "no_stalls": stalls == 0,
        "gate_green": gate_ok,
        "lifecycle_logged": ("worker_restart" in events
                             or "learner_failover" in events),
    }
    summary["checks"] = checks
    metrics.log("chaos_soak", phase="done", **{
        k: v for k, v in summary.items() if k != "checks"})
    metrics.close()
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))
    if rc == 0 and not all(checks.values()):
        rc = 1
    if rc:
        failed = [k for k, v in checks.items() if not v]
        print(f"chaos_soak: FAILED checks: {failed}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
