#!/bin/bash
# TPU component-bench sweep (VERDICT r3 item 1): run ONLY after
# `python scripts/tpu_probe.py` reports {"tpu": "ok"}.
#
# Ordering is risk-ascending: cheap compiled programs first, the
# self-play/RL programs (chunked, watchdog-safe) last, so a mid-sweep
# worker crash costs the least data. NO step is wrapped in a killing
# timeout — every program here is already sized/chunked to finish
# under the ~40s worker watchdog, and killing a TPU client mid-run
# wedges the tunnel (round-2 postmortem). Each result line also lands
# in benchmarks/results.jsonl with platform+date.
#
# Usage: bash scripts/tpu_bench_sweep.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-benchmarks/tpu_sweep_$(date +%H%M)}
mkdir -p "$LOG"

run() {
    name=$1; shift
    echo "=== $name: $*" | tee -a "$LOG/sweep.log"
    # no timeout wrapper by design — see header
    "$@" >>"$LOG/sweep.log" 2>&1
    echo "    rc=$?" | tee -a "$LOG/sweep.log"
    # give a crashed worker its ~15s self-recovery before the next step
    sleep 15
}

run probe      python scripts/tpu_probe.py
run labels     python benchmarks/bench_labels.py --reps 3
run engine     python benchmarks/bench_engine.py --reps 2
run engine1k   python benchmarks/bench_engine.py --batch 1024 --moves 64 --reps 2
run train      python benchmarks/bench_train.py --batch-sweep 64,256,1024 --reps 3
run rollout    python benchmarks/bench_rollout.py --reps 3
run preprocess python benchmarks/bench_preprocess.py --reps 2
run chase_xla  python benchmarks/bench_chase.py --reps 2
run chase_pls  env ROCALPHAGO_PALLAS_CHASE=1 python benchmarks/bench_chase.py --reps 2
run selfplay   python benchmarks/bench_selfplay.py --batch-sweep 16,64,256 --reps 2
run devmcts9   python benchmarks/bench_device_mcts.py --board 9 --sims 32 --reps 2
run devmcts19  python benchmarks/bench_device_mcts.py --board 19 --sims 32 --reps 2
run mcts9      python benchmarks/bench_mcts.py --board 9 --playouts 64 --reps 2
run mcts19     python benchmarks/bench_mcts.py --board 19 --playouts 48 --reps 2
run mcts19r    python benchmarks/bench_mcts.py --board 19 --playouts 48 --lmbda 0.5 --device-rollout --reps 2
run rl         python benchmarks/bench_rl.py --batch 16 --moves 100 --chunk 10 --reps 1

echo "sweep done; results in $LOG/sweep.log + benchmarks/results.jsonl"
