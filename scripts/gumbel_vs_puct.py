"""Head-to-head: Gumbel sequential-halving vs PUCT root selection.

Both sides run the SAME on-device search machinery with the SAME
injected evaluator (uniform policy logits + stone-count value — the
fake-backend seam the suite uses), the same simulation budget and the
same tree capacity; the only difference is the root rule
(``make_gumbel_mcts`` vs ``make_device_mcts``). Any win-rate gap is
therefore attributable to root selection alone — the claim Gumbel
makes (Danihelka et al. 2022) is exactly that it wins at LOW budgets,
which is the regime the on-device search serves in.

Writes ``results/gumbel_demo/gumbel_demo.json`` and prints one JSON
line per simulation budget.

Usage:
    python scripts/gumbel_vs_puct.py [--games 20] [--board 7]
        [--sims 8 16] [--move-limit 80]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine import jaxgo, pygo
    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.search.device_mcts import (
        make_device_mcts,
        make_gumbel_mcts,
    )

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--games", type=int, default=20)
    ap.add_argument("--board", type=int, default=7)
    ap.add_argument("--sims", type=int, nargs="*", default=[8, 16])
    ap.add_argument("--move-limit", type=int, default=80)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="results/gumbel_demo")
    a = ap.parse_args(argv)

    size = a.board
    n = size * size
    cfg = GoConfig(size=size)
    feats = ("board", "ones")
    vfeats = feats + ("color",)

    def fake_policy(params, planes):
        return jnp.zeros((planes.shape[0], n))

    def fake_value(params, planes):
        mine = planes[..., 0].sum(axis=(1, 2))
        theirs = planes[..., 1].sum(axis=(1, 2))
        return (mine - theirs) / n

    def move_of(search, st, rng, gumbel):
        root = jaxgo.from_pygo(cfg, st)
        roots = jax.tree.map(lambda x: x[None], root)
        if gumbel:
            visits, _, best, _ = search(None, None, roots, rng)
            action = int(jax.device_get(best)[0])
            counts = jax.device_get(visits)[0]
        else:
            visits, _ = search(None, None, roots)
            counts = jax.device_get(visits)[0]
            action = int(counts.argmax())
        if action >= n or counts[action] == 0:
            return None
        from rocalphago_tpu.utils.coords import unflatten_idx

        return unflatten_idx(action, size)

    results = []
    for n_sim in a.sims:
        # each engine sizes its own slab: gumbel's halving plan runs
        # more sims than nominal n_sim at small budgets, so a shared
        # 2*n_sim slab would truncate exactly the searches this
        # script exists to compare
        puct = make_device_mcts(cfg, feats, vfeats, fake_policy,
                                fake_value, n_sim=n_sim,
                                max_nodes=2 * n_sim + 2)
        gmb = make_gumbel_mcts(cfg, feats, vfeats, fake_policy,
                               fake_value, n_sim=n_sim,
                               m_root=min(16, n + 1))
        rng = jax.random.key(a.seed + n_sim)
        tally = [0, 0, 0]          # gumbel, puct, draw
        t0 = time.time()
        for g in range(a.games):
            st = pygo.GameState(size=size)
            gumbel_is_black = g % 2 == 0
            while not st.is_end_of_game \
                    and st.turns_played < a.move_limit:
                black_to_move = st.current_player == pygo.BLACK
                use_gumbel = black_to_move == gumbel_is_black
                rng, sub = jax.random.split(rng)
                mv = move_of(gmb if use_gumbel else puct, st, sub,
                             use_gumbel)
                st.do_move(mv)
            w = st.get_winner()
            idx = 2 if w == 0 else (
                0 if (w == pygo.BLACK) == gumbel_is_black else 1)
            tally[idx] += 1
            print(f"sims={n_sim} game {g}: "
                  f"{'gumbel' if idx == 0 else 'puct' if idx == 1 else 'draw'}"
                  f" ({tally})", file=sys.stderr)
        decided = max(tally[0] + tally[1], 1)
        rec = {"metric": "gumbel_vs_puct_winrate",
               "value": round(tally[0] / decided, 3),
               "unit": "win-rate", "sims": n_sim, "board": size,
               "games": a.games, "gumbel": tally[0],
               "puct": tally[1], "draws": tally[2],
               "wall_s": round(time.time() - t0, 1)}
        print(json.dumps(rec))
        results.append(rec)

    os.makedirs(a.out, exist_ok=True)
    with open(os.path.join(a.out, "gumbel_demo.json"), "w") as f:
        json.dump({"note": "same evaluator/budget/tree both sides; "
                           "only the root rule differs",
                   "results": results}, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
