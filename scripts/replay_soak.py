"""Replay-service soak: the wire under kill storms, lossless.

Runs a real multi-process rig — a :mod:`rocalphago_tpu.replaynet`
service SUBPROCESS (crash-safe spill + dedup window), N synthetic
actor SUBPROCESSES (spool-first WAL shipping, degraded mode), and an
in-harness consumer draining ``next_batch`` — and storms it:

* **wire-barrier kills** — a probabilistic plan arms all three
  service barriers (``replay.put`` / ``replay.take`` /
  ``replay.conn``; docs/RESILIENCE.md): connections abort
  mid-request, clients reconnect with backoff and re-ship, the
  dedup window absorbs every retry;
* **whole-actor kills** — SIGKILL at arbitrary points, restart with
  the same spool dir: the actor resumes from ``acked ∪ spooled``
  and regenerates at most the one game that never reached its WAL
  (to the SAME content hash, by construction);
* **service restarts** — SIGTERM mid-traffic: graceful drain
  (in-flight requests finish, dedup window persists, unconsumed
  entries stay spilled), exit 0, restart restores buffer AND
  window; actors spool through the downtime and re-ship in order.

The verdict is exact-set equality, not statistics: every game id
each actor DURABLY produced (its acked ledger ∪ remaining spool —
which the harness also recomputes independently from the synthetic
generator's determinism) must equal the set the consumer took off
the wire. No loss, no duplicates, zero unhandled handler escapes,
and a clean final drain (``replaynet_requested`` →
``replaynet_accept_stopped`` → ``replaynet_drained`` in the service
metrics, final exit 0).

Tier-1 smoke: ``tests/test_replaynet.py`` runs this with small
floors; the @slow soak runs the defaults (≥10 barrier kills, every
barrier hit, ≥1 actor kill, ≥1 service restart).

Usage::

    python scripts/replay_soak.py --out /tmp/replay_soak
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="run dir for logs + summary.json "
                    "(default: a fresh temp dir)")
    ap.add_argument("--actors", type=int, default=3)
    ap.add_argument("--games", type=int, default=12,
                    help="games per actor per spawn (targets grow "
                    "when the storm needs more put traffic)")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--plies", type=int, default=4)
    ap.add_argument("--board", type=int, default=5)
    ap.add_argument("--capacity", type=int, default=8,
                    help="service buffer capacity (small enough "
                    "that overload shedding happens)")
    ap.add_argument("--rate-s", type=float, default=0.1,
                    help="actor pacing between games")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--p-put", type=float, default=0.12,
                    help="kill probability at replay.put")
    ap.add_argument("--p-take", type=float, default=0.12,
                    help="kill probability at replay.take")
    ap.add_argument("--p-conn", type=float, default=0.04,
                    help="kill probability at replay.conn")
    ap.add_argument("--plan", default=None,
                    help="override the whole fault plan verbatim")
    ap.add_argument("--min-kills", type=int, default=10,
                    help="total barrier-kill floor across the storm")
    ap.add_argument("--min-barrier-kills", type=int, default=1,
                    help="per-barrier kill floor (each of put/take/"
                    "conn)")
    ap.add_argument("--min-actor-kills", type=int, default=1)
    ap.add_argument("--min-service-restarts", type=int, default=1)
    ap.add_argument("--chaos-interval-s", type=float, default=3.0,
                    help="seconds between actor-kill / service-"
                    "restart actions")
    ap.add_argument("--deadline-s", type=float, default=240.0,
                    help="hard wall-clock bound on the storm phase")
    ap.add_argument("--drain-s", type=float, default=8.0,
                    help="service drain grace per restart")
    return ap


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    args = build_parser().parse_args()
    out_dir = args.out or tempfile.mkdtemp(prefix="replay_soak_")
    os.makedirs(out_dir, exist_ok=True)

    from rocalphago_tpu.data.replay import compute_game_id
    from rocalphago_tpu.replaynet.actor import synth_games
    from rocalphago_tpu.replaynet.client import ReplayClient, ReplayConn
    from rocalphago_tpu.runtime import faults

    plan = (args.plan if args.plan is not None else
            f"kill@replay.put:p={args.p_put}:seed={args.seed},"
            f"kill@replay.take:p={args.p_take}:seed={args.seed + 1},"
            f"kill@replay.conn:p={args.p_conn}:seed={args.seed + 2}")
    port = _free_port()
    spill_dir = os.path.join(out_dir, "spill")
    service_metrics = os.path.join(out_dir, "service.metrics.jsonl")
    service_log = open(os.path.join(out_dir, "service.log"), "ab")
    actor_log = open(os.path.join(out_dir, "actors.log"), "ab")
    total_target = args.actors * args.games  # grows with respawns

    # ------------------------------------------------- subprocesses

    def start_service(fault_plan: str) -> subprocess.Popen:
        env = dict(os.environ)
        if fault_plan:
            env[faults.FAULT_PLAN_ENV] = fault_plan
        else:
            env.pop(faults.FAULT_PLAN_ENV, None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "rocalphago_tpu.replaynet.server",
             "--host", "127.0.0.1", "--port", str(port),
             "--spill-dir", spill_dir,
             "--capacity", str(args.capacity),
             "--dedup-window", str(max(4096, 4 * total_target)),
             "--drain-s", str(args.drain_s),
             "--metrics", service_metrics],
            env=env, cwd=REPO_ROOT,
            stdout=service_log, stderr=service_log)
        # wait until it serves (reads the hello)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replay service died at start "
                    f"(rc={proc.returncode}); see service.log")
            try:
                ReplayConn("127.0.0.1", port, timeout=1.0).close()
                return proc
            except Exception:  # noqa: BLE001 — not up yet
                time.sleep(0.1)
        raise RuntimeError("replay service never came up")

    targets = {i: args.games for i in range(args.actors)}

    def spawn_actor(i: int) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "rocalphago_tpu.replaynet.actor",
             "--connect", f"127.0.0.1:{port}",
             "--spool-dir", os.path.join(out_dir, f"actor{i}"),
             "--actor-id", str(i), "--games", str(targets[i]),
             "--mode", "synthetic", "--seed", str(args.seed),
             "--batch", str(args.batch), "--plies", str(args.plies),
             "--board", str(args.board),
             "--rate-s", str(args.rate_s),
             "--attempts", "3", "--flush-timeout", "20"],
            cwd=REPO_ROOT, stdout=actor_log, stderr=actor_log)

    def fetch_stats(tries: int = 5) -> dict | None:
        """One stats frame off the live service; None when every try
        was eaten (e.g. by replay.conn kills)."""
        for _ in range(tries):
            try:
                conn = ReplayConn("127.0.0.1", port, timeout=2.0)
                try:
                    return conn.request({"type": "stats"})["replaynet"]
                finally:
                    conn.close()
            except Exception:  # noqa: BLE001 — killed/draining: retry
                time.sleep(0.1)
        return None

    # ---------------------------------------------------- consumer
    taken: set[str] = set()
    taken_lock = threading.Lock()
    batches = {"n": 0}
    stop = threading.Event()

    def consume() -> None:
        while not stop.is_set():
            conn = None
            try:
                conn = ReplayConn("127.0.0.1", port, timeout=8.0)
                while not stop.is_set():
                    reply = conn.request({"type": "next_batch",
                                          "timeout_s": 1.0})
                    if reply.get("type") != "batch":
                        continue
                    gid = str(reply["record"].get("game_id", ""))
                    with taken_lock:
                        if gid:
                            taken.add(gid)
                        batches["n"] += 1
            except Exception:  # noqa: BLE001 — kill/drain: reconnect
                time.sleep(0.1)
            finally:
                if conn is not None:
                    conn.close()

    # ------------------------------------------------------ storm
    service = start_service(plan)
    actors = {i: spawn_actor(i) for i in range(args.actors)}
    consumer = threading.Thread(target=consume, name="soak-consumer")
    consumer.start()

    closed_segments: list[dict] = []   # last stats of dead services
    latest: dict | None = None
    actor_kills = 0
    service_restarts = 0
    clean_rcs: list[int] = []

    def kill_totals() -> dict:
        segs = closed_segments + ([latest] if latest else [])
        out = {"kills": 0, "put_kills": 0, "take_kills": 0,
               "conn_kills": 0, "unhandled": 0}
        for s in segs:
            f = s.get("faults", {})
            out["kills"] += f.get("kills", 0)
            out["put_kills"] += f.get("put_kills", 0)
            out["take_kills"] += f.get("take_kills", 0)
            out["conn_kills"] += f.get("conn_kills", 0)
            out["unhandled"] += s.get("requests", {}).get(
                "unhandled", 0)
        return out

    def floors_met(t: dict) -> bool:
        return (t["kills"] >= args.min_kills
                and t["put_kills"] >= args.min_barrier_kills
                and t["take_kills"] >= args.min_barrier_kills
                and t["conn_kills"] >= args.min_barrier_kills
                and actor_kills >= args.min_actor_kills
                and service_restarts >= args.min_service_restarts)

    def restart_service(fault_plan: str, reason: str) -> None:
        nonlocal service, service_restarts, latest
        snap = fetch_stats()
        if snap is not None:
            latest = snap
        if latest is not None:
            closed_segments.append(latest)
            latest = None
        service.send_signal(signal.SIGTERM)
        try:
            rc = service.wait(timeout=args.drain_s + 20.0)
        except subprocess.TimeoutExpired:
            service.kill()
            rc = service.wait()
        clean_rcs.append(rc)
        service = start_service(fault_plan)
        service_restarts += 1

    t0 = time.monotonic()
    next_chaos = t0 + args.chaos_interval_s
    toggle = 0
    rc = 0
    try:
        while time.monotonic() - t0 < args.deadline_s:
            snap = fetch_stats(tries=2)
            if snap is not None:
                latest = snap
            totals = kill_totals()
            if floors_met(totals):
                break
            # keep put traffic flowing: a finished actor respawns
            # with a bigger target (the expected set grows with it)
            for i, p in actors.items():
                if p.poll() is not None:
                    targets[i] += args.games
                    total_target = sum(targets.values())
                    actors[i] = spawn_actor(i)
            now = time.monotonic()
            if now >= next_chaos:
                next_chaos = now + args.chaos_interval_s
                if (toggle % 2 == 0
                        or service_restarts
                        >= args.min_service_restarts):
                    live = [i for i, p in actors.items()
                            if p.poll() is None]
                    if live:
                        i = live[toggle % len(live)]
                        actors[i].send_signal(signal.SIGKILL)
                        actors[i].wait()
                        actor_kills += 1
                        actors[i] = spawn_actor(i)  # resumes
                else:
                    restart_service(plan, reason="storm")
                toggle += 1
            time.sleep(0.3)

        # --------------------------------------- clean final phase
        # fault-free service incarnation; actors finish and drain
        # their spools; the consumer empties the buffer
        restart_service("", reason="clean_phase")
        expected = {
            compute_game_id(synth_games(
                args.seed, i, k, batch=args.batch,
                plies=args.plies, board=args.board))
            for i, tgt in targets.items() for k in range(tgt)}
        drain_deadline = time.monotonic() + 120.0
        while time.monotonic() < drain_deadline:
            for i, p in actors.items():
                if p.poll() is not None and p.returncode != 0:
                    # rc 2 = spool still held games (service was
                    # down): one clean respawn drains it
                    actors[i] = spawn_actor(i)
            with taken_lock:
                done = expected <= taken
            if done and all(p.poll() == 0
                            for p in actors.values()):
                break
            time.sleep(0.3)
        actor_rcs = {i: p.poll() for i, p in actors.items()}
    finally:
        stop.set()
        consumer.join(timeout=30.0)
        for p in actors.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        final_stats = fetch_stats()
        if final_stats is not None:
            latest = final_stats
        service.send_signal(signal.SIGTERM)
        try:
            final_rc = service.wait(timeout=args.drain_s + 20.0)
        except subprocess.TimeoutExpired:
            service.kill()
            final_rc = service.wait()
        service_log.close()
        actor_log.close()

    # ---------------------------------------------------- verdict
    if latest is not None:
        closed_segments.append(latest)
        latest = None
    totals = kill_totals()
    produced: set[str] = set()
    for i in range(args.actors):
        spool = os.path.join(out_dir, f"actor{i}")
        c = ReplayClient("127.0.0.1", port, spool_dir=spool)
        produced |= c.produced_ids()
    drain_phases = set()
    try:
        with open(service_metrics, encoding="utf-8") as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") == "drain":
                    drain_phases.add(ev.get("phase"))
    except OSError:
        pass
    summary = {
        "plan": plan,
        "expected_games": len(expected),
        "produced_games": len(produced),
        "taken_games": len(taken),
        "taken_batches": batches["n"],
        "actor_targets": targets,
        "actor_rcs": actor_rcs,
        "actor_kills": actor_kills,
        "service_restarts": service_restarts,
        "service_clean_rcs": clean_rcs,
        "service_final_rc": final_rc,
        **totals,
        "drain_phases": sorted(p for p in drain_phases if p),
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    checks = {
        "produced_matches_expected": produced == expected,
        "taken_matches_produced": taken == produced,
        "min_kills": totals["kills"] >= args.min_kills,
        "put_kills": totals["put_kills"] >= args.min_barrier_kills,
        "take_kills": totals["take_kills"] >= args.min_barrier_kills,
        "conn_kills": totals["conn_kills"] >= args.min_barrier_kills,
        "actor_kills": actor_kills >= args.min_actor_kills,
        "service_restarts": (service_restarts
                             >= args.min_service_restarts),
        "actors_exited_clean": all(v == 0
                                   for v in actor_rcs.values()),
        "no_unhandled": totals["unhandled"] == 0,
        "service_exits_clean": (final_rc == 0
                                and all(r == 0 for r in clean_rcs)),
        "drain_clean": {"replaynet_requested",
                        "replaynet_accept_stopped",
                        "replaynet_drained"} <= drain_phases,
    }
    summary["checks"] = checks
    with open(os.path.join(out_dir, "summary.json"), "w",
              encoding="utf-8") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))
    if not all(checks.values()):
        failed = [k for k, v in checks.items() if not v]
        print(f"replay_soak: FAILED checks: {failed}",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
