#!/usr/bin/env python
"""jaxlint CLI — run the project's JAX-aware static analysis.

Usage:
    python scripts/lint.py                  # full report, exit 1 on
                                            # NEW (unbaselined) findings
    python scripts/lint.py --check          # CI form: terse, same exit
    python scripts/lint.py --json           # machine-readable report
    python scripts/lint.py --rules a,b      # run a subset of rules
    python scripts/lint.py --list-rules     # rule catalog
    python scripts/lint.py --update-baseline  # rewrite the baseline
                                              # from current findings
    python scripts/lint.py --write-knobs    # (re)generate docs/KNOBS.md

Exit codes: 0 clean (new findings == 0 AND no stale baseline
entries), 1 findings/stale entries, 2 usage error. Config lives in
pyproject.toml ``[tool.jaxlint]``; the baseline file and suppression
syntax are documented in docs/STATIC_ANALYSIS.md.

Stdlib-only (no jax import): runs in <30 s over the whole repo, so
it rides tier-1 (scripts/test.sh) ahead of the test suite.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from rocalphago_tpu.analysis import (  # noqa: E402
    load_baseline, load_config, run_lint, write_baseline,
)
from rocalphago_tpu.analysis.core import LintContext, rule_catalog  # noqa: E402
from rocalphago_tpu.analysis import core as _core  # noqa: E402
from rocalphago_tpu.analysis.reporters import (  # noqa: E402
    render_json, render_text,
)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_knobs(root: str, config) -> str:
    """(Re)generate docs/KNOBS.md from the env-knob extractor."""
    from rocalphago_tpu.analysis.rules.inventory import render_knobs_doc
    rels = _core.discover_files(root, config)
    modules, _ = _core.parse_modules(root, rels)
    ctx = LintContext(root, config, modules)
    text = render_knobs_doc(ctx)
    path = os.path.join(root, config.docs_knobs)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="JAX-aware static analysis (jaxlint)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: only new findings + summary")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids or family names "
                         "(e.g. concurrency) to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(notes preserved where fingerprints match)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="treat every finding as new")
    ap.add_argument("--write-knobs", action="store_true",
                    help="regenerate docs/KNOBS.md and exit")
    ap.add_argument("--verbose", action="store_true",
                    help="also print baselined findings")
    ap.add_argument("--root", default=None, help=argparse.SUPPRESS)
    a = ap.parse_args(argv)

    root = a.root or repo_root()
    config = load_config(root)

    if a.list_rules:
        for rid, summary in rule_catalog().items():
            fam = _core.RULE_FAMILIES.get(rid, "")
            print(f"{rid:26s} [{fam}] {summary}")
        return 0
    if a.write_knobs:
        path = write_knobs(root, config)
        print(f"jaxlint: wrote {os.path.relpath(path, root)}")
        return 0

    only = None
    if a.rules:
        # tokens may be rule ids OR family names ("concurrency",
        # "donation", …) — a family expands to its rules
        only = _core.expand_rule_names(
            r.strip() for r in a.rules.split(",") if r.strip())
        unknown = only - set(rule_catalog())
        if unknown:
            print(f"jaxlint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    t0 = time.monotonic()
    findings = run_lint(root, config, only=only)
    baseline_path = os.path.join(root, config.baseline)
    if a.no_baseline:
        new, old, stale = findings, [], []
        baseline = None
    else:
        baseline = load_baseline(baseline_path)
        new, old, stale = baseline.partition(findings)
        if only is not None:
            # a rule-subset run must not read the skipped rules'
            # baseline entries as stale
            stale = [e for e in stale if e.get("rule") in only]

    if a.update_baseline:
        write_baseline(baseline_path, findings, previous=baseline)
        print(f"jaxlint: baseline updated with {len(findings)} "
              f"finding(s) -> {config.baseline}")
        return 0

    dt = time.monotonic() - t0
    if a.json:
        print(render_json(new, old, stale))
    else:
        print(render_text(new, old, stale, verbose=a.verbose))
        if not a.check:
            print(f"jaxlint: {len(rule_catalog())} rules over "
                  f"{len(_core.discover_files(root, config))} files "
                  f"in {dt:.1f}s")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
