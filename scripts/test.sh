#!/usr/bin/env bash
# Run the test suite on CPU with the 8-fake-device mesh.
#
# PALLAS_AXON_POOL_IPS= skips the axon TPU-session claim that
# /root/.axon_site/sitecustomize.py performs at interpreter startup —
# that claim can intermittently block for minutes and CPU tests don't
# need the chip. conftest.py still forces JAX_PLATFORMS=cpu and the
# fake-device XLA flag for harnesses that invoke pytest directly.
set -euo pipefail
cd "$(dirname "$0")/.."
# Default tier excludes @pytest.mark.slow (multi-minute trainer/e2e
# tests) to keep the edit-test loop under 5 minutes; `--all`, an
# explicit -m, or an exact ::node-id selection runs without the tier
# filter (so naming one slow test runs it). CI should run --all
# nightly.
#
# Fault tolerance: the default tier includes the chaos SMOKE
# (tests/test_chaos.py::test_chaos_smoke_single_kill_resume — one
# injected kill + exact resume of the 5x5 zero loop, ~1 min) and the
# SERVING-chaos smoke (tests/test_serving_chaos.py fast tier —
# injected faults at every genmove barrier/ladder rung, one fully
# degraded 5x5 game, and the hard-deadline anytime proof, ~15 s);
# the full every-barrier chaos sweeps (training kill/resume AND the
# serving barrier×rung×kind sweep over the real device search) are
# @slow and run with --all. See docs/RESILIENCE.md.
#
# Observability: tests/test_obs.py is tier-1 — span/registry/compile
# -tracking units, a zero-trainer smoke asserting the per-phase span
# records land in metrics.jsonl, and `scripts/obs_report.py
# --selftest` (the fixture render), so the report path cannot rot
# silently. See docs/OBSERVABILITY.md.
#
# Encode parity (tests/test_features.py, tier-1): the gated shared
# chase (`ladders.ladder_planes`) is pinned bit-identical to the
# legacy split formulation at capacity (TestSharedGating), sound on
# the adversarial edge/corner-ladder family, dense-19×19-bounded
# (≤1%) vs the pyfeatures oracle, and a warm second encode is
# asserted compile-free via the obs counters
# (test_warm_encode_compiles_nothing). The overflow/truncation and
# two-phase-equivalence sweeps are @slow. See docs/PERFORMANCE.md
# "Encode path".
#
# Incremental encode (tests/test_incremental.py, tier-1): the delta
# path (features/incremental.py) is trajectory-fuzzed bit-identical
# to the from-scratch encoder at every ply of randomized games
# (captures, ko, a curated 9×9 ladder opening, passes, game end,
# cross-game jumps), the chunked self-play cache carry is pinned
# move-identical, Preprocess.advance matches state_to_tensor, and
# warm advances are compile-free via the obs counters. The longer
# 9×9 fuzz, the monolithic-scan identity and the direct
# batched-encoder match are @slow. See docs/PERFORMANCE.md
# "Incremental encode".
#
# Self-play economics (tests/test_econ.py, tier-1): budget-masked
# slab identity (budget == n_sim bit-matches the plain run; mixed
# budgets stop each row exactly at its cap), forced-playout target
# pruning units, the flags-OFF bit-identity pins for selfplay and a
# tiny zero iteration, terminal ownership/score label parity against
# the engine's area scoring, and the aux-head graft keeping the
# value output bit-identical. The everything-ON zero end-to-end
# (cap + forced-k + aux learn) is @slow. Replay schema-v2 round-trip
# /spill/skip semantics live in tests/test_replay.py (tier-1). See
# docs/PERFORMANCE.md "Self-play economics".
#
# Pipelined dispatch: tests/test_pipeline.py is tier-1 —
# bit-identical pipelined-vs-sync sweeps for PUCT/gumbel search,
# chunked self-play (lagged done-poll) and a zero iteration, the
# sync-gap-strictly-higher A/B, the donation/retry refusal, and the
# step-on-done no-op lemma; the deadline-overshoot-at-depth tests
# live in tests/test_serving_chaos.py. See docs/PERFORMANCE.md.
# Serving (tests/test_serve.py, tier-1): the cross-game batching
# subsystem (rocalphago_tpu/serve; docs/SERVING.md) — evaluator
# coalescing/max-wait/padding semantics (padded rows pinned
# bit-ignored), bounded-queue sheds stepping the resilience ladder
# down (reason `overload`), session admission caps, the probes'
# serve block, and the multi-session SOAK under an installed fault
# plan (one failed eval batch + one watchdog-abandoned hang; every
# other session keeps being served). The split search path
# (prepare_sim/advance_sim/apply_sim) is pinned bit-identical to the
# fused search in tests/test_device_mcts.py, and the concurrent-emit
# test in tests/test_obs.py pins the MetricsLogger/registry
# thread-safety the many-session emit pattern relies on.
# Static analysis (jaxlint, docs/STATIC_ANALYSIS.md): the JAX-aware
# lint — donation reuse, retry-wrapping-donators, host syncs and
# Python branches on tracers in jit bodies, PRNG key reuse,
# float/unhashable static args, mutable-global capture, the
# metric/span/barrier/serve-probe/ROCALPHAGO_* knob inventories
# diffed against docs/{OBSERVABILITY,RESILIENCE,SERVING,KNOBS}.md,
# and the CONCURRENCY family (docs/CONCURRENCY.md): guarded-by
# annotated shared state, a cycle-free whole-project lock-
# acquisition graph, no blocking calls or user callbacks inside
# critical sections, every thread joinable — runs first (stdlib-
# only, all 6 families a few seconds, budgeted <30 s) and fails the
# tier on any unbaselined finding. tests/test_jaxlint.py re-runs it
# in-process (self-lint) plus per-rule fixture coverage, so
# `pytest tests/` alone still enforces it.
#
# Actor/learner split (docs/SCALE.md): tests/test_replay.py is
# tier-1 — replay-buffer semantics (FIFO/eviction/pacing/recency
# sampling/close), spill-restore with torn files, dtype-preserving
# record round-trip, tolerant JSONL ingest, publisher versioning,
# lockstep actor key-chain walk, actor error parking, learner idle
# accounting, and the watchdog waiting_on=replay_fill stall tag
# (~3 s total). The full lockstep-vs-sync bit-exactness A/Bs
# (in-process AND through the run_training CLI) and the 2-process
# gloo sharded-learner-step consistency test are @slow
# (tests/test_zero.py, tests/test_multihost.py) and run with --all.
#
# Multi-size (docs/MULTISIZE.md): tests/test_multisize.py is
# tier-1 — FCN-vs-bias-head A/B at the native size (bit-equal), the
# one-checkpoint-applies-at-every-size facade proof (params shared
# by reference, saved weights bit-equal across at_board sizes),
# value-symmetry invariance across 5/9/13, per-session komi
# (eval_batch_komi bit-compat at default, terminal-sign flip,
# ServePool komi plumbing), MultiSizePool routing/probe/refusal,
# the GTP boardsize re-route carrying komi, and the curriculum
# driver's stage handoff (fast, run_training monkeypatched —
# per-stage seed/iterations argv, bit-equal checkpoint carry,
# curriculum.stage spans in metrics.jsonl). The real 2-stage
# curriculum run (two trainer invocations + transfer gate) is @slow.
#
# Fleet supervision (runtime/supervisor.py; docs/RESILIENCE.md
# "Fleet supervision"): tests/test_fleet_chaos.py is tier-1 — the
# probabilistic fault grammar (kill/:p=/:seed=/random, deterministic
# schedules), supervisor units (restart+MTTR, crash-loop park,
# lockstep restart REFUSAL, drain semantics, stale-heartbeat tags
# reaching the watchdog's waiting_on), dispatcher resurrection and
# park-fails-pending, the lockstep-kill-parks-loudly subprocess
# proof, the SIGTERM drain → exact-resume bit-identity pin, and the
# chaos-soak SMOKE (scripts/chaos_soak.py --steps 3 --min-kills 2:
# randomized kills across actor/learner/serve barriers with the
# green-gate check, ~40 s). The full soak (12 learner steps, ≥6
# kills, defaults) is @slow and runs with --all.
#
# Network gateway (rocalphago_tpu/gateway; docs/GATEWAY.md):
# tests/test_gateway.py is tier-1 — NDJSON framing units (torn/
# oversized/undecodable frames), the full wire conversation over a
# real socket, every typed refusal (bad_proto, unknown_type,
# no_game, illegal_move, bad_board, overload at BOTH the connection
# cap and the pool's admission cap), abrupt-disconnect slot
# reclamation, the gateway.conn fault wall (transient fails one
# request, kill aborts one connection, zero unhandled), graceful
# drain (goodbye + 503 health + phase events), /healthz + /metrics,
# multi-size board routing, the GTP --connect bridge, and the
# gateway-soak SMOKE (scripts/gateway_soak.py in a subprocess:
# kills under load, sheds reconciled against /metrics, clean
# SIGTERM drain, exit 0). The multi-minute default soak is @slow.
#
# Concurrency proofing (runtime half): tests/test_lockcheck.py
# units the ROCALPHAGO_LOCKCHECK=1 instrumented locks (observed
# lock-order graph, cycle raise, held-sets, blocking-while-held,
# contention metrics); the serve SOAK and the concurrent-emit test
# each run once more under the harness, with the soak reconciling
# every OBSERVED lock edge against the STATIC acquisition graph
# (tests/test_serve.py::test_soak_under_lockcheck_...).
python scripts/lint.py --check

ARGS=()
TIER=(-m "not slow")
for a in "$@"; do
    case "$a" in
        --all)  TIER=() ;;
        -m)     TIER=(); ARGS+=("$a") ;;
        *::*)   TIER=(); ARGS+=("$a") ;;
        *)      ARGS+=("$a") ;;
    esac
done
# (JAX 0.9 CPU backend does not serialize executables to the
# persistent compilation cache — measured no-op here — so the tier's
# floor is genuine compile time: ~200 tests averaging ~2s, no single
# test over ~13s.)
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest tests/ ${TIER[@]+"${TIER[@]}"} ${ARGS[@]+"${ARGS[@]}"}
