#!/usr/bin/env bash
# Run the test suite on CPU with the 8-fake-device mesh.
#
# PALLAS_AXON_POOL_IPS= skips the axon TPU-session claim that
# /root/.axon_site/sitecustomize.py performs at interpreter startup —
# that claim can intermittently block for minutes and CPU tests don't
# need the chip. conftest.py still forces JAX_PLATFORMS=cpu and the
# fake-device XLA flag for harnesses that invoke pytest directly.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python -m pytest tests/ "$@"
