"""Kill-safe TPU tunnel health probe.

Prints one status line per phase so a supervising process can tell
exactly where the tunnel stands without ever needing to kill a client
mid-device-program (the round-2 wedge trigger):

- ``phase=import`` / ``phase=devices`` — backend startup progress;
- if startup exceeds ``--startup-limit`` the probe EXITS rc=3 without
  dispatching anything (kill-safe: nothing in flight);
- ``phase=dispatch`` — a 256×256 matmul is about to run (µs on a
  healthy chip; if the probe hangs *after* this line the tunnel is
  wedged, and killing this client cannot make it worse);
- final JSON: ``{"tpu": "ok", "startup_s": ..., "matmul_s": ...}``.

Exit codes: 0 healthy, 3 startup too slow (retry later), 4 matmul
dispatched but wrong platform (CPU fallback attached).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--startup-limit", type=float, default=60.0)
    args = ap.parse_args()

    t0 = time.time()
    print("phase=import", flush=True)
    import jax
    import jax.numpy as jnp

    print(f"phase=devices t={time.time() - t0:.1f}", flush=True)
    devices = jax.devices()
    startup = time.time() - t0
    if startup > args.startup_limit:
        print(json.dumps({"tpu": "startup_hung",
                          "startup_s": round(startup, 1)}))
        return 3

    print(f"phase=dispatch t={startup:.1f}", flush=True)
    t1 = time.time()
    x = jnp.ones((256, 256), jnp.bfloat16)
    jax.device_get(x @ x)
    matmul = time.time() - t1
    platform = devices[0].platform
    print(json.dumps({
        "tpu": "ok" if platform == "tpu" else "wrong_platform",
        "platform": platform,
        "startup_s": round(startup, 1),
        "matmul_s": round(matmul, 2),
    }))
    return 0 if platform == "tpu" else 4


if __name__ == "__main__":
    sys.exit(main())
