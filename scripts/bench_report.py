"""Render bench results and tunnel-health stats from machine logs.

Two inputs, both produced automatically:
- ``benchmarks/results.jsonl`` — every benchmark script appends one
  record per measurement (metric, value, unit, config, platform,
  date); this renders the BENCH_RESULTS.md tables from data instead of
  hand-transcription (VERDICT r2 weak #3).
- hunter ``probe.log`` files — ``probe rc=N [HH:MM:SS]`` lines; this
  summarizes tunnel availability (how often the flapping axon tunnel
  was actually usable), which is the context every TPU number in this
  repo has to be read in.

Usage:
    python scripts/bench_report.py [--date YYYY-MM-DD]
        [--platform tpu] [--log benchmarks/results.jsonl]
        [--probe-log DIR_OR_FILE ...]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time


def load_records(path: str, date: str, platform: str | None):
    """Latest record per (metric, batch, board, config-ish key)."""
    latest: dict = {}
    try:
        f = open(path)
    except OSError as e:
        print(f"bench_report: cannot read {path}: {e}",
              file=sys.stderr)
        return []
    with f:
        for line in f:
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if not isinstance(r, dict) or "metric" not in r:
                continue
            if not str(r.get("date", "")).startswith(date):
                continue
            if platform and r.get("platform") != platform:
                continue
            key = (r["metric"], r.get("batch"), r.get("board"),
                   r.get("interpret"), r.get("lmbda"),
                   r.get("devices"), r.get("pipeline_depth"),
                   # encode A/B axes (bench_encode.py): every
                   # gating/phase1/impl side is its own row
                   r.get("gating"), r.get("phase1"),
                   r.get("chase_impl"),
                   # serving sweep axes (bench_serve.py): each
                   # session count × drive mode is its own row
                   r.get("sessions"), r.get("mode"),
                   # gateway sweep axis (bench_gateway.py): each
                   # connection count is its own row — the direct
                   # and gateway sides of the wire-tax A/B already
                   # split on mode
                   r.get("conns"),
                   # actor/learner scale axes (bench_zero_scale.py):
                   # each actor count × mesh shape is its own row
                   r.get("actors"), r.get("mesh_shape"),
                   # self-play economics axis (bench_selfplay.py
                   # --cap-ab / bench_zero_scale.py --cap-p): each
                   # cap probability is its own row — the baseline
                   # (cap_p=1.0 or absent) and capped sides of the
                   # A/B must not collapse into one
                   r.get("cap_p"),
                   # recovery A/B axis (bench_zero_scale.py
                   # --kill-actor-at): the killed-actor run and the
                   # fault-free run are separate rows
                   r.get("kill_at"),
                   # transposition-cache A/B axis (bench_serve.py
                   # --cache-ab): the cache-off and cache-on arms at
                   # one session count are separate rows
                   r.get("cache"))
            prev = latest.get(key)
            if prev is None or str(r.get("date")) >= str(prev.get("date")):
                latest[key] = r
    def order(k):
        batch = k[1] if isinstance(k[1], (int, float)) else 0
        return (k[0], batch, str(k))
    return [latest[k] for k in sorted(latest, key=order)]


_SKIP_FIELDS = {"metric", "value", "unit", "platform", "date",
                "vs_baseline", "mfu", "host_gap_frac", "us_per_pos",
                "sessions", "conns", "actors", "learner_idle_frac",
                "board", "cap_p", "fullsearch_frac", "mttr_s",
                "hit_rate"}


def render_table(records) -> str:
    """MFU gets its own column (VERDICT r3 #3): benches that know
    their program's XLA-costed flops record ``mfu`` = achieved
    flops/s ÷ the chip's bf16 peak (see benchmarks/_harness.py);
    '—' where a record has none (CPU runs, non-flops metrics).
    The host-gap column shows ``host_gap_frac`` — the fraction of
    wall time the device had nothing in flight (the pipelined-vs-sync
    dispatch A/B; ``pipeline_depth`` in config names the side). The
    µs/pos column renders ``us_per_pos`` — the encode A/B's
    per-position cost (``benchmarks/bench_encode.py``), keyed by the
    gating/phase1/impl fields that stay visible in config. The
    sessions column keys the serving sweep (``bench_serve.py``:
    moves/sec vs concurrent-session count — read the batched-mode
    rows top to bottom for the scaling curve; p50/p99/occupancy stay
    in config). The actors and learner-idle columns key the
    actor/learner scale sweep (``bench_zero_scale.py``: ingest
    games/min and learner steps/s vs actor count — actors=0 is the
    synchronous baseline, whose self-play fraction stays in config as
    ``selfplay_frac``; ``mesh_shape`` also stays in config). The same
    two columns key the wire-rig A/B (``bench_zero_scale.py --wire``:
    ``zero_wire_*`` rows put actor PROCESSES behind replaynet — read
    the learner-idle column against the in-process row at the same
    actor count for the wire tax; docs/REPLAYNET.md). The
    board column keys multi-size sweeps (``bench_multisize.py``: one
    FCN checkpoint served per board size — read same-metric rows
    across boards for the size-scaling table). The cap-p and
    full-frac columns key the self-play economics A/B
    (``bench_selfplay.py --cap-ab``: games/min vs the probability a
    ply gets the full search budget; ``fullsearch_frac`` is the frac
    the run actually drew — read the cap_p=1 row as the baseline).
    The MTTR column renders ``mttr_s`` — the recovery A/B's
    kill-to-first-post-restart-game time (``bench_zero_scale.py
    --kill-actor-at``; ``kill_at`` stays in config and keys the
    row). The conns column keys the gateway wire-tax sweep
    (``bench_gateway.py``: moves/sec vs concurrent connections, the
    direct/gateway modes A/B'd per count — p50/p99 stay in
    config). The hit-rate column renders ``hit_rate`` — the
    transposition-cache A/B's measured cache hit rate
    (``bench_serve.py --cache-ab``; the ``cache`` off/on field stays
    in config and keys the row against its other arm)."""
    lines = ["| metric | value | unit | board | MFU | host gap "
             "| µs/pos | sessions | conns | actors | learner idle "
             "| cap p | full frac | MTTR | hit rate | config |",
             "|---|---|---|---|---|---|---|---|---|---|---|---|---|"
             "---|---|---|"]
    for r in records:
        cfg = ", ".join(f"{k}={v}" for k, v in sorted(r.items())
                        if k not in _SKIP_FIELDS)
        extra = ("" if r.get("vs_baseline") in (None, "")
                 else f" (vs_baseline {r['vs_baseline']})")
        board = r.get("board")
        board = "—" if board in (None, "") else str(board)
        u = r.get("mfu")
        u = "—" if u in (None, "") else f"{100.0 * float(u):.1f}%"
        gap = r.get("host_gap_frac")
        gap = "—" if gap in (None, "") else f"{100.0 * float(gap):.2f}%"
        upp = r.get("us_per_pos")
        upp = "—" if upp in (None, "") else f"{float(upp):g}"
        sess = r.get("sessions")
        sess = "—" if sess in (None, "") else str(sess)
        conns = r.get("conns")
        conns = "—" if conns in (None, "") else str(conns)
        act = r.get("actors")
        act = "—" if act in (None, "") else str(act)
        idle = r.get("learner_idle_frac")
        idle = ("—" if idle in (None, "")
                else f"{100.0 * float(idle):.1f}%")
        capp = r.get("cap_p")
        capp = "—" if capp in (None, "") else f"{float(capp):g}"
        ff = r.get("fullsearch_frac")
        ff = "—" if ff in (None, "") else f"{100.0 * float(ff):.1f}%"
        mttr = r.get("mttr_s")
        mttr = "—" if mttr in (None, "") else f"{float(mttr):g}s"
        hr = r.get("hit_rate")
        hr = "—" if hr in (None, "") else f"{100.0 * float(hr):.1f}%"
        lines.append(f"| {r['metric']} | {r.get('value', '?')}{extra}"
                     f" | {r.get('unit', '?')} | {board} | {u} | {gap}"
                     f" | {upp} | {sess} | {conns} | {act} | {idle}"
                     f" | {capp} | {ff} | {mttr} | {hr} | {cfg} |")
    return "\n".join(lines)


_PROBE = re.compile(r"probe rc=(\d+) \[(\d\d:\d\d:\d\d)\]")


def probe_stats(paths):
    """Availability summary from hunter probe logs.

    A probe is 'up' on rc 0/3 (see scripts/tpu_probe.py). Windows are
    maximal runs of consecutive up-probes; their length is the span
    between the first and last probe of the run (a single up-probe is
    a >0-length window of unknown extent — counted, span 0)."""
    per_file = []
    for pat in paths:
        files = [pat]
        if os.path.isdir(pat):
            files = sorted(glob.glob(os.path.join(pat, "*probe.log")))
        for fp in files:
            try:
                with open(fp) as f:
                    per_file.append([(m.group(2),
                                      int(m.group(1)) in (0, 3))
                                     for m in _PROBE.finditer(f.read())])
            except OSError as e:
                print(f"bench_report: cannot read {fp}: {e}",
                      file=sys.stderr)
                continue

    def hms_to_s(h):
        a, b, c = h.split(":")
        return int(a) * 3600 + int(b) * 60 + int(c)

    n_probes = n_up = 0
    windows, spans = [], []

    def close(run_start, prev_t):
        windows.append((run_start, prev_t))
        d = hms_to_s(prev_t) - hms_to_s(run_start)
        # a window recorded across midnight wraps negative
        spans.append(d + 86400 if d < 0 else d)

    # runs never stitch across files — separate hunts are separate
    # timelines even when their HH:MM:SS happen to be adjacent
    for events in per_file:
        run_start = prev_t = None
        for t, up in events:
            n_probes += 1
            if up:
                n_up += 1
                if run_start is None:
                    run_start = t
                prev_t = t
            elif run_start is not None:
                close(run_start, prev_t)
                run_start = None
        if run_start is not None:
            close(run_start, prev_t)
    return {"probes": n_probes, "up": n_up,
            "up_pct": round(100.0 * n_up / n_probes, 1)
            if n_probes else None,
            "windows": len(windows),
            "window_spans_s": spans,
            "window_times": windows}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render bench tables / tunnel stats from logs")
    ap.add_argument("--date", default=time.strftime("%Y-%m-%d"))
    ap.add_argument("--platform", default=None)
    ap.add_argument("--log", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "results.jsonl"))
    ap.add_argument("--probe-log", nargs="*", default=[],
                    help="hunter probe.log files or their dirs")
    a = ap.parse_args(argv)

    for platform in ([a.platform] if a.platform else ["tpu", "cpu"]):
        recs = load_records(a.log, a.date, platform)
        if recs:
            print(f"\n## {platform} — {a.date}\n")
            print(render_table(recs))
    if a.probe_log:
        s = probe_stats(a.probe_log)
        print(f"\n## tunnel availability\n")
        print(json.dumps(s, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
