#!/bin/bash
# TPU window hunter (round 3): the axon tunnel flaps — minutes of
# health between ~25-minute outages (a client hangs in backend init
# and then raises UNAVAILABLE). A fixed-order sweep burns each healthy
# window on whatever step it happens to be stuck at, and every step
# attempted during an outage costs a ~25-minute init hang. This driver
# instead:
#  - PROBE-GATES every step: a 90s-bounded init+matmul probe (same
#    kill-safety protocol as bench.py's _preflight — the timeout-kill
#    can only land on a client hung in backend init, which has no
#    device program in flight and cannot wedge the tunnel);
#  - runs the single HIGHEST-PRIORITY remaining bench per healthy
#    probe, one client on the tunnel at a time;
#  - never kills a step once it is past the probe (every program here
#    is chunked/sized for the ~40s worker watchdog);
#  - records completed steps in $STATE so a restart resumes.
#
# Usage: bash scripts/tpu_window_hunter.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-benchmarks/tpu_hunt_r3}
STATE="$LOG/done"
mkdir -p "$LOG" "$STATE"

probe() {
    # bounded: rc 0/3 = backend alive (3 = startup ate the dispatch
    # window — alive but slow); timeout/other = down. Mirrors
    # bench.py::_preflight.
    timeout 90 python - <<'EOF' >>"$LOG/probe.log" 2>&1
import sys, time
t0 = time.time()
import jax, jax.numpy as jnp
jax.devices()
if time.time() - t0 > 60:
    sys.exit(3)
x = jnp.ones((256, 256)); print(float((x @ x).sum()))
EOF
    rc=$?
    [ $rc -eq 0 ] || [ $rc -eq 3 ]
}

run() {
    name=$1; shift
    [ -e "$STATE/$name" ] && return 0
    echo "=== $name: $* [$(date +%H:%M:%S)]" | tee -a "$LOG/hunt.log"
    "$@" >>"$LOG/hunt.log" 2>&1
    rc=$?
    echo "    rc=$rc [$(date +%H:%M:%S)]" | tee -a "$LOG/hunt.log"
    if [ $rc -eq 0 ]; then
        touch "$STATE/$name"
    fi
    # crashed-worker self-recovery grace (~15s) before the next client
    sleep 15
    return $rc
}

# headline note: bench.py falls back to CPU when the TPU dies
# mid-attempt; only a platform=tpu result marks that step done.
STEPS="headline train preprocess chase_xla chase_pls selfplay devmcts9 mcts19 mcts19r rl"
n_steps=$(echo $STEPS | wc -w)
deadline=$(( $(date +%s) + ${HUNT_BUDGET_S:-28800} ))

while [ "$(date +%s)" -lt "$deadline" ]; do
    n_done=$(ls "$STATE" | wc -l)
    if [ "$n_done" -eq "$n_steps" ]; then
        echo "hunt complete [$(date +%H:%M:%S)]" | tee -a "$LOG/hunt.log"
        break
    fi
    if ! probe; then
        sleep 45
        continue
    fi
    echo "--- window open ($n_done/$n_steps done) [$(date +%H:%M:%S)]" \
        | tee -a "$LOG/hunt.log"
    # one pass over the remaining steps; each step is itself
    # probe-gated so a window that closes mid-pass stops cheaply
    for s in $STEPS; do
        [ -e "$STATE/$s" ] && continue
        case $s in
            headline)   run headline env _GRAFT_BENCH_MAX_MOVES=300 bash -c 'python bench.py | tail -1 | tee /dev/stderr | grep -q "\"platform\": \"tpu\""' ;;
            train)      run train      python benchmarks/bench_train.py --batch-sweep 64,256,1024 --reps 3 ;;
            preprocess) run preprocess python benchmarks/bench_preprocess.py --reps 2 ;;
            chase_xla)  run chase_xla  python benchmarks/bench_chase.py --reps 2 ;;
            chase_pls)  run chase_pls  env ROCALPHAGO_PALLAS_CHASE=1 python benchmarks/bench_chase.py --reps 2 ;;
            selfplay)   run selfplay   python benchmarks/bench_selfplay.py --batch-sweep 16,64,256 --reps 2 ;;
            devmcts9)   run devmcts9   python benchmarks/bench_device_mcts.py --board 9 --sims 32 --reps 2 ;;
            mcts19)     run mcts19     python benchmarks/bench_mcts.py --board 19 --playouts 48 --reps 2 ;;
            mcts19r)    run mcts19r    python benchmarks/bench_mcts.py --board 19 --playouts 48 --lmbda 0.5 --device-rollout --reps 2 ;;
            rl)         run rl         python benchmarks/bench_rl.py --batch 16 --moves 100 --chunk 10 --reps 1 ;;
        esac || break   # step failed → backend likely died → reprobe
        probe || break
    done
done
echo "hunter exiting: $(ls "$STATE" | wc -l)/$n_steps done [$(date +%H:%M:%S)]" | tee -a "$LOG/hunt.log"
