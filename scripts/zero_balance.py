"""Diagnose zero-loop game balance: natural length vs move caps, and
the komi sensitivity of the outcome labels (VERDICT r4 weak #2).

Plays raw-policy self-play (one net, both colors — exactly the move
rule the zero loop's search degenerates to at temperature 1 with no
value influence on sampling) to NATURAL completion (two passes via the
sensibleness mask) under a generous move limit, then area-scores the
SAME final boards under a sweep of komi values. Because raw-policy
play never reads komi, one set of games cleanly separates the two
suspects the round-4 verdict named:

* truncation — what fraction of games actually end by two passes
  within N plies (the round-4 run capped at 80 and the fraction was
  implicitly 0%: ``mean_moves`` pinned at the cap for 267 iterations);
* komi — the black/white win split of *finished* games as a function
  of komi, plus the raw area-difference distribution, which shows
  directly what compensation the current policy strength supports.

Usage:
  python scripts/zero_balance.py results/zero_scale_r4/run/policy.json \
      [--batch 256] [--max-moves 240] [--komi 5.5 6.5 7.0 7.5] \
      [--seed 0] [--out results/zero_balance_r5/balance.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np

from rocalphago_tpu.engine.pygo import score_board
from rocalphago_tpu.models.nn_util import NeuralNetBase
from rocalphago_tpu.search.selfplay import make_selfplay_chunked


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("policy_json")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--max-moves", type=int, default=240)
    ap.add_argument("--chunk", type=int, default=20)
    ap.add_argument("--komi", type=float, nargs="+",
                    default=[5.5, 6.5, 7.0, 7.5])
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    a = ap.parse_args(argv)

    net = NeuralNetBase.load_model(a.policy_json)
    cfg = net.cfg
    run = make_selfplay_chunked(
        cfg, net.feature_list, net.module.apply, net.module.apply,
        a.batch, max_moves=a.max_moves, chunk=a.chunk,
        temperature=a.temperature, score_on_device=False)
    res = run(net.params, net.params, jax.random.key(a.seed),
              stop_when_done=True)
    done = np.asarray(jax.device_get(res.final.done))
    moves = np.asarray(jax.device_get(res.num_moves))
    boards = np.asarray(jax.device_get(res.final.board))

    # komi-free area difference (black - white stones-and-territory);
    # score_board returns (black, white + komi) so call it with komi 0
    diffs = np.empty(a.batch, np.float64)
    for g in range(a.batch):
        b, w = score_board(boards[g].reshape(cfg.size, cfg.size), 0.0)
        diffs[g] = b - w
    # the komi sensitivity and area-diff stats are over FINISHED games
    # only — scoring a move-capped half-played board is exactly the
    # truncation artifact this script separates komi effects from
    fdiffs = diffs[done]
    if not done.any():
        raise SystemExit(
            f"no game finished within --max-moves {a.max_moves}; "
            "raise it — komi stats over truncated boards would "
            "re-conflate the two effects this script separates")

    report = {
        "policy": a.policy_json,
        "board": cfg.size,
        "batch": a.batch,
        "max_moves": a.max_moves,
        "temperature": a.temperature,
        "seed": a.seed,
        "finished_by_passes": round(float(done.mean()), 4),
        "moves": {
            "mean": round(float(moves.mean()), 2),
            "p50": float(np.percentile(moves, 50)),
            "p90": float(np.percentile(moves, 90)),
            "p99": float(np.percentile(moves, 99)),
            "max": int(moves.max()),
        },
        "area_diff": {          # black minus white, before komi;
            "mean": round(float(fdiffs.mean()), 3),   # finished only
            "p10": float(np.percentile(fdiffs, 10)),
            "p50": float(np.percentile(fdiffs, 50)),
            "p90": float(np.percentile(fdiffs, 90)),
        },
        "komi": {},             # finished games only
    }
    for k in a.komi:
        kd = fdiffs - k
        report["komi"][str(k)] = {
            "black_win": round(float((kd > 0).mean()), 4),
            "white_win": round(float((kd < 0).mean()), 4),
            "draw": round(float((kd == 0).mean()), 4),
        }
    print(json.dumps(report, indent=2))
    if a.out:
        os.makedirs(os.path.dirname(a.out) or ".", exist_ok=True)
        with open(a.out, "w") as f:
            json.dump(report, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
