#!/usr/bin/env bash
# End-to-end pipeline demo on CPU — the complete AlphaGo recipe as
# installed CLIs: SGF corpus → training shards → SL policy training
# (data-parallel over 8 virtual devices) → held-out top-1 eval →
# mesh-sharded batched self-play → REINFORCE improvement → value
# corpus + value training → MCTS-vs-greedy tournament → GTP →
# AlphaZero-style loop over the on-device search (training.zero).
#
# The reference's workflow (SURVEY.md §3.1–§3.5: game_converter →
# supervised/reinforcement/value trainers → ai/mcts/gtp_wrapper),
# exercised as a product: every stage is the installed CLI, artifacts
# land in $OUT.
#
#   bash scripts/pipeline_demo.sh [OUT_DIR]
#
# Runs ~5-10 minutes on one CPU host (tiny nets, bundled SGFs).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-/tmp/rocalphago_demo}"
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
PY="python"
rm -rf "$OUT"      # fresh demo dir — stale shards/splits would trip
mkdir -p "$OUT"    # the trainer's corpus-changed resume guard

echo "== 1/9 convert: bundled SGFs → npz shards"
$PY -m rocalphago_tpu.data.convert \
    --directory tests/test_data --outfile "$OUT/corpus" --size 9

echo "== 2/9 spec + SL training (2 epochs, 8-device data parallel)"
$PY -m rocalphago_tpu.models.specs policy --out "$OUT/policy.json" \
    --board 9 --layers 2 --filters 16
$PY -m rocalphago_tpu.training.sl "$OUT/policy.json" "$OUT/corpus" \
    "$OUT/sl" --epochs 2 --minibatch 16
echo "   metadata:"; tail -c 400 "$OUT/sl/metadata.json"; echo

echo "== 3/9 held-out eval (top-1 / loss on the test split)"
$PY -m rocalphago_tpu.training.evaluate "$OUT/sl/model.json" \
    "$OUT/corpus" --split test --shuffle-npz "$OUT/sl/shuffle.npz"

echo "== 4/9 batched self-play with the trained policy (sharded)"
$PY -m rocalphago_tpu.interface.selfplay_cli \
    --policy "$OUT/sl/model.json" --games 16 --max-moves 30 \
    --chunk 15 --shard --out "$OUT/selfplay"

echo "== 5/9 REINFORCE self-play improvement (2 tiny iterations)"
$PY -m rocalphago_tpu.training.rl "$OUT/sl/model.json" "$OUT/rl" \
    --game-batch 4 --iterations 2 --move-limit 25 --save-every 1
echo

echo "== 6/9 value corpus (one de-correlated position/game) + training"
$PY -m rocalphago_tpu.training.selfplay_data "$OUT/sl/model.json" \
    "$OUT/rl/model.json" "$OUT/value_data" --n-positions 48 \
    --batch 8 --max-moves 30
$PY -m rocalphago_tpu.models.specs value --out "$OUT/value.json" \
    --board 9 --layers 2 --filters 16
$PY -m rocalphago_tpu.training.value "$OUT/value.json" \
    "$OUT/value_data" "$OUT/value" --epochs 1 --minibatch 8 \
    --train-val-test 0.8 0.1 0.1

echo "== 7/9 head-to-head: MCTS(RL policy + value net) vs greedy SL"
$PY -m rocalphago_tpu.interface.tournament \
    "mcts:$OUT/rl/model.json:$OUT/value/model.json" \
    "greedy:$OUT/sl/model.json" --games 2 --board 9 \
    --move-limit 40 --playouts 8

echo "== 8/9 GTP smoke: genmove with the trained policy"
printf 'boardsize 9\nclear_board\ngenmove b\nquit\n' | \
    $PY -m rocalphago_tpu.interface.gtp --policy "$OUT/sl/model.json"

echo "== 9/9 AlphaZero-style loop over the on-device search (1 tiny iteration)"
$PY -m rocalphago_tpu.training.zero "$OUT/rl/model.json" \
    "$OUT/value/model.json" "$OUT/zero" --game-batch 2 \
    --iterations 1 --move-limit 20 --sims 4 --sim-chunk 2

echo "PIPELINE DEMO OK — artifacts in $OUT"
