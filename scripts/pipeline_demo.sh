#!/usr/bin/env bash
# End-to-end pipeline demo on CPU: SGF corpus → training shards →
# SL training (data-parallel over 8 virtual devices) → held-out eval
# → batched self-play → GTP move generation.
#
# The reference's workflow (SURVEY.md §3.1/§3.4/§3.5: game_converter →
# supervised_policy_trainer → ai/gtp_wrapper), exercised as a product:
# every stage is the installed CLI, artifacts land in $OUT.
#
#   bash scripts/pipeline_demo.sh [OUT_DIR]
#
# Finishes in a few minutes on one CPU host (tiny net, bundled SGFs).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-/tmp/rocalphago_demo}"
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
PY="python"
rm -rf "$OUT"      # fresh demo dir — stale shards/splits would trip
mkdir -p "$OUT"    # the trainer's corpus-changed resume guard

echo "== 1/5 convert: bundled SGFs → npz shards"
$PY -m rocalphago_tpu.data.convert \
    --directory tests/test_data --outfile "$OUT/corpus" --size 9

echo "== 2/5 spec + SL training (2 epochs, 8-device data parallel)"
$PY -m rocalphago_tpu.models.specs policy --out "$OUT/policy.json" \
    --board 9 --layers 2 --filters 16
$PY -m rocalphago_tpu.training.sl "$OUT/policy.json" "$OUT/corpus" \
    "$OUT/sl" --epochs 2 --minibatch 16
echo "   metadata:"; tail -c 400 "$OUT/sl/metadata.json"; echo

echo "== 3/5 held-out eval (top-1 / loss on the test split)"
$PY -m rocalphago_tpu.training.evaluate "$OUT/sl/model.json" \
    "$OUT/corpus" --split test --shuffle-npz "$OUT/sl/shuffle.npz"

echo "== 4/5 batched self-play with the trained policy (sharded)"
$PY -m rocalphago_tpu.interface.selfplay_cli \
    --policy "$OUT/sl/model.json" --games 16 --max-moves 30 \
    --chunk 15 --shard --out "$OUT/selfplay"

echo "== 5/5 GTP smoke: genmove with the trained policy"
printf 'boardsize 9\nclear_board\ngenmove b\nquit\n' | \
    $PY -m rocalphago_tpu.interface.gtp --policy "$OUT/sl/model.json"

echo "PIPELINE DEMO OK — artifacts in $OUT"
