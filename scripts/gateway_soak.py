"""Gateway soak: the network front end under kills + overload.

Runs a real :class:`~rocalphago_tpu.gateway.server.GatewayServer`
(tiny nets, a warmed :class:`~rocalphago_tpu.serve.sessions.
ServePool`, the /healthz+/metrics sidecar) and proves the gateway's
headline claims (docs/GATEWAY.md):

* **overload sheds are structured and counted** — the storm drives
  MORE concurrent connections than ``--max-conns``, so every round
  sheds; each shed is a typed ``overload`` frame client-side AND a
  ``gateway_connections_total{result="shed"}`` increment scraped
  back off ``/metrics`` (the two tallies must agree exactly);
* **kills stay inside the fault wall** — a ``kill@gateway.conn``
  plan (docs/RESILIENCE.md "Fault injection") aborts random
  connections mid-conversation; the handler answers with a typed
  ``internal`` error, the session closes, the slot frees, and
  ``requests.unhandled`` stays ZERO for the whole soak;
* **after the storm a fault-free GATE round runs clean** — exactly
  ``--max-conns`` connections, every move lands, nothing shed;
* **SIGTERM drains gracefully** — the supervisor's handler
  (docs/RESILIENCE.md "Fleet supervision") flips ``draining``, the
  gateway stops accepting, finishes in-flight moves, closes every
  session (pool live count returns to zero) and the process is free
  to exit 0; the drain timeline (``gateway_requested`` →
  ``gateway_accept_stopped`` → ``gateway_drained``) lands in
  ``metrics.jsonl``.

Kill draws are deterministic per seed at each barrier hit, but the
interleaving of connections is not — so the harness asserts a
MINIMUM kill count (``--min-kills``) and keeps soaking until the
floor is met (bounded by ``--deadline-s``), the same contract as
``scripts/chaos_soak.py``.

Tier-1 smoke: ``tests/test_gateway.py`` runs this with
``--min-kills 1 --conns 3 --max-conns 2``; the @slow soak runs the
defaults.

Usage::

    JAX_PLATFORMS=cpu python scripts/gateway_soak.py --out /tmp/soak
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile

sys.path.insert(0, ".")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="run dir for metrics.jsonl + summary.json "
                    "(default: a fresh temp dir)")
    ap.add_argument("--board", type=int, default=5)
    ap.add_argument("--sims", type=int, default=2)
    ap.add_argument("--conns", type=int, default=6,
                    help="concurrent connections per storm round "
                    "(keep it above --max-conns so rounds shed)")
    ap.add_argument("--max-conns", type=int, default=3,
                    help="the gateway's connection cap")
    ap.add_argument("--moves", type=int, default=4,
                    help="genmoves per connection per round")
    ap.add_argument("--seed", type=int, default=7,
                    help="kill-schedule seed (per-barrier draws)")
    ap.add_argument("--p-kill", type=float, default=0.15,
                    help="per-request kill probability at the "
                    "gateway.conn barrier")
    ap.add_argument("--plan", default=None,
                    help="override the whole fault plan verbatim")
    ap.add_argument("--min-kills", type=int, default=3,
                    help="soak until at least this many connections "
                    "were kill-aborted")
    ap.add_argument("--deadline-s", type=float, default=180.0,
                    help="hard wall-clock bound on the whole soak")
    ap.add_argument("--slo-ms", type=float, default=1000.0,
                    help="per-genmove SLO the gateway arms")
    return ap


def main() -> int:
    args = build_parser().parse_args()
    out_dir = args.out or tempfile.mkdtemp(prefix="gateway_soak_")
    os.makedirs(out_dir, exist_ok=True)

    import time
    import urllib.request

    from rocalphago_tpu.gateway.client import run_load
    from rocalphago_tpu.gateway.httpapi import GatewayHTTP
    from rocalphago_tpu.gateway.server import GatewayServer
    from rocalphago_tpu.io.metrics import MetricsLogger
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.runtime import faults
    from rocalphago_tpu.runtime.supervisor import Supervisor
    from rocalphago_tpu.serve.sessions import ServePool

    plan = (args.plan if args.plan is not None else
            f"kill@gateway.conn:p={args.p_kill},seed={args.seed}")
    metrics = MetricsLogger(os.path.join(out_dir, "metrics.jsonl"),
                            echo=False)
    metrics.log("gateway_soak", phase="start", plan=plan,
                conns=args.conns, max_conns=args.max_conns,
                min_kills=args.min_kills, seed=args.seed)

    # ------------------------------------------------- the tiny rig
    feats = ("board", "ones")
    pol = CNNPolicy(feats, board=args.board, layers=1,
                    filters_per_layer=2)
    val = CNNValue(feats + ("color",), board=args.board, layers=1,
                   filters_per_layer=2)
    pool = ServePool(val, pol, n_sim=args.sims,
                     max_sessions=args.max_conns,
                     batch_sizes=(1, 2), max_wait_us=2000.0,
                     metrics=metrics)
    pool.warm()
    server = GatewayServer(pool, max_conns=args.max_conns,
                           slo_ms=args.slo_ms,
                           metrics=metrics).start()
    http = GatewayHTTP(server).start()
    sup = Supervisor(metrics=metrics)
    sigterm_installed = sup.install_sigterm()

    def settle(timeout_s: float = 10.0) -> None:
        """Wait for the previous round's handlers to release their
        slots — a straggler would turn the gate round into a shed."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if server.stats()["conns"]["live"] == 0:
                return
            time.sleep(0.05)

    # --------------------------------------------------- the storm
    faults.install(plan)
    totals = {"moves": 0, "sheds": 0, "disconnects": 0, "errors": 0}
    rounds = 0
    t0 = time.monotonic()
    rc = 0
    gate = None
    try:
        while time.monotonic() - t0 < args.deadline_s:
            stats = server.stats()
            if (totals["moves"] > 0 and totals["sheds"] > 0
                    and stats["faults"]["kills"] >= args.min_kills):
                break
            out = run_load("127.0.0.1", server.port,
                           conns=args.conns, moves=args.moves,
                           timeout=60.0)
            for k in totals:
                totals[k] += out[k]
            rounds += 1
            settle()
    finally:
        # ------------------------------------------- the clean gate
        faults.install("")
        metrics.log("gateway_soak", phase="gate")
        try:
            settle()
            gate = run_load("127.0.0.1", server.port,
                            conns=args.max_conns, moves=args.moves,
                            timeout=60.0)
        except Exception as e:  # noqa: BLE001 — a red gate is a
            #                     verdict, not a harness crash
            metrics.log("gateway_soak", phase="gate_error",
                        error=f"{type(e).__name__}: {e}")

        # -------------------------- scrape the sheds off /metrics
        metrics_shed = None
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/metrics",
                timeout=10.0).read().decode()
            for line in body.splitlines():
                if line.startswith(
                        'gateway_connections_total{result="shed"}'):
                    metrics_shed = int(float(line.split()[-1]))
        except Exception as e:  # noqa: BLE001 — counted as a miss
            metrics.log("gateway_soak", phase="scrape_error",
                        error=f"{type(e).__name__}: {e}")

        # ------------------------------------- the SIGTERM drain
        if sigterm_installed:
            os.kill(os.getpid(), signal.SIGTERM)
            drain_t0 = time.monotonic()
            while (not sup.draining
                   and time.monotonic() - drain_t0 < 10.0):
                time.sleep(0.02)
        else:                  # not the main thread (test harness)
            sup.request_drain(reason="sigterm")
        server.drain(reason="sigterm")
        http.close()
        final = server.stats()
        pool_live = pool.stats()["sessions"]["live"]
        pool.close()
        sup.restore_sigterm()
        faults.install(None)

    # ------------------------------------------------- the verdict
    kills = final["faults"]["kills"]
    drain_phases = {json.loads(line).get("phase")
                    for line in open(metrics.path)
                    if json.loads(line).get("event") == "drain"}
    summary = {
        "plan": plan,
        "rounds": rounds,
        "moves": totals["moves"],
        "sheds_client": totals["sheds"],
        "sheds_server": final["conns"]["shed"],
        "sheds_metrics": metrics_shed,
        "disconnects": totals["disconnects"],
        "client_errors": totals["errors"],
        "kills": kills,
        "unhandled": final["requests"]["unhandled"],
        "gate": gate,
        "drained": final["draining"],
        "live_conns_after_drain": final["conns"]["live"],
        "pool_sessions_after_drain": pool_live,
        "drain_phases": sorted(p for p in drain_phases if p),
        "sigterm_installed": sigterm_installed,
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    checks = {
        "moves_landed": totals["moves"] > 0,
        "sheds_observed": totals["sheds"] > 0,
        "sheds_counted": (metrics_shed is not None
                          and metrics_shed == final["conns"]["shed"]
                          and metrics_shed > 0),
        "min_kills": kills >= args.min_kills,
        "no_unhandled": final["requests"]["unhandled"] == 0,
        "gate_green": (gate is not None and gate["sheds"] == 0
                       and gate["disconnects"] == 0
                       and gate["errors"] == 0
                       and gate["moves"]
                       == args.max_conns * args.moves),
        "drain_clean": (final["draining"]
                        and final["conns"]["live"] == 0
                        and pool_live == 0
                        and {"gateway_requested",
                             "gateway_accept_stopped",
                             "gateway_drained"} <= drain_phases),
    }
    summary["checks"] = checks
    metrics.log("gateway_soak", phase="done", **{
        k: v for k, v in summary.items() if k != "checks"})
    metrics.close()
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))
    if rc == 0 and not all(checks.values()):
        rc = 1
    if rc:
        failed = [k for k, v in checks.items() if not v]
        print(f"gateway_soak: FAILED checks: {failed}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
