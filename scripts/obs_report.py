"""Render a run's observability report from its ``metrics.jsonl``.

The capstone of the obs subsystem (docs/OBSERVABILITY.md): every
trainer wraps its iteration phases in tracing spans and logs its
metric-registry snapshot, all into the run directory's
``metrics.jsonl``; this script turns that stream into the per-phase
time breakdown and histogram summary a perf investigation starts
from — which phase dominates an iteration, whether recompiles fired
mid-run, where the genmove latency tail sits.

Stdlib-only (reads through the crash-tolerant
``rocalphago_tpu.runtime.jsonl`` reader — no jax import), so it runs
anywhere, including on a laptop against a copied log.

Usage:
    python scripts/obs_report.py RUN_DIR_or_metrics.jsonl [--top N]
    python scripts/obs_report.py --selftest   # fixture render (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from rocalphago_tpu.obs.registry import quantile_from_buckets  # noqa: E402
from rocalphago_tpu.runtime.jsonl import read_jsonl  # noqa: E402


def nearest_rank(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def span_stats(records) -> dict:
    """``path -> {count, total_s, durs}`` over the span records."""
    out: dict = {}
    for r in records:
        if r.get("event") != "span" or "path" not in r:
            continue
        s = out.setdefault(r["path"], {"count": 0, "total_s": 0.0,
                                       "durs": [], "errors": 0})
        d = float(r.get("dur_s") or 0.0)
        s["count"] += 1
        s["total_s"] += d
        s["durs"].append(d)
        if not r.get("ok", True):
            s["errors"] += 1
    for s in out.values():
        s["durs"].sort()
    return out


def _fmt_s(v) -> str:
    return "—" if v is None else f"{v:.3f}"


def render_spans(stats: dict) -> str:
    """Indented tree (paths sort parents before children), with each
    span's share of its parent's total — the 'where did the time go'
    table."""
    if not stats:
        return "(no span records)"
    width = max(len(p) for p in stats) + 2
    lines = [f"{'span':<{width}} {'count':>6} {'total_s':>9} "
             f"{'mean_s':>8} {'p50_s':>8} {'p99_s':>8} {'%parent':>8}"]
    for path in sorted(stats):
        s = stats[path]
        parent, _, name = path.rpartition("/")
        share = ""
        if parent and parent in stats and stats[parent]["total_s"] > 0:
            frac = 100.0 * s["total_s"] / stats[parent]["total_s"]
            share = f"{frac:.1f}%"
        indent = "  " * path.count("/")
        label = indent + name
        err = f"  ({s['errors']} failed)" if s["errors"] else ""
        lines.append(
            f"{label:<{width}} {s['count']:>6} {s['total_s']:>9.3f} "
            f"{_fmt_s(s['total_s'] / s['count']):>8} "
            f"{_fmt_s(nearest_rank(s['durs'], 0.5)):>8} "
            f"{_fmt_s(nearest_rank(s['durs'], 0.99)):>8} "
            f"{share:>8}{err}")
    return "\n".join(lines)


def render_registry(snap: dict) -> str:
    """Counters/gauges as-is; histograms as count/sum + estimated
    p50/p99 (bucket upper edges) + the non-empty buckets."""
    lines = []
    for key, v in snap.get("counters", {}).items():
        lines.append(f"counter   {key} = {v}")
    for key, v in snap.get("gauges", {}).items():
        lines.append(f"gauge     {key} = {v}")
    for key, h in snap.get("histograms", {}).items():
        p50 = quantile_from_buckets(h, 0.5)
        p99 = quantile_from_buckets(h, 0.99)
        prev = 0
        occupied = []
        for edge, cum in h["buckets"].items():
            if cum > prev:
                occupied.append(f"≤{edge}:{cum - prev}")
            prev = cum
        lines.append(
            f"histogram {key}: count={h['count']} sum={h['sum']} "
            f"p50≲{p50} p99≲{p99}  [{' '.join(occupied)}]")
    return "\n".join(lines) if lines else "(no registry snapshot)"


_LABEL = None   # lazy-compiled label-extraction regex


def _runner_label(key: str):
    """``'dispatch_gap_s{runner="selfplay"}'`` -> ``'selfplay'``."""
    global _LABEL
    if _LABEL is None:
        import re

        _LABEL = re.compile(r'runner="([^"]*)"')
    m = _LABEL.search(key)
    return m.group(1) if m else key


def render_dispatch(snap: dict) -> str:
    """Occupancy/gap table per pipelined runner (runtime.pipeline):
    the device-occupancy gauge next to the dispatch-gap histogram's
    count/total/p99 — the 'was the device ever idle between chunks'
    row that makes the pipelining win (or a sync regression) visible
    in any run's metrics.jsonl."""
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    runners: dict = {}
    for key, v in gauges.items():
        if key.startswith("device_occupancy"):
            runners.setdefault(_runner_label(key), {})["occ"] = v
    for key, h in hists.items():
        if key.startswith("dispatch_gap_s"):
            runners.setdefault(_runner_label(key), {})["gap"] = h
    if not runners:
        return "(no pipelined runners recorded)"
    width = max(len(r) for r in runners) + 2
    lines = [f"{'runner':<{width}} {'occupancy':>9} {'gaps':>6} "
             f"{'gap_total_s':>12} {'gap_p99_s':>10}"]
    for name in sorted(runners):
        r = runners[name]
        occ = r.get("occ")
        occ_s = "—" if occ is None else f"{100.0 * occ:.1f}%"
        h = r.get("gap")
        if h:
            p99 = quantile_from_buckets(h, 0.99)
            lines.append(f"{name:<{width}} {occ_s:>9} "
                         f"{h['count']:>6} {h['sum']:>12.3f} "
                         f"{_fmt_s(p99):>10}")
        else:
            lines.append(f"{name:<{width}} {occ_s:>9} {'—':>6} "
                         f"{'—':>12} {'—':>10}")
    return "\n".join(lines)


def render_evalcache(snap: dict) -> str:
    """Transposition-cache panel (serve/evalcache.py; docs/SERVING.md
    "Evaluation cache"): hit economics and residency, the in-batch
    dedup the dispatcher folds on top, and the two safety tallies —
    version evictions (hot-swap invalidation, correctness under
    version-number reuse) and verify-mode collisions (each one a
    silently-wrong answer that wasn't)."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hits = counters.get("eval_cache_hits_total")
    misses = counters.get("eval_cache_misses_total")
    uniq = counters.get("serve_unique_rows_total")
    dedup = counters.get("serve_dedup_rows_saved_total")
    if hits is None and misses is None and uniq is None:
        return "(no eval cache records)"
    lines = []
    total = (hits or 0) + (misses or 0)
    if total:
        entries = gauges.get("eval_cache_entries")
        res = ("" if entries is None
               else f", {entries:g} entries resident")
        lines.append(f"lookups: {hits or 0} hits / {total} "
                     f"({100.0 * (hits or 0) / total:.1f}% hit rate)"
                     f"{res}")
    if uniq is not None or dedup is not None:
        lines.append(f"device rows: {uniq or 0} unique evaluated, "
                     f"{dedup or 0} in-batch dupes folded")
    evc = counters.get(
        'eval_cache_evictions_total{reason="capacity"}', 0)
    evv = counters.get(
        'eval_cache_evictions_total{reason="version"}', 0)
    coll = counters.get("eval_cache_collisions_total", 0)
    lines.append(f"evictions: capacity={evc} version={evv}, "
                 f"collisions detected: {coll}")
    return "\n".join(lines)


def render_encode(stats: dict, snap: dict) -> str:
    """Encode-path table (the encode overhaul's observability leg):
    per-board per-position cost from the ``encode_pos_us`` histograms
    that ``features/api.py::Preprocess`` records on every host-boundary
    encode, next to the encode span totals and the encode entry
    points' compile counts — 'where does encode time go and did it
    recompile' in one place."""
    hists = {k: h for k, h in snap.get("histograms", {}).items()
             if k.startswith("encode_pos_us")}
    counters = snap.get("counters", {})
    if not hists:
        return "(no encode records)"
    lines = [f"{'board':<8} {'positions':>10} {'p50_us':>10} "
             f"{'p99_us':>10}"]
    for key in sorted(hists):
        h = hists[key]
        label = _runner_label(key)
        if 'board="' in key:
            import re

            m = re.search(r'board="([^"]*)"', key)
            label = m.group(1) if m else key
        p50 = quantile_from_buckets(h, 0.5)
        p99 = quantile_from_buckets(h, 0.99)
        lines.append(f"{label:<8} {h['count']:>10} "
                     f"{('≲' + format(p50, 'g')) if p50 else '—':>10} "
                     f"{('≲' + format(p99, 'g')) if p99 else '—':>10}")
    compiles = {k: v for k, v in counters.items()
                if k.startswith("jax_compiles_total")
                and 'entry="encode' in k}
    if compiles:
        lines.append("compiles: " + "  ".join(
            f"{k}={v}" for k, v in sorted(compiles.items())))
    # incremental-encode hit rate (features/incremental.py): how many
    # positions rode the delta path, and of the ladder chases that
    # path COULD have run, how many were answered by a cached verdict
    delta = counters.get("encode_delta_total", 0)
    full = counters.get("encode_full_total", 0)
    if delta:
        reused = counters.get("encode_incr_verdicts_reused_total", 0)
        ran = counters.get("encode_incr_chases_run_total", 0)
        share = 100.0 * delta / max(delta + full, 1)
        hit = 100.0 * reused / max(reused + ran, 1)
        lines.append(
            f"incremental encode: {delta} delta / {full} full "
            f"({share:.0f}% delta); chase verdicts reused "
            f"{reused}/{reused + ran} ({hit:.0f}% hit)")
        # the invalidation cascade (features/incremental.py): how much
        # per-ply churn the coarse region keys let through, and how
        # often a dormant entry's verdict flip forced a re-chase
        inval = counters.get(
            "encode_incr_entries_invalidated_total", 0)
        foot = counters.get("encode_incr_foot_hits_total", 0)
        flips = counters.get("encode_incr_verdict_flips_total", 0)
        revived = counters.get("encode_incr_entries_revived_total", 0)
        if foot or inval:
            lines.append(
                f"invalidation cascade: {inval / delta:.2f} "
                f"invalidations/ply ({foot} footprint hits → "
                f"{inval} cell-verified stale, {flips} verdict "
                f"flips re-chased, {revived} revived)")
        resets = {k: v for k, v in counters.items()
                  if k.startswith("encode_cache_resets_total")}
        if resets:
            lines.append("cache resets: " + "  ".join(
                f"{k.split('reason=', 1)[-1].strip(chr(34) + '{}')}"
                f"={v}" for k, v in sorted(resets.items())))
    # ladder-free configuration (ROCALPHAGO_LADDER_PLANES): which
    # plane family the run's encoders were built with
    encs = {k: v for k, v in counters.items()
            if k.startswith("encode_encoders_total")}
    if encs:
        def fam(k):
            return k.split("planes=", 1)[-1].strip(chr(34) + "{}")

        no = sum(v for k, v in encs.items() if fam(k) == "noladder")
        lad = sum(v for k, v in encs.items() if fam(k) == "ladder")
        tag = (" — ladder-free" if no and not lad else
               " — MIXED plane families" if no and lad else "")
        lines.append(f"encoders: ladder={lad} noladder={no}{tag}")
    spans = {p: s for p, s in stats.items()
             if p.rsplit("/", 1)[-1] == "encode"}
    if spans:
        total = sum(s["total_s"] for s in spans.values())
        count = sum(s["count"] for s in spans.values())
        lines.append(f"encode spans: {count} calls, "
                     f"{total:.3f}s total")
    return "\n".join(lines)


def render_actor_learner(snap: dict) -> str:
    """Actor/learner split health (docs/SCALE.md): ingest volume and
    rate, buffer fill, the learner's step count and idle fraction,
    and the sample-staleness quantiles — 'are the actors keeping the
    learner fed, and how stale is what it eats' in one block."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    hists = snap.get("histograms", {})
    ingest = counters.get("replay_ingest_games_total")
    steps = counters.get("learner_steps_total")
    if ingest is None and steps is None:
        return "(no actor/learner records)"
    lines = []
    rate = gauges.get("replay_ingest_per_min")
    fill = gauges.get("replay_fill_games")
    evicted = counters.get("replay_evicted_games_total")
    lines.append(
        f"ingest: {ingest or 0} games"
        + (f" @ {rate:.1f}/min" if rate is not None else "")
        + (f", buffer fill {fill:g}" if fill is not None else "")
        + (f", {evicted} evicted" if evicted else ""))
    idle = gauges.get("learner_idle_frac")
    lines.append(
        f"learner: {steps or 0} steps, idle "
        + (f"{100.0 * idle:.1f}%" if idle is not None else "—"))
    h = hists.get("replay_sample_staleness_seconds")
    if h:
        p50 = quantile_from_buckets(h, 0.5)
        p99 = quantile_from_buckets(h, 0.99)
        lines.append(f"staleness: p50≲{p50} p99≲{p99} "
                     f"({h['count']} consumed)")
    actors = {k: v for k, v in counters.items()
              if k.startswith("actor_games_total")}
    if actors:
        lines.append("actors: " + "  ".join(
            f"{k.split('actor=', 1)[-1].strip(chr(34) + '{}')}={v}"
            for k, v in sorted(actors.items())))
    return "\n".join(lines)


def render_fleet(records, snap: dict) -> str:
    """Fleet supervision health (runtime/supervisor.py;
    docs/RESILIENCE.md "Fleet supervision"): restarts grouped by
    worker and reason, parked workers, learner failovers, recovery
    times (death detection → first post-restart heartbeat), and the
    preemption-drain timeline — 'who died, who came back, how fast,
    and did the drain land cleanly' in one block."""
    restarts: dict = {}
    parks, mttrs, failovers, drains = [], [], [], []
    for r in records:
        ev = r.get("event")
        if ev == "worker_restart":
            key = (str(r.get("worker", "?")),
                   str(r.get("reason", "?")))
            restarts[key] = restarts.get(key, 0) + 1
        elif ev == "worker_parked":
            parks.append(r)
        elif ev == "worker_recovered":
            if r.get("mttr_s") is not None:
                mttrs.append(float(r["mttr_s"]))
        elif ev == "learner_failover":
            failovers.append(r)
        elif ev == "drain":
            drains.append(r)
    if not (restarts or parks or failovers or drains):
        # a copied log tail can keep the registry counters without
        # the lifecycle events — summarize from the snapshot then
        counters = {k: v for k, v in snap.get("counters", {}).items()
                    if k.startswith("supervisor_")}
        if counters:
            return "\n".join(f"{k}={v}"
                             for k, v in sorted(counters.items()))
        return "(no fleet supervision records)"
    lines = []
    if restarts:
        per_worker: dict = {}
        for (w, reason), n in restarts.items():
            per_worker.setdefault(w, {})[reason] = n
        lines.append("restarts: " + "  ".join(
            f"{w}={sum(d.values())} ("
            + ", ".join(f"{k}={v}" for k, v in sorted(d.items()))
            + ")" for w, d in sorted(per_worker.items())))
    if parks:
        lines.append("parked: " + "  ".join(
            f"{p.get('worker', '?')} ({p.get('reason', '?')} after "
            f"{p.get('deaths', '?')} deaths)" for p in parks))
    if failovers:
        last = failovers[-1]
        lines.append(
            f"learner failovers: {len(failovers)} (last restored "
            f"step {last.get('restored_step', '?')}, target "
            f"{last.get('target', '?')})")
    if mttrs:
        lines.append(
            f"recovery: mean {sum(mttrs) / len(mttrs):.3f}s, max "
            f"{max(mttrs):.3f}s over {len(mttrs)} restarts")
    if drains:
        t0 = drains[0].get("time")
        steps = []
        for d in drains:
            label = str(d.get("phase", "?"))
            if d is drains[0] and d.get("reason"):
                label += f" ({d['reason']})"
            if d.get("iteration") is not None:
                label += f" @ iter {d['iteration']}"
            if d.get("step") is not None:
                label += f" @ step {d['step']}"
            t = d.get("time")
            if d is not drains[0] and t0 is not None and t is not None:
                label += f" +{float(t) - float(t0):.1f}s"
            steps.append(label)
        lines.append("drain: " + " → ".join(steps))
    return "\n".join(lines)


def render_gateway(records, snap: dict) -> str:
    """Network gateway health (gateway/server.py; docs/GATEWAY.md):
    connections accepted vs shed, the request/error mix, wire-latency
    percentiles, and the gateway drain timeline — 'did the front door
    shed cleanly and how slow was the wire' in one block."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    conns = {k: v for k, v in counters.items()
             if k.startswith("gateway_connections_total")}
    reqs = {k: v for k, v in counters.items()
            if k.startswith("gateway_requests_total")}
    errors = {k: v for k, v in counters.items()
              if k.startswith("gateway_errors_total")}
    wire = snap.get("histograms", {}).get("gateway_wire_seconds")
    drains = [r for r in records
              if r.get("event") == "drain"
              and str(r.get("phase", "")).startswith("gateway_")]
    if not (conns or reqs or errors or wire or drains):
        return "(no gateway records)"
    lines = []
    if conns:
        def count(result):
            return conns.get(
                f'gateway_connections_total{{result="{result}"}}', 0)

        live = gauges.get("gateway_conns_live")
        live_s = "" if live is None else f", {int(live)} live"
        lines.append(f"connections: {count('accepted')} accepted, "
                     f"{count('shed')} shed{live_s}")
    if reqs:
        lines.append("requests: " + "  ".join(
            f"{k.split('type=', 1)[-1].strip(chr(34) + '{}')}={v}"
            for k, v in sorted(reqs.items())))
    if errors:
        lines.append("errors: " + "  ".join(
            f"{k.split('code=', 1)[-1].strip(chr(34) + '{}')}={v}"
            for k, v in sorted(errors.items())))
    if wire and wire.get("count"):
        p50 = quantile_from_buckets(wire, 0.5)
        p99 = quantile_from_buckets(wire, 0.99)
        lines.append(f"wire: {wire['count']} genmoves, "
                     f"p50≲{p50}s p99≲{p99}s")
    if drains:
        t0 = drains[0].get("time")
        steps = []
        for d in drains:
            label = str(d.get("phase", "?"))
            if d is drains[0] and d.get("reason"):
                label += f" ({d['reason']})"
            if d.get("live_conns") is not None:
                label += f" ({d['live_conns']} live)"
            t = d.get("time")
            if d is not drains[0] and t0 is not None and t is not None:
                label += f" +{float(t) - float(t0):.1f}s"
            steps.append(label)
        lines.append("drain: " + " → ".join(steps))
    return "\n".join(lines)


def render_replaynet(records, snap: dict) -> str:
    """Replay service health (replaynet/server.py + client.py;
    docs/REPLAYNET.md): connections accepted vs shed, the request/
    error mix, ingest volume with the dup-hit tax, batches served,
    the actor-side spool depth and reconnects, and the replaynet
    drain timeline — 'did every game land exactly once and how hard
    did the clients have to work for it' in one block."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    conns = {k: v for k, v in counters.items()
             if k.startswith("replaynet_connections_total")}
    reqs = {k: v for k, v in counters.items()
            if k.startswith("replaynet_requests_total")}
    errors = {k: v for k, v in counters.items()
              if k.startswith("replaynet_errors_total")}
    ingest = counters.get("replaynet_ingest_games_total")
    drains = [r for r in records
              if r.get("event") == "drain"
              and str(r.get("phase", "")).startswith("replaynet_")]
    if not (conns or reqs or errors or ingest or drains):
        return "(no replaynet records)"
    lines = []
    if conns:
        def count(result):
            return conns.get(
                f'replaynet_connections_total{{result="{result}"}}',
                0)

        live = gauges.get("replaynet_conns_live")
        live_s = "" if live is None else f", {int(live)} live"
        lines.append(f"connections: {count('accepted')} accepted, "
                     f"{count('shed')} shed{live_s}")
    if reqs:
        lines.append("requests: " + "  ".join(
            f"{k.split('type=', 1)[-1].strip(chr(34) + '{}')}={v}"
            for k, v in sorted(reqs.items())))
    if errors:
        lines.append("errors: " + "  ".join(
            f"{k.split('code=', 1)[-1].strip(chr(34) + '{}')}={v}"
            for k, v in sorted(errors.items())))
    if ingest is not None:
        dups = counters.get("replaynet_dedup_hits_total", 0)
        batches = counters.get("replaynet_batches_out_total", 0)
        lines.append(f"ingest: {ingest} games ({dups} dup acks), "
                     f"{batches} batches out")
    shipped = counters.get("replaynet_shipped_games_total")
    if shipped is not None:
        spool = gauges.get("replaynet_spool_depth")
        recon = counters.get("replaynet_reconnects_total", 0)
        spool_s = "" if spool is None else f", spool depth {int(spool)}"
        lines.append(f"clients: {shipped} games shipped, "
                     f"{recon} reconnects{spool_s}")
    if drains:
        t0 = drains[0].get("time")
        steps = []
        for d in drains:
            label = str(d.get("phase", "?"))
            if d is drains[0] and d.get("reason"):
                label += f" ({d['reason']})"
            if d.get("live_conns") is not None:
                label += f" ({d['live_conns']} live)"
            t = d.get("time")
            if d is not drains[0] and t0 is not None and t is not None:
                label += f" +{float(t) - float(t0):.1f}s"
            steps.append(label)
        lines.append("drain: " + " → ".join(steps))
    return "\n".join(lines)


def _lb_trend(records) -> list:
    """The candidate's Wilson-lb trajectory across the run — from
    the ``canary`` record events (one point per decided game; the
    ``rollout_canary_lb`` gauge in a snapshot only keeps the last)."""
    return [r["wilson_lb"] for r in records
            if r.get("event") == "canary"
            and r.get("phase") == "record"
            and r.get("wilson_lb") is not None]


def render_rollout(records, snap: dict) -> str:
    """Live rollout health (rollout/; docs/ROLLOUT.md): hot-swap
    count + latency and the version the fleet serves, the canary's
    per-arm record with the Wilson-lb trajectory the gate decided
    on, the promotion/rollback timeline, and each replica's routing
    share — 'which net is live, how it got there, and who served
    the traffic' in one block."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    swaps = counters.get("rollout_swaps_total")
    swap_h = snap.get("histograms", {}).get("rollout_swap_seconds")
    routed = {k: v for k, v in counters.items()
              if k.startswith("router_routed_total")}
    canary_evs = [r for r in records if r.get("event") == "canary"]
    if not (swaps or routed or canary_evs):
        return "(no rollout records)"
    lines = []
    if swaps is not None:
        ver = gauges.get("rollout_params_version")
        ver_s = "" if ver is None else f", serving version {int(ver)}"
        lat = ""
        if swap_h and swap_h.get("count"):
            p99 = quantile_from_buckets(swap_h, 0.99)
            lat = f" (swap p99≲{p99}s)"
        lines.append(f"swaps: {int(swaps)} applied{ver_s}{lat}")
    if canary_evs:
        arm_games = {
            arm: counters.get(
                f'rollout_canary_games_total{{arm="{arm}"}}', 0)
            for arm in ("candidate", "incumbent")}
        assigned = {
            arm: counters.get(
                f'rollout_canary_assigned_total{{arm="{arm}"}}', 0)
            for arm in ("candidate", "incumbent")}
        lines.append(
            f"canary: assigned candidate={assigned['candidate']} "
            f"incumbent={assigned['incumbent']}, decided games "
            f"candidate={arm_games['candidate']} "
            f"incumbent={arm_games['incumbent']}")
        trend = _lb_trend(records)
        if trend:
            lb = gauges.get("rollout_canary_lb", trend[-1])
            lines.append(f"wilson lb: {trend[0]:.4f} → {lb:.4f} "
                         f"over {len(trend)} decided games")
        for r in canary_evs:
            ph = r.get("phase")
            if ph == "promote":
                lines.append(
                    f"promoted: version {r.get('candidate')} "
                    f"(lb={r.get('wilson_lb')})")
            elif ph == "rollback":
                lines.append(
                    f"rolled back: version {r.get('candidate')} "
                    f"({r.get('reason')}, lb={r.get('wilson_lb')})")
    if routed:
        total = sum(routed.values()) or 1
        parts = []
        for k, v in sorted(routed.items()):
            name = k.split("replica=", 1)[-1].strip(chr(34) + "{}")
            parts.append(f"{name}={v} ({100.0 * v / total:.0f}%)")
        extra = []
        for short, key in (("spillovers", "router_spillovers_total"),
                           ("failovers", "router_failovers_total"),
                           ("retried genmoves",
                            "router_retried_genmoves_total")):
            n = counters.get(key)
            if n:
                extra.append(f"{n} {short}")
        tail = f" — {', '.join(extra)}" if extra else ""
        lines.append("routing share: " + "  ".join(parts) + tail)
    return "\n".join(lines)


def _aux_trend(records) -> dict:
    """``head -> (first, last)`` aux-loss gauge values across the
    run's registry snapshots (gauges only keep the latest value, so
    the trend comes from walking every snapshot, not just the last)."""
    import re

    out: dict = {}
    for r in records:
        if r.get("event") != "registry" or "snapshot" not in r:
            continue
        for key, v in r["snapshot"].get("gauges", {}).items():
            if not key.startswith("aux_loss"):
                continue
            m = re.search(r'head="([^"]*)"', key)
            head = m.group(1) if m else key
            first, _ = out.get(head, (v, v))
            out[head] = (first, v)
    return out


def render_selfplay_econ(records, snap: dict) -> str:
    """Self-play economics (playout-cap randomization + policy-target
    pruning + aux heads; docs/PERFORMANCE.md): the cheap/full search
    split, realized sims/move against the all-full budget the cap
    avoided, how many recorded policy targets had forced playouts
    pruned out, and the aux-loss trend across registry snapshots —
    'is the cap paying for itself and are the aux heads learning'."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    h = snap.get("histograms", {}).get("selfplay_sims_per_move")
    frac = gauges.get("selfplay_fullsearch_frac")
    pruned = counters.get("policy_targets_pruned_total")
    aux = _aux_trend(records)
    if h is None and frac is None and pruned is None and not aux:
        return "(no self-play economics records)"
    lines = []
    if frac is not None:
        lines.append(f"searches: {100.0 * frac:.1f}% full / "
                     f"{100.0 * (1.0 - frac):.1f}% cheap")
    if h and h.get("count"):
        mean = h["sum"] / h["count"]
        full_est = quantile_from_buckets(h, 1.0)
        saved = ""
        # the full budget isn't in the snapshot; the occupied bucket
        # holding the max observed bounds it from above — good enough
        # for an "is the cap paying" estimate, hence the ≈/≲ hedges
        if full_est and full_est != float("inf") and full_est > mean:
            saved = (f", ≈{100.0 * (1.0 - mean / full_est):.0f}% "
                     f"sims saved vs all-full (≲{full_est:g})")
        lines.append(f"sims: mean {mean:.1f}/move over "
                     f"{h['count']} moves{saved}")
    if pruned is not None:
        lines.append(f"policy targets pruned: {pruned}")
    for head, (first, last) in sorted(aux.items()):
        trend = (f"{first:g} → {last:g}" if first != last
                 else f"{last:g}")
        lines.append(f"aux_loss[{head}]: {trend}")
    return "\n".join(lines)


def render_curriculum(records) -> str:
    """Curriculum ladder (training/curriculum.py; docs/MULTISIZE.md):
    one row per ``curriculum_stage`` event — board, iterations, wall
    time, the stage's final losses and self-play rate — then the
    ``curriculum_transfer`` verdict: did the small-board curriculum
    beat fresh init at the target size with Wilson confidence."""
    stages = [r for r in records
              if r.get("event") == "curriculum_stage"]
    transfers = [r for r in records
                 if r.get("event") == "curriculum_transfer"]
    if not stages and not transfers:
        return "(no curriculum records)"

    def num(r, key):
        v = r.get(key)
        return "—" if v is None else f"{float(v):.3f}"

    lines = [f"{'stage':<6} {'board':>5} {'iters':>6} {'wall_s':>9} "
             f"{'policy_loss':>12} {'value_loss':>11} {'games/min':>10}"]
    for r in stages:
        lines.append(
            f"{r.get('stage', '?'):<6} {r.get('board', '?'):>5} "
            f"{r.get('iterations', '?'):>6} {num(r, 'duration_s'):>9} "
            f"{num(r, 'policy_loss'):>12} {num(r, 'value_loss'):>11} "
            f"{num(r, 'games_per_min'):>10}")
    for t in transfers:
        verdict = ("TRANSFERS" if t.get("transfer")
                   else "not proven")
        lines.append(
            f"transfer @ {t.get('board', '?')}: {verdict} "
            f"(wilson_lb={t.get('wilson_lb')}, "
            f"{t.get('wins_a', '?')}–{t.get('wins_b', '?')} of "
            f"{t.get('games', '?')} games, "
            f"win_rate {t.get('win_rate_a', '?')})")
    return "\n".join(lines)


def render_events(records) -> str:
    """Counts of the notable non-span events (compiles, stalls,
    degradations, retries) — the 'did anything unusual happen' row."""
    counts: dict = {}
    for r in records:
        ev = r.get("event")
        if ev in ("compile", "stall", "degradation", "retry",
                  "resume", "profiler"):
            counts[ev] = counts.get(ev, 0) + 1
    if not counts:
        return "(none)"
    return "  ".join(f"{k}={v}" for k, v in sorted(counts.items()))


def report(records, top: int | None = None) -> str:
    stats = span_stats(records)
    if top:
        keep = sorted(stats, key=lambda p: -stats[p]["total_s"])[:top]
        stats = {p: stats[p] for p in stats if p in keep}
    reg = None
    for r in records:            # last snapshot wins (end-of-run)
        if r.get("event") == "registry" and "snapshot" in r:
            reg = r["snapshot"]
    parts = ["## per-phase time breakdown (span records)", "",
             render_spans(stats), "",
             "## notable events", "", render_events(records), "",
             "## dispatch pipeline (occupancy / host gaps)", "",
             render_dispatch(reg or {}), "",
             "## eval cache (hits / dedup / evictions / collisions)",
             "", render_evalcache(reg or {}), "",
             "## actor/learner (replay ingest / learner idle)", "",
             render_actor_learner(reg or {}), "",
             "## fleet health (restarts / parks / MTTR / drain)", "",
             render_fleet(records, reg or {}), "",
             "## gateway (connections / sheds / wire latency / drain)",
             "", render_gateway(records, reg or {}), "",
             "## replaynet (ingest / dup acks / spool / drain)",
             "", render_replaynet(records, reg or {}), "",
             "## rollout (swaps / canary verdict / routing share)",
             "", render_rollout(records, reg or {}), "",
             "## self-play economics (cap split / sims saved / aux)",
             "", render_selfplay_econ(records, reg or {}), "",
             "## curriculum (per-stage ladder / transfer verdict)", "",
             render_curriculum(records), "",
             "## encode path (per-position cost / compiles)", "",
             render_encode(stats, reg or {}), "",
             "## metric registry (last snapshot)", "",
             render_registry(reg or {})]
    return "\n".join(parts)


# ---------------------------------------------------------- selftest

FIXTURE = [
    {"event": "span", "name": "zero.selfplay", "ok": True,
     "path": "zero.iteration/zero.selfplay",
     "parent": "zero.iteration", "depth": 1, "dur_s": 8.0},
    {"event": "span", "name": "zero.replay", "ok": True,
     "path": "zero.iteration/zero.replay",
     "parent": "zero.iteration", "depth": 1, "dur_s": 1.5},
    {"event": "span", "name": "zero.update", "ok": True,
     "path": "zero.iteration/zero.update",
     "parent": "zero.iteration", "depth": 1, "dur_s": 0.5},
    {"event": "span", "name": "zero.iteration", "ok": True,
     "path": "zero.iteration", "parent": None, "depth": 0,
     "dur_s": 10.5, "iteration": 0},
    {"event": "compile", "entry": "device_mcts.run_sims",
     "dur_s": 3.2, "calls": 1, "recompile": False},
    {"event": "span", "name": "curriculum.stage", "ok": True,
     "path": "curriculum.stage", "parent": None, "depth": 0,
     "dur_s": 12.0, "stage": 0, "board": 9, "iterations": 2},
    {"event": "curriculum_stage", "stage": 0, "board": 9,
     "iterations": 2, "duration_s": 12.0, "policy_loss": 2.71,
     "value_loss": 0.98, "games_per_min": 40.0},
    {"event": "curriculum_stage", "stage": 1, "board": 13,
     "iterations": 1, "duration_s": 30.5, "policy_loss": 2.43,
     "value_loss": 0.91, "games_per_min": 11.0},
    {"event": "curriculum_transfer", "board": 13, "games": 32,
     "transfer": True, "wilson_lb": 0.6241, "wins_a": 26,
     "wins_b": 6, "draws": 0, "win_rate_a": 0.8125},
    # fleet supervision lifecycle (runtime/supervisor.py): a
    # transient actor death that recovers, a dispatcher restart, a
    # crash-looping actor that parks, one learner failover, and a
    # SIGTERM drain landing at an iteration boundary
    {"event": "worker_restart", "worker": "actor:1",
     "reason": "transient", "restarts": 1, "delay_s": 0.25,
     "error": "InjectedFault: actor.game", "time": 100.0},
    {"event": "worker_recovered", "worker": "actor:1", "restarts": 1,
     "mttr_s": 2.4, "time": 102.4},
    {"event": "worker_restart", "worker": "serve:dispatcher",
     "reason": "error", "restarts": 1, "delay_s": 0.5,
     "error": "InjectedKill: serve.dispatch", "time": 103.0},
    {"event": "worker_recovered", "worker": "serve:dispatcher",
     "restarts": 1, "mttr_s": 0.8, "time": 103.8},
    {"event": "worker_parked", "worker": "actor:2",
     "reason": "crash_loop", "deaths": 3,
     "error": "InjectedKill: actor.game", "time": 104.0},
    {"event": "learner_failover", "restored_step": 5, "target": 6,
     "error": "InjectedKill: learner.step", "time": 105.0},
    {"event": "drain", "phase": "requested", "reason": "sigterm",
     "time": 110.0},
    {"event": "drain", "phase": "loop_exit", "iteration": 2,
     "reason": "sigterm", "time": 110.1},
    {"event": "drain", "phase": "checkpoint", "step": 2,
     "reason": "sigterm", "time": 110.9},
    # the gateway's own drain timeline (gateway/server.py): stop
    # accepting, finish in-flight moves, close every session
    {"event": "drain", "phase": "gateway_requested",
     "reason": "sigterm", "time": 111.0},
    {"event": "drain", "phase": "gateway_accept_stopped",
     "time": 111.1},
    {"event": "drain", "phase": "gateway_drained", "live_conns": 0,
     "time": 111.6},
    # the replay service's drain timeline (replaynet/server.py):
    # same three-step shared core, replaynet_ prefix
    {"event": "drain", "phase": "replaynet_requested",
     "reason": "sigterm", "time": 112.0},
    {"event": "drain", "phase": "replaynet_accept_stopped",
     "time": 112.1},
    {"event": "drain", "phase": "replaynet_drained", "live_conns": 0,
     "time": 112.4},
    # a canary run (rollout/canary.py): staged, three decided games,
    # then the Wilson gate rolls the weak candidate back
    {"event": "canary", "phase": "stage", "candidate": 8,
     "incumbent": 7, "fraction": 0.25, "min_games": 3, "time": 120.0},
    {"event": "canary", "phase": "record", "arm": "candidate",
     "won": True, "wilson_lb": 0.2065, "decided": 1, "time": 121.0},
    {"event": "canary", "phase": "record", "arm": "candidate",
     "won": False, "wilson_lb": 0.0949, "decided": 2, "time": 122.0},
    {"event": "canary", "phase": "record", "arm": "candidate",
     "won": False, "wilson_lb": 0.0617, "decided": 3, "time": 123.0},
    {"event": "canary", "phase": "rollback", "candidate": 8,
     "reason": "wilson_lb", "wilson_lb": 0.0617, "time": 123.1},
    # an EARLY snapshot (iteration 0): only its aux_loss gauges matter
    # — the econ section walks every snapshot to render the trend;
    # every other section reads the last snapshot only
    {"event": "registry", "snapshot": {
        "gauges": {'aux_loss{head="ownership"}': 0.92,
                   'aux_loss{head="score"}': 61.0}}},
    {"event": "registry", "snapshot": {
        "counters": {'serve_rung_total{rung="search"}': 41,
                     'serve_rung_total{rung="policy"}': 1,
                     'dispatch_chunks_total{runner="device_mcts"}': 96,
                     'jax_compiles_total{entry="encode.batch"}': 1,
                     'encode_positions_total{board="19"}': 128,
                     "encode_delta_total": 96,
                     "encode_full_total": 32,
                     "encode_incr_verdicts_reused_total": 57,
                     "encode_incr_chases_run_total": 19,
                     "encode_incr_foot_hits_total": 31,
                     "encode_incr_entries_invalidated_total": 12,
                     "encode_incr_verdict_flips_total": 3,
                     "encode_incr_entries_revived_total": 5,
                     'encode_encoders_total{planes="ladder"}': 2,
                     'encode_encoders_total{planes="noladder"}': 1,
                     'encode_cache_resets_total{reason="new_game"}': 2,
                     "eval_cache_hits_total": 592,
                     "eval_cache_misses_total": 320,
                     'eval_cache_evictions_total{reason="capacity"}':
                         12,
                     'eval_cache_evictions_total{reason="version"}': 9,
                     "eval_cache_collisions_total": 0,
                     "serve_unique_rows_total": 71,
                     "serve_dedup_rows_saved_total": 249,
                     "replay_ingest_games_total": 64,
                     "replay_evicted_games_total": 8,
                     "learner_steps_total": 7,
                     'actor_games_total{actor="a0"}': 16,
                     'actor_games_total{actor="a1"}': 16,
                     "policy_targets_pruned_total": 37,
                     'gateway_connections_total{result="accepted"}': 9,
                     'gateway_connections_total{result="shed"}': 3,
                     'gateway_requests_total{type="new_game"}': 9,
                     'gateway_requests_total{type="genmove"}': 40,
                     'gateway_errors_total{code="overload"}': 3,
                     'replaynet_connections_total{result="accepted"}':
                         11,
                     'replaynet_connections_total{result="shed"}': 1,
                     'replaynet_requests_total{type="put_games"}': 30,
                     'replaynet_requests_total{type="next_batch"}': 28,
                     'replaynet_errors_total{code="overload"}': 2,
                     "replaynet_ingest_games_total": 56,
                     "replaynet_dedup_hits_total": 4,
                     "replaynet_batches_out_total": 26,
                     "replaynet_shipped_games_total": 56,
                     "replaynet_reconnects_total": 5,
                     "rollout_swaps_total": 2,
                     'rollout_canary_assigned_total{arm="candidate"}':
                         1,
                     'rollout_canary_assigned_total{arm="incumbent"}':
                         3,
                     'rollout_canary_games_total{arm="candidate"}': 3,
                     'rollout_canary_games_total{arm="incumbent"}': 2,
                     "rollout_canary_rollbacks_total": 1,
                     'router_routed_total{replica="r0"}': 6,
                     'router_routed_total{replica="r1"}': 3,
                     'router_connections_total{result="accepted"}': 9,
                     "router_spillovers_total": 1,
                     "router_failovers_total": 1,
                     "router_retried_genmoves_total": 1},
        "gauges": {"device_mcts_deadline_margin_s": 0.42,
                   "eval_cache_entries": 71,
                   'device_occupancy{runner="device_mcts"}': 0.983,
                   "replay_fill_games": 6,
                   "replay_ingest_per_min": 480.0,
                   "learner_idle_frac": 0.12,
                   "actor_params_version": 7,
                   "selfplay_fullsearch_frac": 0.25,
                   'aux_loss{head="ownership"}': 0.41,
                   'aux_loss{head="score"}': 18.5,
                   "gateway_conns_live": 0,
                   "replaynet_conns_live": 0,
                   "replaynet_spool_depth": 3,
                   "rollout_params_version": 7,
                   "rollout_canary_lb": 0.0617},
        "histograms": {"gtp_genmove_seconds": {
            "count": 42, "sum": 33.6,
            "buckets": {"0.5": 17, "1": 40, "2.5": 42,
                        "+Inf": 42}},
            'dispatch_gap_s{runner="device_mcts"}': {
                "count": 3, "sum": 0.021,
                "buckets": {"0.005": 1, "0.01": 3, "+Inf": 3}},
            'encode_pos_us{board="19"}': {
                "count": 128, "sum": 940800.0,
                "buckets": {"5000": 60, "10000": 126, "25000": 128,
                            "+Inf": 128}},
            "replay_sample_staleness_seconds": {
                "count": 7, "sum": 3.1,
                "buckets": {"0.5": 4, "1": 6, "2.5": 7, "+Inf": 7}},
            "learner_wait_seconds": {
                "count": 7, "sum": 0.9,
                "buckets": {"0.25": 5, "0.5": 7, "+Inf": 7}},
            "selfplay_sims_per_move": {
                "count": 64, "sum": 896.0,
                "buckets": {"10": 48, "50": 64, "+Inf": 64}},
            "gateway_wire_seconds": {
                "count": 40, "sum": 3.0,
                "buckets": {"0.05": 10, "0.1": 38, "0.25": 40,
                            "+Inf": 40}},
            "rollout_swap_seconds": {
                "count": 2, "sum": 0.012,
                "buckets": {"0.01": 1, "0.025": 2, "+Inf": 2}}}}},
]


def selftest() -> int:
    out = report(FIXTURE)
    print(out)
    needed = ("zero.selfplay", "zero.iteration", "76.2%",
              "serve_rung_total", "gtp_genmove_seconds", "compile=1",
              "p99≲2.5", "dispatch pipeline", "98.3%",
              "eval cache (hits / dedup / evictions / collisions)",
              "lookups: 592 hits / 912 (64.9% hit rate), "
              "71 entries resident",
              "device rows: 71 unique evaluated, "
              "249 in-batch dupes folded",
              "evictions: capacity=12 version=9, "
              "collisions detected: 0",
              "encode path", "≲25000",
              'jax_compiles_total{entry="encode.batch"}=1',
              "incremental encode: 96 delta / 32 full (75% delta)",
              "reused 57/76 (75% hit)", "new_game=2",
              "invalidation cascade: 0.12 invalidations/ply "
              "(31 footprint hits → 12 cell-verified stale, "
              "3 verdict flips re-chased, 5 revived)",
              "encoders: ladder=2 noladder=1 — MIXED plane families",
              "actor/learner",
              "ingest: 64 games @ 480.0/min, buffer fill 6, "
              "8 evicted",
              "learner: 7 steps, idle 12.0%",
              "staleness: p50≲0.5 p99≲2.5 (7 consumed)",
              "a0=16", "a1=16",
              "fleet health (restarts / parks / MTTR / drain)",
              "restarts: actor:1=1 (transient=1)  "
              "serve:dispatcher=1 (error=1)",
              "parked: actor:2 (crash_loop after 3 deaths)",
              "learner failovers: 1 (last restored step 5, target 6)",
              "recovery: mean 1.600s, max 2.400s over 2 restarts",
              "drain: requested (sigterm) → loop_exit @ iter 2 "
              "+0.1s → checkpoint @ step 2 +0.9s",
              "gateway (connections / sheds / wire latency / drain)",
              "connections: 9 accepted, 3 shed, 0 live",
              "requests: genmove=40  new_game=9",
              "errors: overload=3",
              "wire: 40 genmoves, p50≲0.1s p99≲0.25s",
              "drain: gateway_requested (sigterm) → "
              "gateway_accept_stopped +0.1s → "
              "gateway_drained (0 live) +0.6s",
              "replaynet (ingest / dup acks / spool / drain)",
              "connections: 11 accepted, 1 shed, 0 live",
              "requests: next_batch=28  put_games=30",
              "errors: overload=2",
              "ingest: 56 games (4 dup acks), 26 batches out",
              "clients: 56 games shipped, 5 reconnects, "
              "spool depth 3",
              "drain: replaynet_requested (sigterm) → "
              "replaynet_accept_stopped +0.1s → "
              "replaynet_drained (0 live) +0.4s",
              "rollout (swaps / canary verdict / routing share)",
              "swaps: 2 applied, serving version 7 (swap p99≲0.025s)",
              "canary: assigned candidate=1 incumbent=3, "
              "decided games candidate=3 incumbent=2",
              "wilson lb: 0.2065 → 0.0617 over 3 decided games",
              "rolled back: version 8 (wilson_lb, lb=0.0617)",
              "routing share: r0=6 (67%)  r1=3 (33%) — "
              "1 spillovers, 1 failovers, 1 retried genmoves",
              "self-play economics (cap split / sims saved / aux)",
              "searches: 25.0% full / 75.0% cheap",
              "sims: mean 14.0/move over 64 moves, "
              "≈72% sims saved vs all-full (≲50)",
              "policy targets pruned: 37",
              "aux_loss[ownership]: 0.92 → 0.41",
              "aux_loss[score]: 61 → 18.5",
              "curriculum (per-stage ladder / transfer verdict)",
              "transfer @ 13: TRANSFERS (wilson_lb=0.6241, "
              "26–6 of 32 games, win_rate 0.8125)")
    missing = [n for n in needed if n not in out]
    if missing:
        print(f"obs_report selftest FAILED: missing {missing}",
              file=sys.stderr)
        return 1
    print("\nobs_report selftest OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-phase time breakdown + histogram summary "
                    "from a run's metrics.jsonl")
    ap.add_argument("run", nargs="?",
                    help="run directory (containing metrics.jsonl) "
                         "or a metrics.jsonl path")
    ap.add_argument("--top", type=int, default=None,
                    help="keep only the N paths with the largest "
                         "total time")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: dump the aggregated span "
                         "stats + last registry snapshot as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="render the built-in fixture and verify the "
                         "output (CI guard for the report path)")
    a = ap.parse_args(argv)
    if a.selftest:
        return selftest()
    if not a.run:
        ap.error("RUN_DIR (or --selftest) required")
    path = a.run
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    if not os.path.exists(path):
        print(f"obs_report: no metrics.jsonl at {path}",
              file=sys.stderr)
        return 2
    records = read_jsonl(path)
    if a.json:
        stats = {p: {k: v for k, v in s.items() if k != "durs"}
                 for p, s in span_stats(records).items()}
        reg = None
        for r in records:
            if r.get("event") == "registry" and "snapshot" in r:
                reg = r["snapshot"]
        print(json.dumps({"spans": stats, "registry": reg},
                         sort_keys=True, indent=2))
        return 0
    print(f"# obs report — {path}\n")
    print(report(records, top=a.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
