#!/bin/bash
# Round-robin + Elo demo: three agent kinds over the same tiny 9x9
# nets (greedy / probabilistic / device-mcts), tournament logs fed to
# the Bradley-Terry Elo fitter. The point is the evaluation PIPELINE
# (tournament --log -> interface.elo) on real games; with random-init
# nets the ordering itself is weak evidence.
#
# Usage: bash scripts/elo_demo.sh [outdir] [games-per-pair]
set -eu
cd "$(dirname "$0")/.."
OUT=${1:-results/elo_demo}
GAMES=${2:-6}
SPECS=benchmarks/tpu_extra_r3
mkdir -p "$OUT"

run_pair() {
    a=$1; b=$2; tag=$3
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m \
        rocalphago_tpu.interface.tournament "$a" "$b" \
        --games "$GAMES" --board 9 --move-limit 120 --playouts 16 \
        --log "$OUT/$tag.jsonl" 2>>"$OUT/games.log" \
        | tee -a "$OUT/games.log"
}

# names in the logs come from the tournament's A/B labels — rewrite
# with jq-free sed to the agent kinds so the Elo table reads naturally
name_fix() {
    sed -i "s/\"A\"/\"$1\"/g; s/\"B\"/\"$2\"/g" "$OUT/$3.jsonl"
}

run_pair "device-mcts:$SPECS/p9.json:$SPECS/v9.json" \
         "greedy:$SPECS/p9.json" mcts_vs_greedy
name_fix mcts greedy mcts_vs_greedy
run_pair "device-mcts:$SPECS/p9.json:$SPECS/v9.json" \
         "probabilistic:$SPECS/p9.json" mcts_vs_prob
name_fix mcts prob mcts_vs_prob
run_pair "probabilistic:$SPECS/p9.json" \
         "greedy:$SPECS/p9.json" prob_vs_greedy
name_fix prob greedy prob_vs_greedy

PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m \
    rocalphago_tpu.interface.elo "$OUT"/*.jsonl --anchor greedy \
    --anchor-elo 1000 | tee "$OUT/elo.json"
