"""Bisect the TPU worker crash on the composed self-play program.

Round-2 standing defect (VERDICT r3 weak #2): every COMPONENT bench
runs on the chip, but the composed self-play program kills the
worker. This script builds the ply program up in stages and runs each
as its own chunk-driven scan, so one invocation in a healthy tunnel
window names the faulting composition:

  engine   — rules step only, uniform-random sensible action
  encode   — + 48-plane feature encode (consumed into the carry)
  forward  — + policy conv forward + softmax sampling from its logits
  full     — the real ``make_selfplay_chunked`` program (color-split
             two-net forwards, live/freeze bookkeeping, action log)

Kill-safety (memory: a client SIGKILLed mid-device-program wedges the
tunnel for hours): every stage runs ≤``--chunk``-ply compiled
segments from a host loop and checks its deadline BETWEEN segments,
so the process never needs to be killed while a program is in
flight. Each stage appends one JSON line to ``--log`` immediately
(a worker crash mid-stage still leaves the earlier verdicts on disk).

Usage (from a healthy window; ~2-4 min with warm compile cache):
    python scripts/tpu_crash_bisect.py --log benchmarks/bisect.jsonl
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--board", type=int, default=19)
    ap.add_argument("--plies", type=int, default=60)
    ap.add_argument("--chunk", type=int, default=10)
    ap.add_argument("--budget-s", type=float, default=420.0)
    ap.add_argument("--log", default="benchmarks/bisect.jsonl")
    ap.add_argument("--stages", default="engine,encode,forward,full")
    args = ap.parse_args()
    deadline = time.time() + args.budget_s

    import jax
    import jax.numpy as jnp
    from jax import lax

    from benchmarks._harness import enable_compile_cache

    enable_compile_cache()

    from rocalphago_tpu.engine import jaxgo
    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.features import DEFAULT_FEATURES
    from rocalphago_tpu.features.planes import batched_encoder
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.search.selfplay import (
        make_selfplay_chunked,
        sensible_mask,
    )

    cfg = GoConfig(size=args.board)
    platform = jax.devices()[0].platform
    net = CNNPolicy(board=args.board, layers=12,
                    filters_per_layer=128)

    def emit(rec):
        rec.update(platform=platform, batch=args.batch,
                   board=args.board, chunk=args.chunk,
                   date=time.strftime("%Y-%m-%dT%H:%M:%S"))
        with open(args.log, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)

    def tunnel_healthy() -> bool:
        """Post-failure reprobe: was that a worker CRASH (tunnel still
        answers) or an OUTAGE (window closed — the failure says
        nothing about the composition)? Same kill-safe probe the
        hunter gates on. Off-TPU there is no tunnel to lose."""
        if platform != "tpu":
            return True
        import subprocess
        probe = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tpu_probe.py")
        try:
            rc = subprocess.run(
                [sys.executable, probe], capture_output=True,
                timeout=90).returncode
        except subprocess.TimeoutExpired:
            return False
        return rc in (0, 3)

    # a stage yields a usable VERDICT when it either ran its full
    # plies clean (exoneration needs sustained execution — the known
    # crash mode appears only past ~30-40s) or failed while the
    # tunnel still answered (a genuine crash, not an outage)
    verdicts = 0
    n_stages = 0
    # one ply at increasing composition depth; every variant consumes
    # what it computes (the carry) so XLA cannot dead-code it away
    vgd = jax.vmap(lambda s: jaxgo.group_data(
        cfg, s.board, with_member=True,
        with_zxor=cfg.enforce_superko, labels=s.labels))
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(jaxgo.step, cfg))
    venc = batched_encoder(cfg, DEFAULT_FEATURES)

    def ply_fn(stage):
        n = cfg.num_points

        def ply(carry, _):
            states, acc, rng = carry
            rng, sub = jax.random.split(rng)
            gd = vgd(states)
            sens = vsens(states, gd)
            logits = jnp.zeros((args.batch, n), jnp.float32)
            if stage in ("encode", "forward"):
                planes = venc(states, gd)
                acc = acc + planes.sum()
            if stage == "forward":
                logits = net.module.apply(
                    net.params, planes).astype(jnp.float32)
            neg = jnp.finfo(jnp.float32).min
            masked = jnp.where(sens, logits, neg)
            action = jnp.where(
                sens.any(axis=-1),
                jax.random.categorical(sub, masked, axis=-1),
                jnp.int32(n))                      # forced pass
            return (vstep(states, action.astype(jnp.int32), gd),
                    acc, rng), None

        @jax.jit
        def segment(states, acc, rng):
            (states, acc, rng), _ = lax.scan(
                ply, (states, acc, rng), None, length=args.chunk)
            return states, acc, rng

        return segment

    for stage in args.stages.split(","):
        n_stages += 1
        if time.time() > deadline:
            emit({"stage": stage, "ok": False, "outage": True,
                  "error": "bisect budget exhausted before stage"})
            continue
        t0 = time.time()
        try:
            if stage == "full":
                run = make_selfplay_chunked(
                    cfg, DEFAULT_FEATURES, net.module.apply,
                    net.module.apply, args.batch, args.plies,
                    chunk=args.chunk, score_on_device=False)
                res = run(net.params, net.params, jax.random.key(0),
                          deadline=min(deadline, time.time() + 240))
                jax.device_get(res.final.board)
                plies = res.actions.shape[0]
            else:
                seg = ply_fn(stage)
                states = jaxgo.new_states(cfg, args.batch)
                acc, rng = jnp.float32(0), jax.random.key(0)
                plies = 0
                while plies < args.plies:
                    if plies and time.time() > deadline:
                        break          # between segments: clean stop
                    states, acc, rng = seg(states, acc, rng)
                    jax.device_get(acc)    # force real completion
                    plies += args.chunk
            dt = time.time() - t0
            full = plies >= args.plies
            if full:
                verdicts += 1
            emit({"stage": stage, "ok": full, "plies": plies,
                  "secs": round(dt, 1),
                  **({} if full else {"truncated": True}),
                  "board_plies_per_s": round(
                      plies * args.batch / max(dt, 1e-6), 1)})
        except Exception as e:  # noqa: BLE001 — the verdict IS the point
            healthy = tunnel_healthy()
            if healthy:
                verdicts += 1        # a GENUINE crash verdict
            emit({"stage": stage, "ok": False, "outage": not healthy,
                  "secs": round(time.time() - t0, 1),
                  "error": f"{type(e).__name__}: {e}"[:500]})
            # a worker crash takes ~15s to self-recover; give it that
            # before the next stage so one crash doesn't cascade
            time.sleep(20)
    # rc 0 ONLY when every requested stage produced a usable verdict
    # (clean full run, or a crash with the tunnel still answering) —
    # anything less and the hunter must retry in a later window
    # rather than bank a partial/outage-polluted bisect as done
    return 0 if verdicts == n_stages else 1


if __name__ == "__main__":
    sys.exit(main())
