"""End-of-run strength ladder over a gated zero run's promotion pool.

The in-run ladder probes (``metrics.jsonl`` ``event: ladder``) sample
ONE past snapshot per gate; this script plays the full table instead:
the LAST promoted pair against every earlier pool snapshot,
raw-policy stochastic sampling (the round-4 failure mode's exact
measurement — ``results/zero_scale_r4/strength_*.jsonl`` showed
iteration-260 losing 25–75 to iteration-80 raw when trained WITHOUT a
gate; VERDICT r4 #2 asks the gated rerun to show this monotone).

Each pool snapshot ``best.NNNNN.policy.msgpack`` gets a sibling spec
JSON (same architecture as --spec) so ``interface.tournament`` can
load it, then the matches run through the tournament CLI's machinery
in-process. Every row now carries the incumbent's Wilson 95% lower
bound over decided games, so "ahead" claims are statistically honest.

CROSS-SIZE transfer ladder: with FCN checkpoints (size-generic
params) ``--board`` may differ from the size the pool was trained at
— the tournament re-boards the nets via ``at_board``. ``--vs-fresh
SEED`` additionally plays the FINAL snapshot against a freshly-
initialized net of the same architecture at ``--board``: the
transferred-vs-fresh measurement the multi-size curriculum is gated
on (``transfer`` is claimed only when the Wilson lower bound clears
0.5; docs/MULTISIZE.md records results).

Usage::

    python scripts/zero_ladder_matches.py results/zero_r5/run \
        --spec results/zero_r5/zp9.json --games 64 \
        --out results/zero_r5/ladder_final.json

    # 9x9-trained pool measured at 13x13 against fresh init
    python scripts/zero_ladder_matches.py results/zero_r5/run \
        --spec results/zero_r5/zp9.json --board 13 --vs-fresh 7
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def pool_snapshots(run_dir: str) -> list[tuple[int, str]]:
    """Numerically-sorted ``(iteration, weights_path)`` pairs."""
    pool = os.path.join(run_dir, "pool")
    try:
        names = os.listdir(pool)
    except FileNotFoundError:
        raise SystemExit(
            f"{pool} does not exist — pass a gated training.zero "
            "out_dir (its pool/ holds the promoted best.NNNNN.* "
            "snapshots this ladder replays)")
    out = []
    for name in names:
        m = re.fullmatch(r"best\.(\d+)\.policy\.msgpack", name)
        if m:
            out.append((int(m.group(1)), os.path.join(pool, name)))
    # numeric sort on the captured iteration: zero-padding keeps
    # lexicographic order only until an iteration outgrows the pad
    # width, and nothing enforces that width here
    out.sort(key=lambda pair: pair[0])
    return out


def write_spec(spec_path: str, weights: str, out_dir: str) -> str:
    """Spec JSON in ``out_dir`` pointing at one pool snapshot's
    weights (absolute path — the spec does NOT live beside them).
    Generated specs go to a temp dir, never into the run's pool/:
    writing there silently clobbered git-tracked pool spec artifacts
    with whatever --spec the caller supplied (ADVICE round 5)."""
    with open(spec_path) as f:
        spec = json.load(f)
    spec["weights_file"] = os.path.abspath(weights)
    out = os.path.join(
        out_dir, os.path.basename(weights).replace(
            ".policy.msgpack", ".policy.json"))
    with open(out, "w") as f:
        json.dump(spec, f)
    return out


def fresh_spec(spec_path: str, board: int, seed: int,
               out_dir: str) -> str:
    """Spec + weights for a FRESHLY-initialized net of ``--spec``'s
    architecture at ``board`` — the transfer baseline. Saved into the
    temp spec dir like the snapshot specs."""
    from rocalphago_tpu.models.nn_util import NeuralNetBase

    net = NeuralNetBase.load_model(spec_path)
    fresh = type(net)(net.feature_list, board=board, seed=seed,
                      **net.spec_kwargs)
    out = os.path.join(out_dir, "fresh.json")
    fresh.save_model(out, os.path.join(out_dir, "fresh.flax.msgpack"))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir")
    ap.add_argument("--spec", required=True,
                    help="policy spec JSON matching the pool's arch")
    ap.add_argument("--games", type=int, default=64)
    ap.add_argument("--board", type=int, default=9,
                    help="match board size; may differ from the "
                         "pool's training size for FCN checkpoints "
                         "(re-boarded via at_board)")
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--move-limit", type=int, default=240)
    ap.add_argument("--vs-fresh", type=int, default=None,
                    metavar="SEED",
                    help="also play the final snapshot against a "
                         "fresh-init net (this seed) at --board — "
                         "the Wilson-gated transferred-vs-fresh "
                         "measurement")
    ap.add_argument("--out", default=None)
    a = ap.parse_args(argv)

    snaps = pool_snapshots(a.run_dir)
    need = 1 if a.vs_fresh is not None else 2
    if len(snaps) < need:
        raise SystemExit(
            f"need >={need} pool snapshots, found {len(snaps)}")
    spec_dir = tempfile.mkdtemp(prefix="zero_ladder_specs.")
    specs = {it: write_spec(a.spec, w, spec_dir) for it, w in snaps}
    last_it = snaps[-1][0]

    from rocalphago_tpu.interface import tournament
    from rocalphago_tpu.interface.elo import wilson_lower_bound

    def lb_of(r):
        decided = r["wins"]["A"] + r["wins"]["B"]
        return round(wilson_lower_bound(r["wins"]["A"], decided), 4)

    rows = []
    for it, _ in snaps[:-1]:
        r = tournament.main([
            f"probabilistic:{specs[last_it]}",
            f"probabilistic:{specs[it]}",
            "--games", str(a.games), "--board", str(a.board),
            "--temperature", str(a.temperature),
            "--move-limit", str(a.move_limit)])
        rows.append({"incumbent": last_it, "opponent": it,
                     "incumbent_win_rate": r["win_rate_a"],
                     "wilson_lb": lb_of(r),
                     "wins": r["wins"]})
        print(json.dumps(rows[-1]), flush=True)

    result = {
        "run_dir": a.run_dir, "games_per_match": a.games,
        "board": a.board,
        "final_snapshot": last_it,
        "matches": rows,
        "monotone": all(r["incumbent_win_rate"] >= 0.5 for r in rows),
    }
    if a.vs_fresh is not None:
        fresh = fresh_spec(a.spec, a.board, a.vs_fresh, spec_dir)
        r = tournament.main([
            f"probabilistic:{specs[last_it]}",
            f"probabilistic:{fresh}",
            "--games", str(a.games), "--board", str(a.board),
            "--temperature", str(a.temperature),
            "--move-limit", str(a.move_limit)])
        lb = lb_of(r)
        result["vs_fresh"] = {
            "snapshot": last_it, "board": a.board,
            "seed": a.vs_fresh,
            "transferred_win_rate": r["win_rate_a"],
            "wilson_lb": lb,
            # the gate the curriculum claims transfer on: the
            # transferred net must beat fresh init with confidence
            "transfer": lb >= 0.5,
            "wins": r["wins"]}
        print(json.dumps(result["vs_fresh"]), flush=True)
    if a.out:
        with open(a.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
