"""Paired zero-trainer runs: PUCT visit targets vs Gumbel π′ targets.

Round-3 finding (results/zero_demo/zero_target_comparison.json): from
RANDOM nets, π′ = softmax(logits + σ(q̂)) is noise — σ ranks by the
VALUE net, and an untrained value net makes the target unlearnable
while PUCT's visit counts (prior-dominated) still teach. The round-3
conclusion predicted π′ becomes informative exactly when the value
net does. This script is the ABOVE-THE-NOISE-FLOOR rerun (VERDICT r3
#7): warm-start BOTH runs from the same trained policy/value pair
(e.g. the round-4 zero run's exports, value_acc ≈ 0.7+) and compare
policy-CE trajectories under identical configs/seeds.

Usage:
    python scripts/zero_target_compare.py POLICY.json VALUE.json \
        OUT_DIR [--iterations 10] [--game-batch 16] [--sims 16] \
        [--move-limit 80] [--seed 11]

Writes OUT_DIR/{puct,gumbel}/ (full trainer artifacts) and
OUT_DIR/comparison.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_one(mode: str, a, out_dir: str) -> list[dict]:
    # the trainer's metrics logger APPENDS: rerunning into a used
    # out_dir would silently mix stale rows from a differently
    # configured run into the comparison
    stale = os.path.join(out_dir, "metrics.jsonl")
    if os.path.exists(stale):
        raise SystemExit(
            f"{stale} already exists — pick a fresh OUT_DIR (the "
            "trainer appends, and mixed runs would corrupt the "
            "comparison)")
    args = [sys.executable, "-m", "rocalphago_tpu.training.zero",
            a.policy_json, a.value_json, out_dir,
            "--iterations", str(a.iterations),
            "--game-batch", str(a.game_batch),
            "--sims", str(a.sims),
            "--move-limit", str(a.move_limit),
            "--seed", str(a.seed),
            "--save-every", str(max(a.iterations, 1))]
    if mode == "gumbel":
        args += ["--gumbel", "--m-root", str(a.m_root)]
    elif mode == "gumbel_sample":
        # VERDICT r4 #9: pi' targets with the play distribution
        # decoupled from the halving winner (moves sampled from pi')
        args += ["--gumbel", "--m-root", str(a.m_root),
                 "--gumbel-sample-moves"]
    else:
        args += ["--dirichlet-alpha", str(a.dirichlet_alpha)]
    t0 = time.time()
    # bound the wait (ADVICE r4): a wedged trainer (device hang) must
    # not block the paired comparison forever. Budget generously from
    # the requested work — 90s per iteration covers the slowest
    # observed CPU iteration several times over — plus compile slack.
    # ZERO_COMPARE_TIMEOUT_SCALE stretches the budget when the host
    # is deliberately oversubscribed (round-5 measured a 5-way-nice'd
    # box blowing the uncontended budget ~2x, not a wedge).
    scale = float(os.environ.get("ZERO_COMPARE_TIMEOUT_SCALE", "1"))
    timeout_s = (600 + 90 * a.iterations) * scale
    try:
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        raise SystemExit(
            f"{mode} run exceeded {timeout_s}s — trainer wedged? "
            f"Partial metrics (if any) are in {out_dir}")
    if proc.returncode != 0:
        raise SystemExit(
            f"{mode} run failed rc={proc.returncode}:\n"
            + proc.stderr[-2000:])
    print(f"{mode}: {a.iterations} iterations in "
          f"{time.time() - t0:.0f}s", flush=True)
    rows = []
    with open(os.path.join(out_dir, "metrics.jsonl")) as f:
        for line in f:
            r = json.loads(line)
            if r.get("event") == "iteration":
                rows.append({k: round(float(r[k]), 4) for k in (
                    "policy_loss", "value_loss", "value_acc",
                    "value_mse") if k in r})
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("policy_json")
    ap.add_argument("value_json")
    ap.add_argument("out_dir")
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--game-batch", type=int, default=16)
    ap.add_argument("--sims", type=int, default=16)
    ap.add_argument("--move-limit", type=int, default=80)
    ap.add_argument("--m-root", type=int, default=8)
    ap.add_argument("--dirichlet-alpha", type=float, default=0.15)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--modes", nargs="+",
                    default=["puct", "gumbel"],
                    choices=["puct", "gumbel", "gumbel_sample"],
                    help="trainer modes to pair (gumbel_sample = "
                         "pi' targets + moves sampled from pi'; "
                         "VERDICT r4 #9)")
    a = ap.parse_args(argv)

    os.makedirs(a.out_dir, exist_ok=True)
    results = {}
    for mode in a.modes:
        try:
            results[mode] = run_one(mode, a,
                                    os.path.join(a.out_dir, mode))
        except SystemExit:
            # emit whatever the OTHER mode already banked before
            # dying — a half comparison beats none (ADVICE r4)
            if results:
                partial = os.path.join(a.out_dir, "partial.json")
                with open(partial, "w") as f:
                    json.dump(results, f, indent=2)
                print(f"wrote {partial} (completed modes only)",
                      file=sys.stderr)
            raise

    def ce_first_last(rows):
        ce = [r["policy_loss"] for r in rows]
        if not ce:
            raise SystemExit(
                "a trainer run exited clean but logged no iteration "
                "rows — nothing to compare (check --iterations)")
        return {"first": ce[0], "last": ce[-1],
                "delta": round(ce[-1] - ce[0], 4)}

    comparison = {
        "config": {k: getattr(a, k) for k in (
            "policy_json", "value_json", "iterations", "game_batch",
            "sims", "move_limit", "m_root", "dirichlet_alpha",
            "seed", "modes")},
        **results,
        "policy_ce": {m: ce_first_last(results[m])
                      for m in a.modes},
    }
    path = os.path.join(a.out_dir, "comparison.json")
    with open(path, "w") as f:
        json.dump(comparison, f, indent=2)
    print(json.dumps(comparison["policy_ce"], indent=2))
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
