// Native Go game replayer for corpus conversion.
//
// Role: the host-side rules hot loop of SGF->training-data conversion
// (SURVEY.md §3.4) — the counterpart of the reference's optional
// Cython engine branch (SURVEY.md §2a "native components"). The device
// path (feature encoding, training) stays JAX/XLA; this replaces only
// the per-move Python rules bookkeeping (pygo.GameState.do_move) when
// walking millions of recorded positions.
//
// Semantics mirror rocalphago_tpu.engine.pygo exactly:
//   * captures via liberty-less opponent groups, suicide illegal,
//   * simple ko (single capture by a lone stone left with exactly one
//     liberty bans the captured point),
//   * stone_ages[p] = turns_played at placement (-1 when empty),
//   * two consecutive passes end the game; later moves are illegal,
//   * handicap/setup stones get age 0.
//
// API (extern "C", ctypes-friendly): go_replay() writes the pre-move
// snapshot of every ply (board, player to move, recorded mover, ko,
// step count, stone ages) and returns the ply count, or -(k+1) if the
// k-th move is illegal.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int8_t EMPTY = 0;

struct Board {
    int size = 0;
    int n = 0;
    std::vector<int8_t> stones;
    std::vector<int32_t> ages;
    int32_t ko = -1;          // flat point banned by simple ko, -1 none
    int32_t turns = 0;
    int8_t to_move = 1;       // black
    int passes = 0;           // consecutive
    bool over = false;

    void init(int s) {
        size = s;
        n = s * s;
        stones.assign(n, EMPTY);
        ages.assign(n, -1);
    }

    inline int neighbors(int p, int out[4]) const {
        const int x = p / size, y = p % size;
        int k = 0;
        if (x > 0) out[k++] = p - size;
        if (x + 1 < size) out[k++] = p + size;
        if (y > 0) out[k++] = p - 1;
        if (y + 1 < size) out[k++] = p + 1;
        return k;
    }

    // Flood-fill the group at `p` on `b`; returns stone count and
    // whether it has at least `min_libs` liberties (early exit).
    int group(const std::vector<int8_t>& b, int p,
              std::vector<int32_t>& stack, std::vector<uint8_t>& seen,
              bool* has_lib) const {
        const int8_t color = b[p];
        stack.clear();
        std::fill(seen.begin(), seen.end(), 0);
        stack.push_back(p);
        seen[p] = 1;
        int count = 0;
        bool lib = false;
        int nb[4];
        while (!stack.empty()) {
            const int q = stack.back();
            stack.pop_back();
            ++count;
            const int k = neighbors(q, nb);
            for (int i = 0; i < k; ++i) {
                const int r = nb[i];
                if (b[r] == EMPTY) {
                    lib = true;
                } else if (b[r] == color && !seen[r]) {
                    seen[r] = 1;
                    stack.push_back(r);
                }
            }
        }
        *has_lib = lib;
        return count;
    }

    void remove_group(std::vector<int8_t>& b, int p,
                      std::vector<int32_t>& removed) const {
        const int8_t color = b[p];
        std::vector<int32_t> stack{p};
        b[p] = EMPTY;
        removed.push_back(p);
        int nb[4];
        while (!stack.empty()) {
            const int q = stack.back();
            stack.pop_back();
            const int k = neighbors(q, nb);
            for (int i = 0; i < k; ++i) {
                const int r = nb[i];
                if (b[r] == color) {
                    b[r] = EMPTY;
                    removed.push_back(r);
                    stack.push_back(r);
                }
            }
        }
    }

    // Apply a move; returns false if illegal. `action == n` is a pass.
    bool play(int32_t action, int8_t color,
              std::vector<int32_t>& scratch_stack,
              std::vector<uint8_t>& scratch_seen) {
        if (over) return false;
        if (action == n) {
            ko = -1;
            ++turns;
            to_move = static_cast<int8_t>(-color);
            if (++passes >= 2) over = true;
            return true;
        }
        passes = 0;
        if (action < 0 || action > n) return false;
        if (stones[action] != EMPTY) return false;
        if (ko == action) return false;

        std::vector<int8_t> b = stones;
        b[action] = color;
        std::vector<int32_t> captured;
        int nb[4];
        const int k = neighbors(action, nb);
        for (int i = 0; i < k; ++i) {
            const int r = nb[i];
            if (b[r] == -color) {
                bool has_lib = false;
                group(b, r, scratch_stack, scratch_seen, &has_lib);
                if (!has_lib) remove_group(b, r, captured);
            }
        }
        bool own_lib = false;
        const int own_count =
            group(b, action, scratch_stack, scratch_seen, &own_lib);
        if (!own_lib) return false;  // suicide

        // simple ko: lone stone capturing exactly one, left in atari
        ko = -1;
        if (captured.size() == 1 && own_count == 1) {
            int libs = 0;
            for (int i = 0; i < k; ++i)
                if (b[nb[i]] == EMPTY) ++libs;
            if (libs == 1) ko = captured[0];
        }

        stones.swap(b);
        for (const int32_t p : captured) ages[p] = -1;
        ages[action] = turns;
        ++turns;
        to_move = static_cast<int8_t>(-color);
        return true;
    }
};

}  // namespace

extern "C" {

// Writes pre-move snapshots for each of n_moves plies. Returns
// n_moves on success, -(k+1) if ply k is illegal (including setup
// collisions reported as ply 0).
int go_replay(int size,
              const int32_t* setup_black, int n_sb,
              const int32_t* setup_white, int n_sw,
              const int32_t* moves, const int8_t* colors, int n_moves,
              int8_t* out_boards,    // [n_moves * size*size]
              int8_t* out_to_move,   // [n_moves]
              int32_t* out_kos,      // [n_moves]
              int32_t* out_steps,    // [n_moves]
              int32_t* out_ages) {   // [n_moves * size*size]
    if (size < 2 || size > 25) return -1;
    Board bd;
    bd.init(size);
    for (int i = 0; i < n_sb; ++i) {
        const int32_t p = setup_black[i];
        if (p < 0 || p >= bd.n || bd.stones[p] != EMPTY) return -1;
        bd.stones[p] = 1;
        bd.ages[p] = 0;
    }
    for (int i = 0; i < n_sw; ++i) {
        const int32_t p = setup_white[i];
        if (p < 0 || p >= bd.n || bd.stones[p] != EMPTY) return -1;
        bd.stones[p] = -1;
        bd.ages[p] = 0;
    }
    if (n_moves > 0) bd.to_move = colors[0];

    std::vector<int32_t> scratch_stack;
    scratch_stack.reserve(bd.n);
    std::vector<uint8_t> scratch_seen(bd.n);

    for (int m = 0; m < n_moves; ++m) {
        std::memcpy(out_boards + static_cast<size_t>(m) * bd.n,
                    bd.stones.data(), bd.n);
        out_to_move[m] = bd.to_move;
        out_kos[m] = bd.ko;
        out_steps[m] = bd.turns;
        std::memcpy(out_ages + static_cast<size_t>(m) * bd.n,
                    bd.ages.data(),
                    static_cast<size_t>(bd.n) * sizeof(int32_t));
        if (!bd.play(moves[m], colors[m], scratch_stack, scratch_seen))
            return -(m + 1);
    }
    return n_moves;
}

}  // extern "C"
