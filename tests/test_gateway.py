"""Network play gateway (``rocalphago_tpu/gateway``): the NDJSON
wire protocol, structured shedding, per-request fault wall, drain
semantics, the HTTP probe sidecar, and the GTP bridge.

Fast tier: protocol framing unit tests (torn / oversized / undecodable
frames), a full happy-path conversation over a real socket, every
typed refusal (``bad_proto``, ``unknown_type``, ``no_game``,
``illegal_move``, ``bad_board``, ``overload`` at both the connection
cap and the pool's admission cap), abrupt-disconnect slot reclamation,
graceful drain (goodbye + clean thread exit + 503 health), multi-size
board routing, the ``--connect`` GTP bridge, and a short
``scripts/gateway_soak.py`` run in a subprocess. The multi-minute
default soak is ``slow``.
"""

import io
import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from rocalphago_tpu.gateway import protocol
from rocalphago_tpu.gateway.client import (
    GatewayClient,
    GatewayClosed,
    GatewayError,
    GatewayRefused,
    connect_with_retry,
    run_load,
)
from rocalphago_tpu.gateway.server import GatewayServer
from rocalphago_tpu.io.metrics import MetricsLogger
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.jsonl import read_jsonl
from rocalphago_tpu.serve import ServePool

SIZE = 5
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """Tests install plans programmatically; always restore the
    env-derived (empty) plan afterwards."""
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def nets():
    from rocalphago_tpu.models import CNNPolicy, CNNValue

    pol = CNNPolicy(("board", "ones"), board=SIZE, layers=1,
                    filters_per_layer=2)
    val = CNNValue(("board", "ones", "color"), board=SIZE, layers=1,
                   filters_per_layer=2)
    return pol, val


@pytest.fixture(scope="module")
def pool(nets):
    """One warm 5×5 pool shared by the module (XLA compiles
    dominate); tests read stat DELTAS, never absolute counters."""
    pol, val = nets
    p = ServePool(val, pol, n_sim=6, max_sessions=4,
                  batch_sizes=(1, 2, 4), max_wait_us=2000)
    p.warm()
    yield p
    p.close()


@pytest.fixture(scope="module")
def server(pool):
    """One long-lived gateway for the happy-path / refusal tests.
    Shedding and drain tests build their own (drain is one-way)."""
    srv = GatewayServer(pool, max_conns=4, slo_ms=2000.0)
    srv.start()
    yield srv
    srv.close()


def settle(server, pool=None, timeout: float = 10.0) -> None:
    """Wait until the gateway's handler threads have released every
    connection slot (and, when given, the pool every session) — an
    abrupt client close is only *observed* by the server at its next
    read, so admission-sensitive asserts must not race it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        live = server.stats()["conns"]["live"]
        pool_live = (0 if pool is None
                     else pool.stats()["sessions"]["live"])
        if live == 0 and pool_live == 0:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"gateway did not settle: {server.stats()['conns']}")


def raw_conn(port: int):
    """A frame-level client: (socket, buffered reader) with the
    server's hello already consumed — for tests that must write
    malformed bytes no GatewayClient would ever send."""
    sock = socket.create_connection(("127.0.0.1", port), timeout=30.0)
    reader = sock.makefile("rb")
    hello = protocol.read_frame(reader)
    assert hello["type"] == "hello"
    return sock, reader


# ----------------------------------------------------------- protocol


def test_frame_roundtrip_is_byte_stable():
    msg = {"type": "new_game", "id": 3, "board": 5, "komi": 5.5}
    wire = protocol.encode_frame(msg)
    assert wire.endswith(b"\n") and wire.count(b"\n") == 1
    # sorted keys: identical dicts encode identically
    assert wire == protocol.encode_frame(dict(reversed(msg.items())))
    assert protocol.read_frame(io.BytesIO(wire)) == msg


def test_torn_and_empty_frames_are_disconnects():
    assert protocol.read_frame(io.BytesIO(b"")) is None
    # EOF mid-line: a torn frame, not an error
    assert protocol.read_frame(io.BytesIO(b'{"type": "ok"')) is None
    assert protocol.read_frame(io.BytesIO(b"\n")) is None


def test_oversized_frame_is_fatal():
    big = b'{"pad": "' + b"x" * 100 + b'"}\n'
    with pytest.raises(protocol.ProtocolError) as ei:
        protocol.read_frame(io.BytesIO(big), limit=32)
    assert ei.value.code == "frame_too_big"
    assert ei.value.fatal


def test_frame_bound_counts_the_newline():
    ok = b'{"a": "' + b"x" * 22 + b'"}\n'      # exactly 32 bytes
    assert len(ok) == 32
    assert protocol.read_frame(io.BytesIO(ok), limit=32) is not None
    over = b'{"a": "' + b"x" * 23 + b'"}\n'    # 33 bytes, complete line
    with pytest.raises(protocol.ProtocolError) as ei:
        protocol.read_frame(io.BytesIO(over), limit=32)
    assert ei.value.code == "frame_too_big"
    assert ei.value.fatal


def test_blank_lines_are_skipped_not_disconnects():
    wire = b"\n\n" + protocol.encode_frame({"type": "ok"}) + b"\n"
    reader = io.BytesIO(wire)
    assert protocol.read_frame(reader) == {"type": "ok"}
    # the trailing blank line runs into EOF: a disconnect
    assert protocol.read_frame(reader) is None


def test_undecodable_frame_is_nonfatal():
    for bad in (b"{oops}\n", b"[1, 2]\n", b'"str"\n'):
        with pytest.raises(protocol.ProtocolError) as ei:
            protocol.read_frame(io.BytesIO(bad))
        assert ei.value.code == "bad_request"
        assert not ei.value.fatal


def test_error_frame_schema():
    f = protocol.error_frame("overload", "full", id=7,
                             retry_after_s=1.0)
    assert f == {"type": "error", "code": "overload", "msg": "full",
                 "id": 7, "retry_after_s": 1.0}
    with pytest.raises(AssertionError):
        protocol.error_frame("not_a_code", "nope")


# ----------------------------------------------------- happy path


def test_happy_path_conversation(server, pool):
    """hello → new_game → genmove/play/komi → close → new game on the
    SAME connection; probe counters move with the traffic."""
    before = server.stats()
    client = GatewayClient("127.0.0.1", server.port)
    try:
        assert client.hello["proto"] == protocol.PROTO_VERSION
        assert client.hello["name"] == "rocalphago-gateway"
        assert client.boards == (SIZE,)
        assert client.default_board == SIZE

        opened = client.new_game(komi=5.5)
        assert (opened["board"], opened["komi"]) == (SIZE, 5.5)

        reply = client.genmove("b")
        assert reply["type"] == "move"
        assert reply["elapsed_ms"] >= 0.0
        assert reply["slo_hit"] is False    # 2s SLO, 6-sim search
        assert "rung" in reply
        vertex = reply["move"]
        assert vertex == "pass" or vertex[0].isalpha()

        assert client.play("w", "pass")["type"] == "ok"
        assert client.set_komi(6.5)["type"] == "ok"
        assert client.close_game()["type"] == "ok"
        # the connection outlives the game: a second game opens
        assert client.new_game()["board"] == SIZE
    finally:
        client.close()
    settle(server, pool)
    after = server.stats()
    assert after["conns"]["accepted"] == before["conns"]["accepted"] + 1
    assert after["requests"]["genmoves"] \
        == before["requests"]["genmoves"] + 1
    assert after["requests"]["total"] >= before["requests"]["total"] + 6
    assert after["requests"]["unhandled"] \
        == before["requests"]["unhandled"]
    assert after["wire_ms"]["p50"] is not None
    assert after["slo_ms"] == 2000.0
    assert after["boards"] == [SIZE]


def test_hello_pins_protocol_version(server):
    client = GatewayClient("127.0.0.1", server.port)
    try:
        ok = client.request({"type": "hello",
                             "proto": protocol.PROTO_VERSION})
        assert ok["proto"] == protocol.PROTO_VERSION
        with pytest.raises(GatewayError) as ei:
            client.request({"type": "hello", "proto": 99})
        assert ei.value.code == "bad_proto"
    finally:
        client.close()
    settle(server)


# ------------------------------------------------------ typed refusals


def test_unknown_type_is_survivable(server):
    client = GatewayClient("127.0.0.1", server.port)
    try:
        with pytest.raises(GatewayError) as ei:
            client.request({"type": "flarb"})
        assert ei.value.code == "unknown_type"
        # the connection survived the refusal
        assert client.new_game()["type"] == "ok"
    finally:
        client.close()
    settle(server)


def test_requests_before_new_game_are_no_game(server):
    client = GatewayClient("127.0.0.1", server.port)
    try:
        for req in ({"type": "genmove", "color": "b"},
                    {"type": "play", "color": "b", "move": "C3"},
                    {"type": "komi", "komi": 7.5}):
            with pytest.raises(GatewayError) as ei:
                client.request(req)
            assert ei.value.code == "no_game"
    finally:
        client.close()
    settle(server)


def test_illegal_move_leaves_game_intact(server):
    client = GatewayClient("127.0.0.1", server.port)
    try:
        client.new_game()
        client.play("b", "C3")
        with pytest.raises(GatewayError) as ei:
            client.play("w", "C3")         # occupied point
        assert ei.value.code == "illegal_move"
        # state held: the game still answers
        assert client.genmove("w")["type"] == "move"
    finally:
        client.close()
    settle(server)


def test_bad_board_names_what_is_served(server):
    client = GatewayClient("127.0.0.1", server.port)
    try:
        with pytest.raises(GatewayError) as ei:
            client.new_game(board=9)
        assert ei.value.code == "bad_board"
        assert str(SIZE) in str(ei.value)
    finally:
        client.close()
    settle(server)


def test_malformed_new_game_fields_do_not_leak_sessions(server, pool):
    """A non-numeric ``komi``/``board`` is a typed ``bad_request``
    that never reaches the pool — repeated past ``max_sessions``
    it must not eat admission slots (the REVIEW.md leak)."""
    before = server.stats()
    client = GatewayClient("127.0.0.1", server.port)
    try:
        for _ in range(pool.stats()["sessions"]["max"] + 1):
            with pytest.raises(GatewayError) as ei:
                client.request({"type": "new_game", "komi": "abc"})
            assert ei.value.code == "bad_request"
        with pytest.raises(GatewayError) as ei:
            client.request({"type": "new_game", "komi": [6.5]})
        assert ei.value.code == "bad_request"
        with pytest.raises(GatewayError) as ei:
            client.request({"type": "new_game", "board": "five"})
        assert ei.value.code == "bad_request"
        assert pool.stats()["sessions"]["live"] == 0
        # every slot survived: a real game still opens
        assert client.new_game()["type"] == "ok"
    finally:
        client.close()
    settle(server, pool)
    after = server.stats()
    assert after["requests"]["unhandled"] \
        == before["requests"]["unhandled"]


def test_malformed_komi_is_bad_request_and_game_holds(server, pool):
    before = server.stats()["requests"]["unhandled"]
    client = GatewayClient("127.0.0.1", server.port)
    try:
        client.new_game()
        with pytest.raises(GatewayError) as ei:
            client.request({"type": "komi", "komi": {"k": 1}})
        assert ei.value.code == "bad_request"
        # the game survived the refusal
        assert client.genmove("b")["type"] == "move"
    finally:
        client.close()
    settle(server, pool)
    assert server.stats()["requests"]["unhandled"] == before


def test_blank_line_over_wire_is_harmless(server):
    sock, reader = raw_conn(server.port)
    try:
        sock.sendall(b"\n")
        sock.sendall(protocol.encode_frame(
            {"type": "hello", "id": 1,
             "proto": protocol.PROTO_VERSION}))
        assert protocol.read_frame(reader)["type"] == "ok"
    finally:
        reader.close()
        sock.close()
    settle(server)


def test_bad_json_over_wire_is_reported_not_fatal(server):
    sock, reader = raw_conn(server.port)
    try:
        sock.sendall(b"{this is not json\n")
        err = protocol.read_frame(reader)
        assert err["type"] == "error" and err["code"] == "bad_request"
        # the line boundary survived: the connection still works
        sock.sendall(protocol.encode_frame(
            {"type": "hello", "id": 1,
             "proto": protocol.PROTO_VERSION}))
        assert protocol.read_frame(reader)["type"] == "ok"
    finally:
        reader.close()
        sock.close()
    settle(server)


def test_oversized_frame_drops_the_connection(server):
    sock, reader = raw_conn(server.port)
    try:
        pad = "x" * (protocol.max_frame_bytes() + 16)
        sock.sendall(json.dumps({"pad": pad}).encode() + b"\n")
        err = protocol.read_frame(reader)
        assert err["code"] == "frame_too_big"
        # fatal: the server hangs up after the refusal
        assert protocol.read_frame(reader) is None
    finally:
        reader.close()
        sock.close()
    settle(server)


# --------------------------------------------------------- shedding


def test_connection_cap_sheds_with_retry_hint(pool):
    srv = GatewayServer(pool, max_conns=1).start()
    try:
        shed_c = obs_registry.counter("gateway_connections_total",
                                      result="shed")
        shed0 = shed_c.value
        first = GatewayClient("127.0.0.1", srv.port)
        try:
            with pytest.raises(GatewayRefused) as ei:
                GatewayClient("127.0.0.1", srv.port)
            assert ei.value.code == "overload"
            assert ei.value.retry_after_s == 1.0
        finally:
            first.close()
        settle(srv)
        assert srv.stats()["conns"]["shed"] == 1
        assert shed_c.value == shed0 + 1
        # the slot came back: the next connection is admitted
        readmitted = GatewayClient("127.0.0.1", srv.port)
        readmitted.close()
        settle(srv)
        assert srv.stats()["conns"]["accepted"] == 2
    finally:
        srv.close()


def test_connect_with_retry_rides_out_a_shed(pool):
    """ISSUE 17 satellite: a client shed at accept backs off AT
    LEAST the server's ``retry_after_s`` (not just the jitter
    floor) and is admitted on a later attempt once a slot frees —
    the injectable sleep doubles as the slot-freeing hook, so the
    test asserts the schedule instead of waiting it out."""
    srv = GatewayServer(pool, max_conns=1).start()
    hog = GatewayClient("127.0.0.1", srv.port)
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        hog.close()
        settle(srv)

    try:
        c = connect_with_retry("127.0.0.1", srv.port, attempts=4,
                               base_delay=0.01, max_delay=0.05,
                               sleep=sleep)
        c.close()
        # exactly one shed round, floored by the refusal's hint
        # (jitter alone tops out at max_delay=0.05 here)
        assert len(sleeps) == 1 and sleeps[0] >= 1.0
        settle(srv)
        assert srv.stats()["conns"]["shed"] == 1
        assert srv.stats()["conns"]["accepted"] == 2
        # and a dead port still propagates the final failure
        with pytest.raises(OSError):
            connect_with_retry("127.0.0.1", 1, attempts=2,
                               base_delay=0.01, max_delay=0.02,
                               timeout=1.0, sleep=lambda s: None)
    finally:
        hog.close()
        srv.close()


def test_pool_admission_cap_sheds_new_game(pool):
    """More connections than pool sessions: the 5th new_game is a
    structured ``overload`` refusal from the pool's admission
    controller, not a hang — and closing a game frees the slot."""
    srv = GatewayServer(pool, max_conns=8).start()
    clients = []
    try:
        for _ in range(pool.stats()["sessions"]["max"]):
            c = GatewayClient("127.0.0.1", srv.port)
            clients.append(c)
            c.new_game()
        extra = GatewayClient("127.0.0.1", srv.port)
        clients.append(extra)
        with pytest.raises(GatewayRefused) as ei:
            extra.new_game()
        assert ei.value.code == "overload"
        assert ei.value.retry_after_s is not None
        assert srv.stats()["conns"]["shed"] >= 1
        clients[0].close_game()
        assert extra.new_game()["type"] == "ok"
    finally:
        for c in clients:
            c.close()
        settle(srv, pool)
        srv.close()


def test_abrupt_disconnect_reclaims_session_and_slot(server, pool):
    """A client that vanishes without ``close`` must not leak its
    pool session or its connection slot."""
    client = GatewayClient("127.0.0.1", server.port)
    client.new_game()
    assert pool.stats()["sessions"]["live"] >= 1
    client.sock.shutdown(socket.SHUT_RDWR)  # no goodbye, no close frame
    client.close()
    settle(server, pool)
    assert server.stats()["conns"]["live"] == 0


def test_load_generator_counts_partial_and_full_games(server):
    out = run_load("127.0.0.1", server.port, conns=2, moves=2,
                   board=SIZE)
    assert out["moves"] == 4
    assert out["sheds"] == out["disconnects"] == out["errors"] == 0
    assert len(out["latencies_s"]) == 4
    assert out["elapsed_s"] > 0
    settle(server)


# ------------------------------------------------------- fault wall


def test_injected_kill_aborts_connection_not_server(server, pool):
    """A kill at ``gateway.conn`` ends THAT connection with a typed
    ``internal`` error; the server keeps serving new ones."""
    before = server.stats()
    client = GatewayClient("127.0.0.1", server.port)
    faults.install("kill@gateway.conn:p=1.0,seed=3")
    try:
        with pytest.raises((GatewayError, GatewayClosed)) as ei:
            client.new_game()
        if isinstance(ei.value, GatewayError):
            assert ei.value.code == "internal"
    finally:
        faults.install(None)
        client.close()
    settle(server, pool)
    after = server.stats()
    assert after["faults"]["kills"] == before["faults"]["kills"] + 1
    assert after["requests"]["unhandled"] \
        == before["requests"]["unhandled"]
    # the server survived: a clean client plays on
    survivor = GatewayClient("127.0.0.1", server.port)
    try:
        assert survivor.new_game()["type"] == "ok"
    finally:
        survivor.close()
    settle(server, pool)


def test_injected_transient_fails_one_request_only(server, pool):
    """A transient at ``gateway.conn`` fails the request it hit and
    nothing else — the connection and its game survive."""
    before = server.stats()
    client = GatewayClient("127.0.0.1", server.port)
    try:
        client.new_game()
        faults.install("io_error@gateway.conn:p=1.0,seed=5")
        with pytest.raises(GatewayError) as ei:
            client.genmove("b")
        assert ei.value.code == "internal"
        faults.install(None)
        assert client.genmove("b")["type"] == "move"
    finally:
        faults.install(None)
        client.close()
    settle(server, pool)
    after = server.stats()
    assert after["faults"]["injected"] \
        == before["faults"]["injected"] + 1
    assert after["requests"]["unhandled"] \
        == before["requests"]["unhandled"]


# ------------------------------------------------------------- drain


def test_drain_is_graceful_idempotent_and_observable(pool, tmp_path):
    metrics = MetricsLogger(str(tmp_path / "metrics.jsonl"),
                            echo=False)
    srv = GatewayServer(pool, max_conns=4, metrics=metrics).start()
    from rocalphago_tpu.gateway.httpapi import GatewayHTTP

    http = GatewayHTTP(srv).start()
    client = GatewayClient("127.0.0.1", srv.port)
    client.new_game()
    try:
        srv.drain(reason="test")
        assert srv.draining
        # the idle connection was nudged out and its session closed
        settle(srv, pool)
        with pytest.raises(GatewayClosed):
            client.request({"type": "genmove", "color": "b"})
        # the listener is gone: new connections are refused at TCP
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", srv.port),
                                     timeout=2.0)
        # health flips to 503/draining for dumb LB checks
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/healthz", timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["status"] == "draining"
        srv.drain(reason="again")          # idempotent: returns fast
    finally:
        client.close()
        http.close()
        srv.close()
        metrics.close()
    phases = [r.get("phase") for r in
              read_jsonl(str(tmp_path / "metrics.jsonl"))
              if r.get("event") == "drain"]
    assert phases == ["gateway_requested", "gateway_accept_stopped",
                      "gateway_drained"]


# ------------------------------------------------------- HTTP probes


def test_healthz_and_metrics_endpoints(server, pool):
    from rocalphago_tpu.gateway.httpapi import GatewayHTTP

    http = GatewayHTTP(server).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/healthz",
                timeout=10) as r:
            assert r.status == 200
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert body["serve"]["sessions"]["max"] \
            == pool.stats()["sessions"]["max"]
        assert body["gateway"]["proto"] == protocol.PROTO_VERSION
        assert set(body["gateway"]["conns"]) \
            == {"live", "max", "accepted", "shed"}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/metrics",
                timeout=10) as r:
            text = r.read().decode()
        assert "gateway_conns_live" in text
        assert 'gateway_connections_total{result="accepted"}' in text
        assert "gateway_wire_seconds" in text
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        http.close()


# -------------------------------------------------- multi-size routing


def test_multisize_pool_routes_by_board(nets):
    from rocalphago_tpu.multisize import MultiSizePool

    pol, val = nets
    mpool = MultiSizePool(val, pol, sizes=(5, 7), n_sim=4,
                          batch_sizes=(1, 2))
    srv = GatewayServer(mpool, max_conns=2).start()
    try:
        client = GatewayClient("127.0.0.1", srv.port)
        try:
            assert client.boards == (5, 7)
            assert client.default_board == 5
            assert client.new_game(board=7)["board"] == 7
            assert client.genmove("b")["type"] == "move"
            with pytest.raises(GatewayError) as ei:
                client.new_game(board=9)
            assert ei.value.code == "bad_board"
        finally:
            client.close()
        settle(srv)
    finally:
        srv.close()
        mpool.close()


# -------------------------------------------------------- GTP bridge


def test_gtp_bridge_speaks_gtp_over_the_wire(server):
    from rocalphago_tpu.interface.gtp import GatewayBridge

    client = GatewayClient("127.0.0.1", server.port)
    bridge = GatewayBridge(client)
    try:
        assert bridge.handle("protocol_version") == ("= 2\n\n", False)
        assert bridge.handle("name") \
            == ("= rocalphago-gateway\n\n", False)
        assert bridge.handle("known_command genmove") \
            == ("= true\n\n", False)
        assert bridge.handle(f"boardsize {SIZE}") == ("=\n\n", False)
        reply, done = bridge.handle("boardsize 19")
        assert reply == "? unacceptable size\n\n" and not done
        assert bridge.handle("clear_board") == ("=\n\n", False)
        assert bridge.handle("komi 6.5") == ("=\n\n", False)
        reply, done = bridge.handle("genmove b")
        assert reply.startswith("= ") and not done
        assert bridge.handle("play w pass") == ("=\n\n", False)
        reply, done = bridge.handle("frobnicate")
        assert reply == "? unknown command\n\n" and not done
        reply, done = bridge.handle("1 quit")
        assert reply == "=1\n\n" and done
    finally:
        client.close()
    settle(server)


def test_gtp_bridge_loop_and_shed_reporting(server, pool):
    from rocalphago_tpu.interface.gtp import (
        GatewayBridge,
        run_bridge,
    )

    client = GatewayClient("127.0.0.1", server.port)
    out = io.StringIO()
    try:
        run_bridge(GatewayBridge(client),
                   instream=io.StringIO(
                       "name\ngenmove b\nquit\nname\n"),
                   outstream=out)
    finally:
        client.close()
    text = out.getvalue()
    # the loop stopped at quit: exactly one name reply
    assert text.count("= rocalphago-gateway") == 1
    assert "= " in text.split("rocalphago-gateway")[1]
    settle(server, pool)


def test_gtp_connect_cli_reports_refusal(pool):
    """``gtp.py --connect`` against a full gateway exits with the
    structured refusal, not a traceback or a hang."""
    from rocalphago_tpu.interface import gtp

    srv = GatewayServer(pool, max_conns=1).start()
    holder = GatewayClient("127.0.0.1", srv.port)
    try:
        with pytest.raises(SystemExit) as ei:
            gtp.main(["--connect", f"127.0.0.1:{srv.port}"])
        assert "gateway refused" in str(ei.value)
        assert "retry" in str(ei.value)
    finally:
        holder.close()
        settle(srv)
        srv.close()
    # malformed --connect is an argparse error, before any network
    with pytest.raises(SystemExit):
        gtp.main(["--connect", "no-port-here"])


# --------------------------------------------------------------- soak


def run_soak(tmp_path, extra):
    out_dir = str(tmp_path / "soak")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "gateway_soak.py"),
         "--out", out_dir, *extra],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PALLAS_AXON_POOL_IPS=""),
        cwd=REPO, capture_output=True, text=True, timeout=600)
    return proc, os.path.join(out_dir, "summary.json")


def check_soak(proc, out):
    assert proc.returncode == 0, \
        f"soak failed:\n{proc.stdout}\n{proc.stderr}"
    with open(out) as f:
        summary = json.load(f)
    assert all(summary["checks"].values()), summary["checks"]
    assert summary["unhandled"] == 0
    assert summary["sheds_metrics"] == summary["sheds_server"] > 0
    return summary


@pytest.mark.slow
def test_gateway_soak_smoke(tmp_path):
    """The chaos soak, sized for the full tier (suite wall-time): kills at the
    connection barrier, sheds counted in /metrics, a green gate
    after the storm, and a clean SIGTERM drain (exit 0)."""
    proc, out = run_soak(tmp_path, ["--conns", "3", "--max-conns", "2",
                                    "--moves", "3", "--min-kills", "1",
                                    "--p-kill", "0.3",
                                    "--deadline-s", "150"])
    summary = check_soak(proc, out)
    assert summary["kills"] >= 1


@pytest.mark.slow
def test_gateway_soak_full(tmp_path):
    proc, out = run_soak(tmp_path, [])
    summary = check_soak(proc, out)
    assert summary["kills"] >= 3
