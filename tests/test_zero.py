"""AlphaZero-style iteration (``training.zero``) smoke + sanity.

Tiny nets, tiny search — the point is that the full loop (device-MCTS
self-play with recorded visit targets → chunked replay gradients for
both nets → one optimizer step each) runs compiled end-to-end and
moves both nets' parameters with finite losses.
"""

import jax
import jax.flatten_util  # noqa: F401 — used as jax.flatten_util
import numpy as np
import optax
import pytest

from rocalphago_tpu.engine.jaxgo import GoConfig
from rocalphago_tpu.models import CNNPolicy, CNNValue
from rocalphago_tpu.training.zero import (
    init_zero_state,
    make_zero_iteration,
)

SIZE = 5
FEATS = ("board", "ones")
VFEATS = FEATS + ("color",)


@pytest.fixture(scope="module")
def nets():
    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    return pol, val


@pytest.mark.slow
def test_zero_iteration_trains_both_nets(nets):
    pol, val = nets
    cfg = GoConfig(size=SIZE)
    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    # move_limit must cover natural 5x5 game length (~47 plies): the
    # value loss is masked to games that END by two passes, so a
    # too-small cap leaves the value net untrained (by design —
    # capped-game area scores label half-played boards)
    iteration = make_zero_iteration(
        cfg, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, batch=2, move_limit=60, n_sim=8, max_nodes=16,
        sim_chunk=4, replay_chunk=7)
    state = init_zero_state(pol.params, val.params, tx_p, tx_v, seed=3)

    new, metrics = iteration(state)
    assert int(jax.device_get(new.iteration)) == 1
    for key in ("policy_loss", "value_loss", "black_win_rate",
                "draw_rate", "mean_moves", "value_mse", "value_acc",
                "finished_rate"):
        assert np.isfinite(float(jax.device_get(metrics[key]))), key
    assert 0.0 <= float(jax.device_get(metrics["value_acc"])) <= 1.0
    # 60 plies cover natural 5x5 endings — games must actually end
    # (otherwise the masked value loss trains on nothing)
    assert float(jax.device_get(metrics["finished_rate"])) > 0

    def delta(a, b):
        fa, _ = jax.flatten_util.ravel_pytree(jax.device_get(a))
        fb, _ = jax.flatten_util.ravel_pytree(jax.device_get(b))
        return float(np.abs(np.asarray(fa) - np.asarray(fb)).max())

    assert delta(state.policy_params, new.policy_params) > 0
    assert delta(state.value_params, new.value_params) > 0

    # a second iteration continues from the new state (rng threads on)
    newer, _ = iteration(new)
    assert int(jax.device_get(newer.iteration)) == 2
    assert not np.array_equal(np.asarray(new.rng),
                              np.asarray(newer.rng))


@pytest.mark.slow
def test_zero_cli_trains_saves_and_resumes(tmp_path, nets):
    """The trainer CLI end to end on tiny specs: metrics written,
    GTP-loadable exports, and a rerun with a higher --iterations
    resumes from the checkpoint instead of restarting."""
    import json

    from rocalphago_tpu.training.zero import run_training

    pol, val = nets
    pj, vj = str(tmp_path / "p.json"), str(tmp_path / "v.json")
    pol.save_model(pj)
    val.save_model(vj)
    out = str(tmp_path / "out")
    args = [pj, vj, out, "--game-batch", "2", "--iterations", "1",
            "--move-limit", "16", "--sims", "4", "--sim-chunk", "2",
            "--save-every", "1"]
    final = run_training(args)
    assert final["iteration"] == 0

    from rocalphago_tpu.models.nn_util import NeuralNetBase

    exported = NeuralNetBase.load_model(str(tmp_path / "out"
                                            / "policy.json"))
    assert exported.board == SIZE

    args[args.index("--iterations") + 1] = "2"
    final = run_training(args)
    assert final["iteration"] == 1          # resumed, ran only iter 1
    lines = [json.loads(ln) for ln in
             (tmp_path / "out" / "metrics.jsonl").read_text()
             .splitlines()]
    assert any(e["event"] == "resume" and e["iteration"] == 1
               for e in lines)
    # evaluator gating ran (default-on): a gate match was logged and
    # the pool holds the iteration-0 incumbent snapshot
    gates = [e for e in lines if e["event"] == "gate"]
    assert gates and all(0.0 <= g["win_rate_a"] <= 1.0 for g in gates)
    assert (tmp_path / "out" / "pool"
            / "best.00000.policy.msgpack").exists()


def test_zero_gate_decide_requires_wilson_bound():
    """Promotion needs BOTH the point-estimate threshold AND a Wilson
    95% lower bound >= 0.5 on the decided-game win rate (VERDICT r5
    #4). ``decide`` reads only ``self.threshold``, so the rule is
    testable without building the match machinery."""
    from rocalphago_tpu.training.zero import ZeroGate

    g = object.__new__(ZeroGate)
    g.threshold = 0.55

    def result(wa, wb):
        return {"wins_a": wa, "wins_b": wb,
                "win_rate_a": wa / max(wa + wb, 1)}

    promoted, lb = g.decide(result(38, 26))     # 0.594 at 64 games:
    assert not promoted and lb < 0.5            # round 5's coin flip
    promoted, lb = g.decide(result(45, 19))     # 0.703: decisive
    assert promoted and lb >= 0.5
    g.threshold = 0.75                          # the point threshold
    promoted, _ = g.decide(result(45, 19))      # still gates on top
    assert not promoted


def test_zero_gate_match_and_promotion(tmp_path, nets):
    """ZeroGate mechanics: an even match reports a sane tally; a
    promotion writes a loadable best-pair snapshot; sample() draws
    from the pool statelessly."""
    from rocalphago_tpu.training.zero import ZeroGate

    pol, val = nets
    cfg = GoConfig(size=SIZE, komi=7.0)
    gate = ZeroGate(cfg, FEATS, pol.module.apply,
                    str(tmp_path / "pool"), games=8, threshold=0.55,
                    temperature=1.0, move_limit=60, chunk=20)
    r = gate.match(pol.params, pol.params, jax.random.key(0))
    assert r["wins_a"] + r["wins_b"] + r["draws"] == 8
    assert 0.0 <= r["win_rate_a"] <= 1.0

    gate.promote(pol.params, val.params, 3)
    snaps = gate.snapshots()
    assert [s[0] for s in snaps] == [3]
    lp, lv = gate.load(snaps[0], pol.params, val.params)
    flat0, _ = jax.flatten_util.ravel_pytree(pol.params)
    flat1, _ = jax.flatten_util.ravel_pytree(lp)
    np.testing.assert_array_equal(np.asarray(flat0),
                                  np.asarray(flat1))
    # the sole snapshot IS the incumbent — nothing past to ladder
    assert gate.sample(7, 11) is None
    gate.promote(pol.params, val.params, 5)
    # with a past entry the draw is stateless and never the incumbent
    assert gate.sample(7, 11) == gate.sample(7, 11)
    assert gate.sample(7, 11)[0] == 3


@pytest.mark.slow
@pytest.mark.parametrize("sample_moves", [False, True])
def test_zero_iteration_gumbel_targets(nets, sample_moves):
    """The Gumbel variant: self-play plays halving winners (or, with
    ``gumbel_sample``, samples moves from pi' — VERDICT r4 #9) and
    the policy learns from pi' (improved policy) float targets - one
    iteration must move both nets with finite losses."""
    pol, val = nets
    cfg = GoConfig(size=SIZE)
    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    iteration = make_zero_iteration(
        cfg, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, batch=2, move_limit=60, n_sim=8, max_nodes=16,
        sim_chunk=4, replay_chunk=8, gumbel=True,
        gumbel_sample=sample_moves)
    state = init_zero_state(pol.params, val.params, tx_p, tx_v,
                            seed=3)
    new_state, metrics = iteration(state)
    for k in ("policy_loss", "value_loss"):
        assert np.isfinite(float(metrics[k])), (k, metrics[k])
    flat0, _ = jax.flatten_util.ravel_pytree(state.policy_params)
    flat1, _ = jax.flatten_util.ravel_pytree(new_state.policy_params)
    assert not np.allclose(np.asarray(flat0), np.asarray(flat1))
    vflat0, _ = jax.flatten_util.ravel_pytree(state.value_params)
    vflat1, _ = jax.flatten_util.ravel_pytree(new_state.value_params)
    assert not np.allclose(np.asarray(vflat0), np.asarray(vflat1))


@pytest.mark.slow
def test_zero_actor_learner_lockstep_bit_exact(nets):
    """The acceptance pin (docs/SCALE.md): one lockstep actor + FIFO
    learner reproduce the synchronous iteration BIT-identically —
    same keys (the actor walks ``next_keys`` locally), same games
    (host round-trip through the buffer keeps raw dtypes), same
    params/opt-state/rng after two steps."""
    import optax as _optax

    from rocalphago_tpu.data.replay import ReplayBuffer
    from rocalphago_tpu.training.actor import (
        ParamsPublisher,
        SelfplayActor,
    )
    from rocalphago_tpu.training.learner import ZeroLearner

    pol, val = nets
    cfg = GoConfig(size=SIZE)
    tx_p, tx_v = _optax.sgd(0.01), _optax.sgd(0.01)
    iteration = make_zero_iteration(
        cfg, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, batch=2, move_limit=16, n_sim=4, max_nodes=16,
        sim_chunk=2, replay_chunk=5)
    state = init_zero_state(pol.params, val.params, tx_p, tx_v,
                            seed=5)

    s_sync = state
    sync_metrics = []
    for _ in range(2):
        s_sync, m = iteration(s_sync)
        sync_metrics.append(
            {k: float(jax.device_get(v)) for k, v in m.items()})

    buf = ReplayBuffer(capacity=4)
    pub = ParamsPublisher()
    actor = SelfplayActor(iteration.play, pub, buf, state.rng,
                          lockstep=True, games=2, poll_s=0.05)
    learner = ZeroLearner(iteration.learn, buf)
    pub.publish(state.policy_params, state.value_params, version=0)
    actor.start()
    s_al = state
    try:
        for it in range(2):
            s_al, m, entry = learner.step(s_al, timeout=120.0)
            assert entry.version == it       # FIFO, in lockstep order
            # the learner adds replay_version/replay_staleness_s on
            # top of the iteration metrics — those aside, identical
            assert {k: m[k] for k in sync_metrics[it]} \
                == sync_metrics[it]
            pub.publish(s_al.policy_params, s_al.value_params,
                        version=it + 1)
    finally:
        buf.close()
        actor.stop()
    assert actor.error is None

    def flat(tree):
        f, _ = jax.flatten_util.ravel_pytree(jax.device_get(tree))
        return np.asarray(f)

    for attr in ("policy_params", "value_params", "opt_policy",
                 "opt_value"):
        np.testing.assert_array_equal(
            flat(getattr(s_sync, attr)), flat(getattr(s_al, attr)),
            err_msg=attr)
    np.testing.assert_array_equal(np.asarray(s_sync.rng),
                                  np.asarray(s_al.rng))
    assert int(jax.device_get(s_al.iteration)) == 2


@pytest.mark.slow
def test_zero_actor_learner_cli_bit_exact(tmp_path, nets):
    """`run_training --actor-learner` (1 actor) vs the synchronous
    CLI: exported params bit-identical, iteration metrics equal."""
    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.training.zero import run_training

    pol, val = nets
    pj, vj = str(tmp_path / "p.json"), str(tmp_path / "v.json")
    pol.save_model(pj)
    val.save_model(vj)
    base = [pj, vj, "", "--game-batch", "2", "--iterations", "2",
            "--move-limit", "12", "--sims", "4", "--sim-chunk", "2",
            "--save-every", "2", "--seed", "5"]

    def run(out, extra):
        args = list(base)
        args[2] = str(tmp_path / out)
        return run_training(args + extra)

    f_sync = run("sync", [])
    f_al = run("al", ["--actor-learner"])
    for k in ("policy_loss", "value_loss", "mean_moves",
              "finished_rate"):
        assert f_sync[k] == f_al[k], k
    for name in ("policy", "value"):
        pa = NeuralNetBase.load_model(
            str(tmp_path / "sync" / f"{name}.json")).params
        pb = NeuralNetBase.load_model(
            str(tmp_path / "al" / f"{name}.json")).params
        fa, _ = jax.flatten_util.ravel_pytree(pa)
        fb, _ = jax.flatten_util.ravel_pytree(pb)
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb),
                                      err_msg=name)


@pytest.mark.slow
def test_zero_iteration_sharded_matches_unsharded(nets):
    """Mesh wiring is placement + constraints only: one iteration on
    the virtual 8-device mesh must match the unsharded run
    bit-for-bit (same rng, same math; XLA inserts the collectives)."""
    from rocalphago_tpu.parallel import mesh as meshlib

    pol, val = nets
    cfg = GoConfig(size=SIZE)
    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    kw = dict(batch=4, move_limit=20, n_sim=8, max_nodes=16,
              sim_chunk=4, replay_chunk=8)
    base = make_zero_iteration(
        cfg, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, **kw)
    mesh = meshlib.make_mesh(4)
    sharded = make_zero_iteration(
        cfg, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, mesh=mesh, **kw)
    s0 = init_zero_state(pol.params, val.params, tx_p, tx_v, seed=7)
    s0m = meshlib.replicate(mesh, init_zero_state(
        pol.params, val.params, tx_p, tx_v, seed=7))
    _, m1 = base(s0)
    _, m2 = sharded(s0m)
    for k in m1:
        np.testing.assert_allclose(
            float(jax.device_get(m1[k])), float(jax.device_get(m2[k])),
            rtol=1e-5, err_msg=k)
