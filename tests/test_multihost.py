"""Multi-host wiring tests (single-process simulation).

The reference has no distributed layer (SURVEY.md §2c); the rebuild's
multi-host story is ``jax.distributed`` bring-up + coordinator-only
artifact writes. Real DCN needs multiple processes, so these tests
exercise the seams: ``distributed_init`` dispatch, and that a
non-coordinator trainer process writes NO artifact files while still
training (checkpoint saves stay all-process for Orbax).
"""

import json
import os

import numpy as np
import pytest

from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.training.sl import SLTrainer

from tests.test_sl_trainer import small_cfg, small_net, write_dataset


@pytest.fixture()
def corpus(tmp_path):
    prefix = str(tmp_path / "data" / "corpus")
    os.makedirs(tmp_path / "data")
    write_dataset(prefix)
    return prefix


def test_distributed_init_noop_single_process(monkeypatch):
    calls = []
    monkeypatch.setattr(
        meshlib.jax.distributed, "initialize",
        lambda *a, **k: calls.append((a, k)))
    meshlib.distributed_init()          # no coordinator, 1 process
    assert calls == []


def test_distributed_init_dispatches_multiprocess(monkeypatch):
    calls = []
    monkeypatch.setattr(
        meshlib.jax.distributed, "initialize",
        lambda *a, **k: calls.append(k))
    # distributed_init selects gloo CPU collectives before a REAL
    # multi-process bring-up; with initialize mocked there is no
    # distributed client, and a leaked flag would break this process's
    # own (single-process) CPU backend creation — mask the capability
    # so this mocked dispatch never touches process-global jax config
    monkeypatch.setattr(
        meshlib, "cpu_collectives_available", lambda: False)
    meshlib.distributed_init(coordinator="host0:1234",
                             num_processes=2, process_id=1)
    assert calls and calls[0]["num_processes"] == 2
    calls.clear()
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    meshlib.distributed_init()          # env-driven pod bring-up
    assert len(calls) == 1


def test_coordinator_is_true_single_process():
    assert meshlib.is_coordinator()


def test_non_coordinator_writes_no_artifacts(corpus, tmp_path,
                                             monkeypatch):
    """A process with ``is_coordinator() == False`` must train (Orbax
    checkpoints land — every process participates in multi-host saves)
    but never touch metadata/metrics/weights/shuffle files."""
    monkeypatch.setattr(meshlib, "is_coordinator", lambda: False)
    out = tmp_path / "out"
    trainer = SLTrainer(small_cfg(corpus, out, epochs=1),
                        net=small_net())
    result = trainer.run()
    trainer.ckpt.close()
    assert result["step"] > 0
    assert not (out / "metadata.json").exists()
    assert not (out / "metrics.jsonl").exists()
    assert not (out / "shuffle.npz").exists()
    assert not (out / "model.json").exists()
    assert (out / "checkpoints").is_dir()
    assert os.listdir(out / "checkpoints")


def test_non_coordinator_split_matches_coordinator(corpus, tmp_path,
                                                   monkeypatch):
    """The shuffle split is a pure function of the seed, so a
    non-coordinator (which never reads or writes shuffle.npz on a cold
    start) computes the identical split."""
    out_a = tmp_path / "a"
    t_coord = SLTrainer(small_cfg(corpus, out_a, epochs=1),
                        net=small_net())
    monkeypatch.setattr(meshlib, "is_coordinator", lambda: False)
    out_b = tmp_path / "b"
    t_worker = SLTrainer(small_cfg(corpus, out_b, epochs=1),
                         net=small_net())
    np.testing.assert_array_equal(t_coord.train_idx, t_worker.train_idx)
    np.testing.assert_array_equal(t_coord.test_idx, t_worker.test_idx)
    t_coord.ckpt.close()
    t_worker.ckpt.close()


_NO_GLOO = pytest.mark.skipif(
    not meshlib.cpu_collectives_available(),
    reason="installed jaxlib ships no gloo CPU collectives — a "
           "2-process CPU bring-up fails at the first cross-process "
           "op with 'Multiprocess computations aren't implemented on "
           "the CPU backend'")


def _run_two_workers(tmp_path, mode=None, timeout=180):
    """Spawn coordinator + worker ``multihost_worker.py`` processes
    over a free loopback port; return their JSON results by pid."""
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:          # free loopback port
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "multihost_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""    # never claim the TPU tunnel
    env.pop("XLA_FLAGS", None)          # 1 real CPU device/process —
    # the parent's 8-virtual-device flag must not leak into children
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = [subprocess.Popen(
        [_sys.executable, worker, str(i), "2", str(port),
         str(tmp_path)] + ([mode] if mode else []),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, (out, err)
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return {o["process"]: o for o in outs}


@_NO_GLOO
def test_two_process_distributed_dp_step(tmp_path):
    """REAL 2-process ``jax.distributed`` bring-up (VERDICT r3 #8):
    localhost coordinator, CPU backend, one local device per process.
    Both processes must complete one data-parallel step, agree on the
    replicated result, and only the coordinator may write artifacts.
    ``distributed_init`` selects gloo TCP collectives on CPU (the
    default CPU client has no collectives transport at all), so this
    runs wherever the jaxlib ships gloo — capability-gated above."""
    by_pid = _run_two_workers(tmp_path)
    assert set(by_pid) == {0, 1}
    # the DP step saw the GLOBAL device set and agreed on the result
    assert all(o["n_global_devices"] == 2 for o in by_pid.values())
    assert by_pid[0]["loss"] == pytest.approx(by_pid[1]["loss"])
    assert by_pid[0]["w"] == by_pid[1]["w"]
    # coordinator-only artifact discipline held over real processes
    assert by_pid[0]["coordinator"] is True
    assert by_pid[1]["coordinator"] is False
    assert os.path.exists(tmp_path / "result.json")
    assert os.listdir(tmp_path) == ["result.json"]


@_NO_GLOO
@pytest.mark.slow
def test_two_process_sharded_learner_step(tmp_path):
    """One SHARDED zero learner step over real 2-process gloo DCN
    (the actor/learner split's consumer — docs/SCALE.md): both
    processes ingest the identical host-side game record, ``learn``
    commits it to its declared shardings (batch on ``data``, params
    replicated), and the replicated post-update params must be
    bit-consistent across hosts — the checksum and losses each
    process reports from its OWN addressable shards agree."""
    by_pid = _run_two_workers(tmp_path, mode="zero_learner",
                              timeout=300)

    assert set(by_pid) == {0, 1}
    assert all(o["n_global_devices"] == 2 for o in by_pid.values())
    # params consistent across hosts after the sharded update
    assert by_pid[0]["params_checksum"] == by_pid[1]["params_checksum"]
    assert by_pid[0]["policy_loss"] == by_pid[1]["policy_loss"]
    assert by_pid[0]["value_loss"] == by_pid[1]["value_loss"]
    # the artifact-write discipline holds in this mode too
    assert by_pid[0]["coordinator"] is True
    assert by_pid[1]["coordinator"] is False
    assert os.listdir(tmp_path) == ["result.json"]
