"""Multi-host wiring tests (single-process simulation).

The reference has no distributed layer (SURVEY.md §2c); the rebuild's
multi-host story is ``jax.distributed`` bring-up + coordinator-only
artifact writes. Real DCN needs multiple processes, so these tests
exercise the seams: ``distributed_init`` dispatch, and that a
non-coordinator trainer process writes NO artifact files while still
training (checkpoint saves stay all-process for Orbax).
"""

import json
import os

import numpy as np
import pytest

from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.training.sl import SLTrainer

from tests.test_sl_trainer import small_cfg, small_net, write_dataset


@pytest.fixture()
def corpus(tmp_path):
    prefix = str(tmp_path / "data" / "corpus")
    os.makedirs(tmp_path / "data")
    write_dataset(prefix)
    return prefix


def test_distributed_init_noop_single_process(monkeypatch):
    calls = []
    monkeypatch.setattr(
        meshlib.jax.distributed, "initialize",
        lambda *a, **k: calls.append((a, k)))
    meshlib.distributed_init()          # no coordinator, 1 process
    assert calls == []


def test_distributed_init_dispatches_multiprocess(monkeypatch):
    calls = []
    monkeypatch.setattr(
        meshlib.jax.distributed, "initialize",
        lambda *a, **k: calls.append(k))
    meshlib.distributed_init(coordinator="host0:1234",
                             num_processes=2, process_id=1)
    assert calls and calls[0]["num_processes"] == 2
    calls.clear()
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    meshlib.distributed_init()          # env-driven pod bring-up
    assert len(calls) == 1


def test_coordinator_is_true_single_process():
    assert meshlib.is_coordinator()


def test_non_coordinator_writes_no_artifacts(corpus, tmp_path,
                                             monkeypatch):
    """A process with ``is_coordinator() == False`` must train (Orbax
    checkpoints land — every process participates in multi-host saves)
    but never touch metadata/metrics/weights/shuffle files."""
    monkeypatch.setattr(meshlib, "is_coordinator", lambda: False)
    out = tmp_path / "out"
    trainer = SLTrainer(small_cfg(corpus, out, epochs=1),
                        net=small_net())
    result = trainer.run()
    trainer.ckpt.close()
    assert result["step"] > 0
    assert not (out / "metadata.json").exists()
    assert not (out / "metrics.jsonl").exists()
    assert not (out / "shuffle.npz").exists()
    assert not (out / "model.json").exists()
    assert (out / "checkpoints").is_dir()
    assert os.listdir(out / "checkpoints")


def test_non_coordinator_split_matches_coordinator(corpus, tmp_path,
                                                   monkeypatch):
    """The shuffle split is a pure function of the seed, so a
    non-coordinator (which never reads or writes shuffle.npz on a cold
    start) computes the identical split."""
    out_a = tmp_path / "a"
    t_coord = SLTrainer(small_cfg(corpus, out_a, epochs=1),
                        net=small_net())
    monkeypatch.setattr(meshlib, "is_coordinator", lambda: False)
    out_b = tmp_path / "b"
    t_worker = SLTrainer(small_cfg(corpus, out_b, epochs=1),
                         net=small_net())
    np.testing.assert_array_equal(t_coord.train_idx, t_worker.train_idx)
    np.testing.assert_array_equal(t_coord.test_idx, t_worker.test_idx)
    t_coord.ckpt.close()
    t_worker.ckpt.close()
