"""Worker process for the REAL 2-process ``jax.distributed`` test.

Not a test module — ``tests/test_multihost.py`` spawns two of these
(coordinator + worker) over localhost DCN loopback on the CPU
backend, each with ONE local device, and checks that a data-parallel
step runs globally: the batch is sharded across processes, XLA
inserts the gradient collective, and both processes converge on the
identical replicated result. The reference has no distributed layer
at all (SURVEY.md §2c); this is the rebuild's multi-host bring-up
path actually executing, not the mocked dispatch test above it.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
       <out_dir> [mode]

``mode`` selects the step (default ``dp``): ``dp`` is the original
data-parallel SGD step; ``zero_learner`` runs ONE sharded zero
learner step (``training/zero.py``'s ``learn`` half — the
actor/learner split's consumer) from a deterministic host-side game
record, and reports a params checksum both processes must agree on.

Prints one JSON line with the step result; writes ``result.json``
into <out_dir> ONLY on the coordinator (artifact-write discipline —
``mesh.is_coordinator``).
"""

import json
import os
import sys


def zero_learner_step(meshlib, mesh):
    """One sharded learner step over the GLOBAL mesh.

    The game record is built host-side, identical on every process —
    exactly what the replay buffer hands a learner (host numpy from
    an actor's ``device_get``). ``learn`` itself commits the arrays
    to its declared shardings, so this exercises the real multi-host
    ingest path: replicated params in, data-sharded batch, replicated
    params out, addressable on every process."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from rocalphago_tpu.data.replay import ZeroGames
    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.training.zero import (
        init_zero_state,
        make_zero_iteration,
    )

    board, batch, move_limit = 5, 2, 8
    feats = ("board", "ones")
    vfeats = feats + ("color",)
    pol = CNNPolicy(feats, board=board, layers=1, filters_per_layer=4)
    val = CNNValue(vfeats, board=board, layers=1, filters_per_layer=4)
    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    iteration = make_zero_iteration(
        GoConfig(size=board), feats, vfeats, pol.module.apply,
        val.module.apply, tx_p, tx_v, batch=batch,
        move_limit=move_limit, n_sim=2, max_nodes=8, sim_chunk=2,
        replay_chunk=4, mesh=mesh)
    state = meshlib.replicate(mesh, init_zero_state(
        pol.params, val.params, tx_p, tx_v, seed=0))

    n_act = board * board + 1
    rs = np.random.RandomState(7)
    live = np.zeros((move_limit, batch), bool)
    live[:6] = True
    games = ZeroGames(
        # pass is legal from any position, so the replayed actions
        # never depend on engine legality
        actions=np.full((move_limit, batch), n_act - 1, np.int32),
        live=live,
        visits=rs.randint(0, 5, (move_limit, batch, n_act))
        .astype(np.int32),
        winners=np.array([1, -1], np.int32),
        finished=np.ones((batch,), bool))

    state2, metrics = iteration.learn(state, games)
    leaves = (jax.tree.leaves(state2.policy_params)
              + jax.tree.leaves(state2.value_params))
    # replicated outputs are fully addressable on every process
    checksum = float(sum(float(jnp.sum(jnp.abs(x))) for x in leaves))
    return {
        "policy_loss": round(float(jax.device_get(
            metrics["policy_loss"])), 6),
        "value_loss": round(float(jax.device_get(
            metrics["value_loss"])), 6),
        "params_checksum": round(checksum, 5),
    }


def main() -> int:
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, out_dir = sys.argv[3], sys.argv[4]
    mode = sys.argv[5] if len(sys.argv) > 5 else "dp"

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.parallel import mesh as meshlib

    meshlib.distributed_init(coordinator=f"localhost:{port}",
                             num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    mesh = meshlib.make_mesh()          # all GLOBAL devices

    if mode == "zero_learner":
        result = zero_learner_step(meshlib, mesh)
        result.update({
            "process": pid,
            "coordinator": meshlib.is_coordinator(),
            "n_global_devices": len(jax.devices()),
        })
        if meshlib.is_coordinator():
            with open(os.path.join(out_dir, "result.json"), "w") as f:
                json.dump(result, f)
        print(json.dumps(result))
        return 0

    # deterministic global batch; each process owns its slice
    gshape = (4 * nproc, 3)
    global_x = np.arange(np.prod(gshape), dtype=np.float32) \
        .reshape(gshape) / 10.0
    local = global_x[pid * 4:(pid + 1) * 4]
    x = jax.make_array_from_process_local_data(
        meshlib.data_sharding(mesh, 2), local, global_shape=gshape)
    w = meshlib.replicate(mesh, jnp.ones((3,), jnp.float32))

    @jax.jit
    def dp_step(w, x):
        # data-parallel SGD: per-shard grads, XLA inserts the
        # cross-process mean reduction (the NCCL-allreduce analogue)
        loss, g = jax.value_and_grad(
            lambda w: jnp.mean((x @ w - 1.0) ** 2))(w)
        return w - 0.1 * g, loss

    w2, loss = dp_step(w, x)
    # replicated outputs are addressable on every process
    result = {
        "process": pid,
        "coordinator": meshlib.is_coordinator(),
        "loss": float(jax.device_get(loss)),
        "w": np.asarray(jax.device_get(w2)).round(6).tolist(),
        "n_global_devices": len(jax.devices()),
    }
    if meshlib.is_coordinator():
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(result, f)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
