"""Worker process for the REAL 2-process ``jax.distributed`` test.

Not a test module — ``tests/test_multihost.py`` spawns two of these
(coordinator + worker) over localhost DCN loopback on the CPU
backend, each with ONE local device, and checks that a data-parallel
step runs globally: the batch is sharded across processes, XLA
inserts the gradient collective, and both processes converge on the
identical replicated result. The reference has no distributed layer
at all (SURVEY.md §2c); this is the rebuild's multi-host bring-up
path actually executing, not the mocked dispatch test above it.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
       <out_dir>

Prints one JSON line with the step result; writes ``result.json``
into <out_dir> ONLY on the coordinator (artifact-write discipline —
``mesh.is_coordinator``).
"""

import json
import os
import sys


def main() -> int:
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, out_dir = sys.argv[3], sys.argv[4]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.parallel import mesh as meshlib

    meshlib.distributed_init(coordinator=f"localhost:{port}",
                             num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    mesh = meshlib.make_mesh()          # all GLOBAL devices

    # deterministic global batch; each process owns its slice
    gshape = (4 * nproc, 3)
    global_x = np.arange(np.prod(gshape), dtype=np.float32) \
        .reshape(gshape) / 10.0
    local = global_x[pid * 4:(pid + 1) * 4]
    x = jax.make_array_from_process_local_data(
        meshlib.data_sharding(mesh, 2), local, global_shape=gshape)
    w = meshlib.replicate(mesh, jnp.ones((3,), jnp.float32))

    @jax.jit
    def dp_step(w, x):
        # data-parallel SGD: per-shard grads, XLA inserts the
        # cross-process mean reduction (the NCCL-allreduce analogue)
        loss, g = jax.value_and_grad(
            lambda w: jnp.mean((x @ w - 1.0) ** 2))(w)
        return w - 0.1 * g, loss

    w2, loss = dp_step(w, x)
    # replicated outputs are addressable on every process
    result = {
        "process": pid,
        "coordinator": meshlib.is_coordinator(),
        "loss": float(jax.device_get(loss)),
        "w": np.asarray(jax.device_get(w2)).round(6).tolist(),
        "n_global_devices": len(jax.devices()),
    }
    if meshlib.is_coordinator():
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(result, f)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
