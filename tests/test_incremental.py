"""Incremental (delta) encode: trajectory-fuzz bit-identity.

The delta path (``features/incremental.py``) must NEVER be
"approximately" right: at every ply of any game, warm or cold cache,
``encode_step`` produces exactly the planes of the from-scratch
encoder. These tests pin that over randomized full-game trajectories
(multi-stone captures, ko, passes, game end), a curated ladder
opening (the planes whose chase verdicts the cache actually reuses),
arbitrary cross-game jumps (correctness must not depend on the cache
matching the position), the batched self-play carry, and the
``Preprocess.advance`` host-boundary entry — with the ``pyfeatures``
oracle as the independent check on the exactly-specified planes
(the ladder planes are a documented 2-ply approximation of the
oracle, so their independent anchor is the from-scratch device read
they must be bit-identical to).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.engine.jaxgo import GoConfig
from rocalphago_tpu.features import Preprocess, pyfeatures
from rocalphago_tpu.features import incremental as incr
from rocalphago_tpu.features import planes as jplanes

FULL = pyfeatures.DEFAULT_FEATURES
NON_LADDER = tuple(f for f in FULL if not f.startswith("ladder"))

# one compiled (encode_step, encode) pair per (size, features) shared
# across the whole module — the fuzz re-uses programs, not traces
_PROGRAMS: dict = {}


def programs(cfg: GoConfig, features=None):
    key = (cfg.size, cfg.komi, features)
    if key not in _PROGRAMS:
        _PROGRAMS[key] = (
            jax.jit(lambda s, c: incr.encode_step(
                cfg, s, c, features=features)),
            jax.jit(lambda s: jplanes.encode(cfg, s,
                                             features=features)),
        )
    return _PROGRAMS[key]


def plane_slices(features):
    out, off = {}, 0
    for f in features:
        k = pyfeatures.FEATURE_PLANES[f]
        out[f] = slice(off, off + k)
        off += k
    return out


def fuzz_trajectory(size, seed, plies, features=None, start=None,
                    oracle_every=0, pass_every=0):
    """Play one randomized game, delta-encoding every successive
    position against the carried cache and asserting bit-identity
    with the from-scratch encoder at every ply (plus the oracle on
    the exactly-specified planes at sampled plies). Returns the
    final cache for stat assertions."""
    cfg = GoConfig(size=size, komi=5.5)
    step_fn, full_fn = programs(cfg, features)
    cache = incr.init_cache(cfg)
    pst = start.copy() if start is not None else pygo.GameState(
        size=size, komi=5.5)
    rng = np.random.default_rng(seed)
    sl = plane_slices(features or FULL)
    checked = 0
    for i in range(plies):
        if pst.is_end_of_game:
            break
        moves = pst.get_legal_moves()
        if pass_every and i % pass_every == pass_every - 1:
            mv = None                     # pass mid-game
        elif not moves:
            mv = None
        else:
            mv = moves[rng.integers(len(moves))]
        pst.do_move(mv)
        jst = jaxgo.from_pygo(cfg, pst)
        got, cache = step_fn(jst, cache)
        want = full_fn(jst)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"delta vs from-scratch diverged at ply {i} "
                    f"(move {mv}):\nboard=\n{pst.board}")
        checked += 1
        if oracle_every and i % oracle_every == 2:
            feats = features or FULL
            ora = pyfeatures.state_to_planes(pst, feats)
            g = np.asarray(got)
            for name in feats:
                if name.startswith("ladder"):
                    continue   # documented approximation; anchored
                    # by the from-scratch bit-identity above
                np.testing.assert_array_equal(
                    g[:, :, sl[name]], ora[:, :, sl[name]],
                    err_msg=f"oracle plane {name} at ply {i}")
    assert checked >= min(plies, 10) // 2
    return cache


class TestTrajectoryParity:
    def test_dense_5x5_full_game_with_passes(self):
        """Small dense board: multi-stone captures, ko fights and
        forced passes all occur naturally; the game is fuzzed to its
        double-pass end and every ply must be bit-identical."""
        cache = fuzz_trajectory(5, seed=1, plies=70, oracle_every=5,
                                pass_every=11)
        stats = np.asarray(cache.stats)
        assert stats[incr.STAT_ENCODES] >= 30

    @pytest.mark.slow
    def test_capture_heavy_7x7(self):
        cache = fuzz_trajectory(7, seed=4, plies=40, oracle_every=9)
        # dense random play must actually have exercised the ladder
        # machinery (refreshes) — otherwise the fuzz proves little
        assert np.asarray(cache.stats)[incr.STAT_REFRESHED] > 0

    def test_ladder_opening_9x9(self):
        """From a curated working-ladder position (the shape whose
        chase verdicts the cache exists to reuse): random play on top
        of a live ladder churns candidates, chases and invalidations."""
        st = pygo.GameState(size=9, komi=5.5)
        st.do_move((1, 2), pygo.BLACK)
        st.do_move((2, 2), pygo.WHITE)
        st.do_move((2, 1), pygo.BLACK)
        st.do_move((8, 8), pygo.WHITE)
        st.do_move((3, 1), pygo.BLACK)
        st.current_player = pygo.BLACK
        cache = fuzz_trajectory(9, seed=7, plies=18, start=st)
        stats = np.asarray(cache.stats)
        assert stats[incr.STAT_CHASES] > 0

    def test_cross_game_jump_stays_exact(self):
        """Correctness must never depend on the cache matching the
        position: encode game A's trajectory, then — with the SAME
        warm cache, no reset — encode an unrelated game B position.
        Board-diff invalidation handles the jump."""
        cfg = GoConfig(size=5, komi=5.5)
        step_fn, full_fn = programs(cfg)
        cache = incr.init_cache(cfg)
        rng = np.random.default_rng(11)
        pst = pygo.GameState(size=5, komi=5.5)
        for _ in range(16):
            moves = pst.get_legal_moves()
            pst.do_move(moves[rng.integers(len(moves))])
            jst = jaxgo.from_pygo(cfg, pst)
            _, cache = step_fn(jst, cache)
        other = pygo.GameState(size=5, komi=5.5)
        for _ in range(9):
            moves = other.get_legal_moves()
            other.do_move(moves[rng.integers(len(moves))])
        jst = jaxgo.from_pygo(cfg, other)
        got, cache = step_fn(jst, cache)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(full_fn(jst)))

    @pytest.mark.slow
    def test_encode_delta_step_form(self):
        """The ``encode_delta(prev_state, cache, move)`` convenience
        (device-side step + encode) equals stepping on host and
        calling ``encode_step`` on the successor."""
        cfg = GoConfig(size=5, komi=5.5)
        step_fn, _ = programs(cfg)
        delta_fn = jax.jit(lambda s, c, m: incr.encode_delta(
            cfg, s, c, m))
        state = jaxgo.new_state(cfg)
        cache_a = incr.init_cache(cfg)
        cache_b = incr.init_cache(cfg)
        rng = np.random.default_rng(3)
        for _ in range(12):
            gd = jaxgo.group_data(cfg, state.board,
                                  with_zxor=cfg.enforce_superko,
                                  labels=state.labels)
            legal = np.asarray(
                jaxgo.legal_mask(cfg, state, gd))[:cfg.num_points]
            options = np.nonzero(legal)[0]
            mv = int(options[rng.integers(len(options))]) if len(
                options) else cfg.num_points
            planes_a, cache_a = delta_fn(state, cache_a,
                                         jnp.int32(mv))
            state = jaxgo.step(cfg, state, jnp.int32(mv))
            planes_b, cache_b = step_fn(state, cache_b)
            np.testing.assert_array_equal(np.asarray(planes_a),
                                          np.asarray(planes_b))


class TestBatchedCarry:
    @pytest.mark.slow
    def test_batched_delta_encoder_matches_batched_encoder(self):
        """The vmapped delta sibling must equal the one true batched
        encoder on every step of a batch of independent games."""
        cfg = GoConfig(size=5)
        batch = 4
        enc = jax.jit(jplanes.batched_encoder(cfg, FULL))
        denc = jax.jit(incr.batched_delta_encoder(cfg, FULL))
        states = jaxgo.new_states(cfg, batch)
        caches = incr.init_caches(cfg, batch)
        vstep = jax.jit(jax.vmap(lambda s, a: jaxgo.step(cfg, s, a)))
        rng = np.random.default_rng(17)
        for _ in range(6):
            want = enc(states)
            got, caches = denc(states, caches)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
            actions = jnp.asarray(
                rng.integers(0, cfg.num_points + 1, size=batch),
                jnp.int32)
            states = vstep(states, actions)

    @pytest.mark.slow
    def test_selfplay_incremental_bit_identical(self):
        """The fused self-play ply loop with the cache carried through
        the scan: same rng → exactly the same games, plus the chunked
        runner (device-resident donated carry across segments)."""
        from rocalphago_tpu.models import CNNPolicy
        from rocalphago_tpu.search.selfplay import make_selfplay_chunked

        cfg = GoConfig(size=5)
        net = CNNPolicy(board=5, layers=2, filters_per_layer=4)
        # from-scratch baseline rides the jitted CHUNKED runner (one
        # compiled 4-ply segment) rather than an eager play_games —
        # same results, a fraction of the tier-1 wall time
        base = make_selfplay_chunked(
            cfg, net.feature_list, net.module.apply, net.module.apply,
            4, 8, chunk=4, incremental=False, score_on_device=False)(
            net.params, net.params, jax.random.key(0))
        chunked = make_selfplay_chunked(
            cfg, net.feature_list, net.module.apply, net.module.apply,
            4, 8, chunk=4, incremental=True, score_on_device=False)
        res = chunked(net.params, net.params, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(res.actions),
                                      np.asarray(base.actions))
        np.testing.assert_array_equal(np.asarray(res.final.board),
                                      np.asarray(base.final.board))

    @pytest.mark.slow
    def test_play_games_incremental_bit_identical(self):
        """The monolithic (un-chunked) scan with the cache carry —
        the slow-tier sibling of the chunked identity above."""
        from rocalphago_tpu.models import CNNPolicy
        from rocalphago_tpu.search.selfplay import play_games

        cfg = GoConfig(size=5)
        net = CNNPolicy(board=5, layers=2, filters_per_layer=4)
        base = play_games(cfg, net.feature_list, net.module.apply,
                          net.params, net.module.apply, net.params,
                          jax.random.key(0), 4, 24,
                          incremental=False)
        on = play_games(cfg, net.feature_list, net.module.apply,
                        net.params, net.module.apply, net.params,
                        jax.random.key(0), 4, 24, incremental=True)
        np.testing.assert_array_equal(np.asarray(base.actions),
                                      np.asarray(on.actions))
        np.testing.assert_array_equal(np.asarray(base.final.board),
                                      np.asarray(on.final.board))


class TestPreprocessAdvance:
    def test_advance_parity_move_form_resets_and_counters(self):
        """One Preprocess, one compile set (tier-1 wall-time budget):
        ``advance`` matches ``state_to_tensor`` ply by ply, the
        ``move=`` form steps-and-encodes, ``reset_cache`` counts its
        reason exactly once per warm cache, and the delta/full
        counters flow the way the obs_report hit-rate line reads."""
        from rocalphago_tpu.obs import registry as obs_registry

        cfg = GoConfig(size=5, komi=5.5)
        pre = Preprocess(cfg=cfg)
        snap0 = obs_registry.REGISTRY.snapshot()["counters"]
        d0 = snap0.get("encode_delta_total", 0)
        f0 = snap0.get("encode_full_total", 0)
        pst = pygo.GameState(size=5, komi=5.5)
        rng = np.random.default_rng(23)
        plies = 8
        for i in range(plies):
            moves = pst.get_legal_moves()
            pst.do_move(moves[rng.integers(len(moves))])
            jst = jaxgo.from_pygo(cfg, pst)
            got = np.asarray(pre.advance(jst))
            want = np.asarray(pre.state_to_tensor(jst))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"ply {i}")
        # move= form: step on device and encode the successor
        got = np.asarray(pre.advance(jst, move=12))
        successor = jaxgo.step(cfg, jst, jnp.int32(12))
        want = np.asarray(pre.state_to_tensor(successor))
        np.testing.assert_array_equal(got, want)

        snap = obs_registry.REGISTRY.snapshot()["counters"]
        assert snap.get("encode_delta_total", 0) == d0 + plies + 1
        assert snap.get("encode_full_total", 0) == f0 + plies + 1

        key = 'encode_cache_resets_total{reason="undo"}'
        before = snap.get(key, 0)
        pre.reset_cache(reason="undo")
        after = obs_registry.REGISTRY.snapshot()["counters"].get(
            key, 0)
        assert after == before + 1
        assert pre._cache is None
        # resetting an already-cold cache counts nothing
        pre.reset_cache(reason="undo")
        assert obs_registry.REGISTRY.snapshot()["counters"].get(
            key, 0) == after

    def test_warm_advance_compiles_nothing(self):
        """Warm-path zero-compile smoke (the obs compile counters the
        issue asks for): after the first ``advance`` the delta program
        is compiled; every further ply must ride the jit cache."""
        from rocalphago_tpu.obs import registry as obs_registry

        cfg = GoConfig(size=5)
        pre = Preprocess(("board", "ladder_capture", "ladder_escape"),
                         cfg=cfg)
        state = jaxgo.new_state(cfg)
        key = 'jax_compiles_total{entry="encode.delta"}'
        pre.advance(state)
        before = obs_registry.REGISTRY.snapshot()["counters"].get(
            key, 0)
        assert before >= 1          # the cold call really was tracked
        for mv in (3, 8, 15):
            state = jaxgo.step(cfg, state, jnp.int32(mv))
            pre.advance(state)
        after = obs_registry.REGISTRY.snapshot()["counters"].get(
            key, 0)
        assert after == before      # warm plies: zero compile growth
        assert pre._delta_step.compiles == 1
        assert pre._delta_step.calls == 4

def _ladder_board_9x9():
    """A 9×9 position with a live working ladder (black chasing the
    white stone at (2,2) toward the far corner) AND a white group in
    atari at (4,3)-(4,4) — sitting inside the chase's read region, so
    capturing it churns exactly the cells the ladder verdicts read."""
    st = pygo.GameState(size=9, komi=5.5)
    st.do_move((1, 2), pygo.BLACK)
    st.do_move((2, 2), pygo.WHITE)      # the ladder prey
    st.do_move((2, 1), pygo.BLACK)
    st.do_move((8, 8), pygo.WHITE)
    st.do_move((3, 1), pygo.BLACK)
    # the sacrificial white group on the chase diagonal, one liberty
    # at (4, 5)
    st.do_move((4, 3), pygo.WHITE)
    st.do_move((3, 3), pygo.BLACK)
    st.do_move((4, 4), pygo.WHITE)
    st.do_move((3, 4), pygo.BLACK)
    st.do_move((8, 0), pygo.WHITE)
    st.do_move((5, 3), pygo.BLACK)
    st.do_move((0, 8), pygo.WHITE)
    st.do_move((5, 4), pygo.BLACK)
    st.do_move((8, 4), pygo.WHITE)
    st.do_move((4, 2), pygo.BLACK)
    st.current_player = pygo.BLACK
    return st


class TestInvalidationCascade:
    """The coarsened-key / record-board invalidation model
    (features/incremental.py "How invalidation works"): adversarial
    churn inside an active chase's read region, and the exactness of
    WHAT a footprint hit re-chases."""

    def test_ladder_heavy_adversarial_game(self):
        """Captures INSIDE the live chase's read region — the churn
        pattern the coarse region keys must not mis-classify: the
        capture at (4,5) deletes the two-stone white group the ladder
        verdicts read right past. Bit-identity at every ply is the
        wall; the stats prove the cascade actually fired (region hits
        that survived the cell test and invalidated entries)."""
        st = _ladder_board_9x9()
        cfg = GoConfig(size=9, komi=5.5)
        step_fn, full_fn = programs(cfg, None)
        cache = incr.init_cache(cfg)
        jst = jaxgo.from_pygo(cfg, st)
        got, cache = step_fn(jst, cache)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(full_fn(jst)))
        assert np.asarray(cache.stats)[incr.STAT_CHASES] > 0
        # the adversarial sequence: capture the in-region group, have
        # white replay into the hole (self-atari — legal), then GROW
        # the prey string itself, keeping the ladder alive throughout
        for mv, color in (((4, 5), pygo.BLACK), ((4, 4), pygo.WHITE),
                          ((6, 3), pygo.BLACK), ((3, 2), pygo.WHITE),
                          ((6, 5), pygo.BLACK)):
            st.do_move(mv, color)
            st.current_player = pygo.BLACK
            jst = jaxgo.from_pygo(cfg, st)
            got, cache = step_fn(jst, cache)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(full_fn(jst)),
                err_msg=f"delta diverged after adversarial {mv}")
        stats = np.asarray(cache.stats)
        assert stats[incr.STAT_FOOT_HITS] > 0
        assert stats[incr.STAT_INVALIDATED] > 0
        # then fuzz forward from the wreckage — the general wall
        fuzz_trajectory(9, seed=29, plies=12, start=st)

    def test_far_churn_does_not_invalidate(self):
        """The tightening's contract: stone churn OUTSIDE every
        recorded footprint must invalidate nothing — verdicts keep
        being reused, no entry dies, no chase re-runs beyond the new
        position's own fresh candidates.

        The churn points are chosen OUTSIDE the union of the recorded
        footprints, which is most of the board here: the lone W(8,8)
        corner stone is itself a two-liberty prey, and its chase
        footprints sweep diagonally corner to corner — so the
        far-CORNER cells a human would call "nowhere near the ladder"
        are exactly the cells the footprint guard must watch. The top
        edge away from both preys' ladder fans is genuinely outside."""
        cfg = GoConfig(size=9, komi=5.5)
        step_fn, full_fn = programs(cfg, None)
        cache = incr.init_cache(cfg)
        st = pygo.GameState(size=9, komi=5.5)
        st.do_move((1, 2), pygo.BLACK)
        st.do_move((2, 2), pygo.WHITE)
        st.do_move((2, 1), pygo.BLACK)
        st.do_move((8, 8), pygo.WHITE)
        st.do_move((3, 1), pygo.BLACK)
        st.current_player = pygo.BLACK
        jst = jaxgo.from_pygo(cfg, st)
        got, cache = step_fn(jst, cache)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(full_fn(jst)))
        before = np.asarray(cache.stats).copy()
        assert before[incr.STAT_CHASES] > 0
        # a top-edge exchange outside every recorded footprint (three
        # liberties each — neither stone spawns a chaseable lane).
        # (0,5) still shares a COARSE region with recorded footprint
        # cells, so this also exercises the two-tier path: region hit
        # -> exact cell test -> pass -> nothing invalidated.
        for mv, color in (((0, 5), pygo.WHITE), ((0, 7), pygo.BLACK)):
            st.do_move(mv, color)
            st.current_player = pygo.BLACK
            jst = jaxgo.from_pygo(cfg, st)
            got, cache = step_fn(jst, cache)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(full_fn(jst)))
        delta = np.asarray(cache.stats) - before
        assert delta[incr.STAT_INVALIDATED] == 0
        assert delta[incr.STAT_REUSED] > 0

    def test_verdict_flip_rechases_exactly_the_flipped_lanes(self):
        """A ladder-breaker INSIDE the chase footprint flips the
        recorded verdict: that lane must re-chase (the flip counter),
        and ONLY affected entries die — the far corner of the cache
        stays live and reused."""
        cfg = GoConfig(size=9, komi=5.5)
        step_fn, full_fn = programs(cfg, None)
        cache = incr.init_cache(cfg)
        st = pygo.GameState(size=9, komi=5.5)
        st.do_move((1, 2), pygo.BLACK)
        st.do_move((2, 2), pygo.WHITE)
        st.do_move((2, 1), pygo.BLACK)
        st.do_move((8, 8), pygo.WHITE)
        st.do_move((3, 1), pygo.BLACK)
        st.current_player = pygo.BLACK
        jst = jaxgo.from_pygo(cfg, st)
        got, cache = step_fn(jst, cache)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(full_fn(jst)))
        before = np.asarray(cache.stats).copy()
        # the breaker: a white stone on the escape diagonal turns the
        # working ladder into a failing one — the verdict FLIPS
        st.do_move((5, 5), pygo.WHITE)
        st.current_player = pygo.BLACK
        jst = jaxgo.from_pygo(cfg, st)
        got, cache = step_fn(jst, cache)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(full_fn(jst)))
        delta = np.asarray(cache.stats) - before
        assert delta[incr.STAT_FOOT_HITS] > 0
        assert delta[incr.STAT_INVALIDATED] > 0
        assert delta[incr.STAT_FLIPS] > 0
        assert delta[incr.STAT_CHASES] >= delta[incr.STAT_FLIPS]

    def test_wide_footprint_fallback_bit_identical(self, monkeypatch):
        """ROCALPHAGO_LADDER_FOOT=wide (the legacy dilate⁴ blanket)
        stays available as the A/B lever — and stays bit-identical."""
        monkeypatch.setenv("ROCALPHAGO_LADDER_FOOT", "wide")
        cfg = GoConfig(size=7, komi=5.5)
        # fresh programs: the knob is read at trace time
        step_fn = jax.jit(lambda s, c: incr.encode_step(cfg, s, c))
        full_fn = jax.jit(lambda s: jplanes.encode(cfg, s))
        cache = incr.init_cache(cfg)
        pst = pygo.GameState(size=7, komi=5.5)
        rng = np.random.default_rng(31)
        for i in range(14):
            moves = pst.get_legal_moves()
            if not moves:
                break
            pst.do_move(moves[rng.integers(len(moves))])
            jst = jaxgo.from_pygo(cfg, pst)
            got, cache = step_fn(jst, cache)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(full_fn(jst)),
                err_msg=f"wide-footprint delta diverged at ply {i}")


@pytest.mark.slow
def test_long_fuzz_9x9_bit_identity():
    """Longer 9×9 trajectory (the ladder-rich board size) with passes
    — the slow-tier safety net behind the fast fuzzes above."""
    fuzz_trajectory(9, seed=2, plies=60, oracle_every=12,
                    pass_every=17)
