"""REINFORCE trainer: gradient correctness, loop behavior, resume.

The reference's RL trainer test runs a handful of lockstep games on a
tiny model and asserts completion + written weights (SURVEY.md §4
"Trainer smoke tests"). Here additionally the replay-accumulated
policy gradient is checked against a direct ``jax.grad`` of the whole
replayed log-likelihood — the rebuild's scan-with-per-ply-grads must
be exactly the REINFORCE gradient, not an approximation of it.
"""

import dataclasses
import functools
import json
import os

import jax
import jax.flatten_util  # noqa: F401 — used as jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.features.planes import encode
from rocalphago_tpu.models import CNNPolicy
from rocalphago_tpu.search.selfplay import play_games, sensible_mask
from rocalphago_tpu.training.rl import (
    RLConfig,
    RLState,
    RLTrainer,
    make_rl_iteration,
    make_rl_iteration_chunked,
)
from rocalphago_tpu.io.checkpoint import pack_rng

SIZE = 5
FEATURES = ("board", "ones")
BATCH = 4
MOVES = 10
TEMP = 0.67


@pytest.fixture(scope="module")
def net():
    return CNNPolicy(FEATURES, board=SIZE, layers=2, filters_per_layer=4)


@pytest.mark.slow
def test_replay_gradient_matches_direct_grad(net):
    """(params_old - params_new)/lr from the iteration must equal
    jax.grad of the directly-written REINFORCE objective. Run in
    float32 (bf16 kernels fuse differently between the scan and the
    unrolled reference, adding ~1% noise that would mask real bugs)."""
    from rocalphago_tpu.models.policy import PolicyNet

    cfg = jaxgo.GoConfig(size=SIZE)
    module = PolicyNet(board=SIZE,
                       input_planes=net.preprocess.output_dim,
                       layers=2, filters_per_layer=4,
                       dtype=jnp.float32)
    params = module.init(
        jax.random.key(0),
        jnp.zeros((1, SIZE, SIZE, net.preprocess.output_dim)))
    lr = 0.1
    tx = optax.sgd(lr)
    iteration = make_rl_iteration(cfg, FEATURES, module.apply, tx,
                                  BATCH, MOVES, TEMP)
    key = jax.random.key(3)
    state0 = RLState(params, tx.init(params), jnp.int32(0),
                     pack_rng(key))
    new_state, metrics = jax.jit(iteration)(state0, params)

    # reproduce the games the iteration played (same key split)
    game_key = jax.random.split(key)[1]
    result = play_games(cfg, FEATURES, module.apply, params,
                        module.apply, params, game_key, BATCH,
                        MOVES, TEMP)
    actions = np.asarray(result.actions)
    live = np.asarray(result.live)
    winners = np.asarray(result.winners).astype(np.float32)
    half = BATCH // 2
    z = np.concatenate([winners[:half], -winners[half:]])
    n = cfg.num_points

    enc = jax.vmap(functools.partial(encode, cfg, features=FEATURES))
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(jaxgo.step, cfg))

    # precompute the replayed states/masks outside the loss (they do
    # not depend on params)
    states = jaxgo.new_states(cfg, BATCH)
    planes_seq, sens_seq = [], []
    for t in range(MOVES):
        planes_seq.append(enc(states))
        sens_seq.append(np.asarray(vsens(states)))
        states = vstep(states, jnp.asarray(actions[t]))

    def direct_loss(p):
        total = 0.0
        for t in range(MOVES):
            start = 0 if t % 2 == 0 else half
            sel = slice(start, start + half)
            w = (z[sel] * live[t, sel]
                 * (actions[t, sel] < n).astype(np.float32))
            logits = module.apply(p, planes_seq[t][sel])
            neg = jnp.finfo(logits.dtype).min
            masked = jnp.where(jnp.asarray(sens_seq[t][sel]),
                               logits / TEMP, neg)
            logp = jax.nn.log_softmax(masked, axis=-1)
            a = jnp.minimum(jnp.asarray(actions[t, sel]), n - 1)
            lp = jnp.take_along_axis(logp, a[:, None], axis=1)[:, 0]
            total = total - (jnp.asarray(w) * lp).sum() / BATCH
        return total

    grads_ref = jax.grad(direct_loss)(params)
    grads_got = jax.tree.map(lambda a, b: (a - b) / lr,
                             params, new_state.params)
    flat_ref, _ = jax.flatten_util.ravel_pytree(grads_ref)
    flat_got, _ = jax.flatten_util.ravel_pytree(grads_got)
    np.testing.assert_allclose(np.asarray(flat_got),
                               np.asarray(flat_ref),
                               rtol=1e-3, atol=1e-5)
    assert 0.0 <= float(metrics["win_rate"]) <= 1.0


@pytest.mark.slow
def test_chunked_iteration_is_bit_identical(net):
    """The watchdog-safe chunked iteration (game segments + replay
    segments driven from host) must produce EXACTLY the monolithic
    iteration's params, opt state and metrics — same per-ply op order,
    same gradient accumulation order, same rng chain."""
    cfg = jaxgo.GoConfig(size=SIZE)
    tx = optax.sgd(0.1)
    mono = jax.jit(make_rl_iteration(
        cfg, FEATURES, net.module.apply, tx, BATCH, MOVES, TEMP))
    chunked = make_rl_iteration_chunked(
        cfg, FEATURES, net.module.apply, tx, BATCH, MOVES, TEMP,
        chunk=3)   # deliberately not a divisor of MOVES (remainder seg)
    state0 = RLState(net.params, tx.init(net.params), jnp.int32(0),
                     pack_rng(jax.random.key(7)))
    got_m, metrics_m = mono(state0, net.params)
    got_c, metrics_c = chunked(state0, net.params)

    flat_m, _ = jax.flatten_util.ravel_pytree(
        jax.device_get(got_m.params))
    flat_c, _ = jax.flatten_util.ravel_pytree(
        jax.device_get(got_c.params))
    np.testing.assert_array_equal(np.asarray(flat_m),
                                  np.asarray(flat_c))
    np.testing.assert_array_equal(np.asarray(got_m.rng),
                                  np.asarray(got_c.rng))
    for k in metrics_m:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(metrics_m[k])),
            np.asarray(jax.device_get(metrics_c[k])), err_msg=k)


@pytest.mark.slow
def test_chunked_iteration_sharded_matches_unsharded(net):
    """The chunked iteration with the game batch sharded over the
    8-virtual-device mesh's data axis must match the unsharded chunked
    iteration — environment parallelism across devices changes the
    placement, not the math."""
    cfg = jaxgo.GoConfig(size=SIZE)
    tx = optax.sgd(0.1)
    from rocalphago_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2)
    plain = make_rl_iteration_chunked(
        cfg, FEATURES, net.module.apply, tx, BATCH, MOVES, TEMP,
        chunk=4)
    sharded = make_rl_iteration_chunked(
        cfg, FEATURES, net.module.apply, tx, BATCH, MOVES, TEMP,
        chunk=4, mesh=mesh)
    state0 = RLState(net.params, tx.init(net.params), jnp.int32(0),
                     pack_rng(jax.random.key(11)))
    got_p, metrics_p = plain(state0, net.params)
    got_s, metrics_s = sharded(state0, net.params)

    flat_p, _ = jax.flatten_util.ravel_pytree(
        jax.device_get(got_p.params))
    flat_s, _ = jax.flatten_util.ravel_pytree(
        jax.device_get(got_s.params))
    np.testing.assert_allclose(np.asarray(flat_p), np.asarray(flat_s),
                               rtol=1e-6, atol=1e-7)
    for k in metrics_p:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(metrics_p[k])),
            np.asarray(jax.device_get(metrics_s[k])),
            rtol=1e-6, err_msg=k)


@pytest.fixture()
def no_persistent_compile_cache():
    """Disable the suite's persistent XLA compile cache for tests
    whose program this machine's cache round-trips INCORRECTLY.

    On the pinned toolchain (jaxlib 0.4.36 CPU), the RL iteration
    executable comes back from the persistent compilation cache
    producing exactly-zero parameter updates: a COLD cache run of
    ``test_rl_trainer_runs_and_saves`` passes and writes the entry,
    and the immediately following warm run fails — same code, same
    seeds. The trainer's correctness is pinned by the gradient and
    bit-identity tests either way; this fixture only takes the broken
    serialization round-trip out of the loop for the loop-behavior
    tests it corrupts (a fresh compile costs ~3s here)."""
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    # the flag alone is NOT enough: the cache object initializes once
    # per process at the first compile and later get/put calls use it
    # without re-checking the flag — reset so the next (in-test)
    # compile re-initializes under the disabled flag, and again on
    # teardown so the rest of the suite gets its cache back
    _cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    _cc.reset_cache()


def make_trainer(tmp_path, net, iterations=2, save_every=1):
    cfg = RLConfig(out_dir=str(tmp_path / "rl"), learning_rate=0.01,
                   game_batch=BATCH, iterations=iterations,
                   save_every=save_every, policy_temp=TEMP,
                   move_limit=MOVES, seed=0, num_devices=2)
    fresh = CNNPolicy(FEATURES, board=SIZE, layers=2,
                      filters_per_layer=4)
    fresh.params = jax.device_get(net.params)
    return RLTrainer(cfg, net=fresh)


def test_rl_trainer_runs_and_saves(tmp_path, net,
                                   no_persistent_compile_cache):
    trainer = make_trainer(tmp_path, net)
    before = jax.device_get(trainer.state.params)
    final = trainer.run()
    after = jax.device_get(trainer.state.params)
    assert final["iteration"] == 1
    assert 0.0 <= final["win_rate"] <= 1.0
    diff = jax.tree.map(lambda a, b: float(np.abs(a - b).max()),
                        before, after)
    assert max(jax.tree.leaves(diff)) > 0  # params actually moved

    out = trainer.cfg.out_dir
    with open(os.path.join(out, "metadata.json")) as f:
        meta = json.load(f)
    assert len(meta["epochs"]) == 2
    # initial snapshot + one per save_every=1 iteration
    assert len(trainer.pool.snapshots()) == 3
    assert os.path.exists(os.path.join(out, "weights.00002.flax.msgpack"))


def test_rl_trainer_resumes(tmp_path, net,
                            no_persistent_compile_cache):
    trainer = make_trainer(tmp_path, net, iterations=2)
    trainer.run()
    trainer.ckpt.close()
    # a fresh trainer over the same out_dir must resume, not restart
    resumed = make_trainer(tmp_path, net, iterations=3)
    assert resumed.start_iteration == 2
    final = resumed.run()
    assert final["iteration"] == 2
    with open(os.path.join(resumed.cfg.out_dir, "metadata.json")) as f:
        meta = json.load(f)
    assert [e["iteration"] for e in meta["epochs"]] == [0, 1, 2]
