"""SGF parsing, corpus conversion and input-pipeline tests (reference
strategy: ``tests/test_game_converter.py``, SURVEY.md §4)."""

import glob
import json
import os

import numpy as np
import pytest

from rocalphago_tpu.data import pipeline, sgf as sgflib
from rocalphago_tpu.data.convert import GameConverter
from rocalphago_tpu.engine import pygo

DATA = os.path.join(os.path.dirname(__file__), "test_data")
FEATURES = ("board", "ones", "turns_since", "liberties", "sensibleness")


class TestSGF:
    def test_parse_roundtrip(self):
        text = open(os.path.join(DATA, "game0.sgf")).read()
        g = sgflib.parse(text)
        assert g.size == 9 and g.komi == 5.5
        assert g.result == "W+R" and g.winner == pygo.WHITE
        assert len(g.moves) >= 30
        # render → parse → identical moves
        g2 = sgflib.parse(sgflib.render(g))
        assert g2.moves == g.moves
        assert g2.size == g.size

    def test_render_escapes_property_values(self):
        g = sgflib.from_moves(5, 5.5, [(pygo.BLACK, (2, 2))], "B+R")
        g.properties["PB"] = "net]weird\\name"
        g2 = sgflib.parse(sgflib.render(g))
        assert g2.properties["PB"] == "net]weird\\name"
        assert g2.moves == g.moves

    def test_render_keeps_move_comments_out_of_root(self):
        text = ("(;GM[1]FF[4]SZ[5]KM[5.5]RE[B+R]"
                ";B[cc]C[a move comment];W[dd])")
        g = sgflib.parse(text)
        rendered = sgflib.render(g)
        assert "a move comment" not in rendered  # not relocated to root
        assert sgflib.parse(rendered).moves == g.moves

    def test_replay_yields_states_before_moves(self):
        g = sgflib.parse(open(os.path.join(DATA, "game0.sgf")).read())
        steps = 0
        for st, move, player in sgflib.replay(g):
            assert st.current_player == player
            assert st.board[move] == 0
            steps += 1
        assert steps == len(g.moves)

    def test_handicap_replay(self):
        g = sgflib.parse(open(os.path.join(DATA, "handicap.sgf")).read())
        assert g.setup_black == [(2, 2), (6, 6)]
        first = next(iter(sgflib.replay(g)))
        st, move, player = first
        assert st.board[2, 2] == pygo.BLACK
        assert player == pygo.WHITE  # white moves first after handicap

    def test_variation_keeps_main_line(self):
        # first child subtree is the main line; the second is a variation
        g = sgflib.parse(
            "(;GM[1]SZ[9];B[aa](;W[bb];B[cc];W[dd])(;W[ee]))")
        assert [m for _, m in g.moves] == [(0, 0), (1, 1), (2, 2), (3, 3)]

    def test_free_setup_ages_and_turn(self):
        g = sgflib.parse("(;GM[1]SZ[9]AB[cc]AW[gg];W[dd];B[ee])")
        it = sgflib.replay(g)
        st, move, player = next(it)
        assert st.board[2, 2] == pygo.BLACK
        assert st.board[6, 6] == pygo.WHITE
        assert st.stone_ages[2, 2] == 0 and st.stone_ages[6, 6] == 0
        assert player == pygo.WHITE and st.current_player == pygo.WHITE

    def test_pass_and_bad_points(self):
        g = sgflib.parse("(;GM[1]SZ[9];B[dd];W[];B[tt])")
        assert g.moves[1] == (pygo.WHITE, None)
        assert g.moves[2] == (pygo.BLACK, None)
        with pytest.raises(sgflib.SGFError):
            sgflib.parse("(;GM[1]SZ[9];B[zz])")
        with pytest.raises(sgflib.SGFError):
            sgflib.parse("hello world")


class TestConverter:
    @pytest.fixture(scope="class")
    def conv(self):
        return GameConverter(FEATURES, board_size=9)

    def test_convert_game_shapes(self, conv):
        text = open(os.path.join(DATA, "game0.sgf")).read()
        states, actions = conv.convert_game(text)
        g = sgflib.parse(text)
        n_board_moves = sum(1 for _, m in g.moves if m is not None)
        assert states.shape == (n_board_moves, 9, 9, conv.pre.output_dim)
        assert states.dtype == np.uint8
        assert actions.shape == (n_board_moves,)
        assert (actions >= 0).all() and (actions < 81).all()
        # first position: empty board, black to move, action = first move
        first = g.moves[0][1]
        assert actions[0] == first[0] * 9 + first[1]
        assert states[0, :, :, 0].sum() == 0  # no own stones yet

    def test_sgfs_to_shards_skips_corrupt(self, conv, tmp_path):
        files = sorted(glob.glob(os.path.join(DATA, "*.sgf")))
        prefix = str(tmp_path / "corpus")
        with pytest.warns(UserWarning):
            manifest = conv.sgfs_to_shards(files, prefix, shard_size=64)
        assert manifest["num_games"] == 5  # 4 games + handicap
        assert len(manifest["errors"]) == 2  # corrupt + notsgf
        assert manifest["num_positions"] == sum(manifest["shard_counts"])
        assert manifest["num_shards"] == len(
            glob.glob(prefix + "-*.npz"))

    def test_hdf5_roundtrip(self, conv, tmp_path):
        files = [os.path.join(DATA, "game0.sgf")]
        out = str(tmp_path / "corpus.h5")
        n = conv.sgfs_to_hdf5(files, out)
        states, actions = pipeline.load_hdf5(out)
        direct_s, direct_a = conv.convert_game(open(files[0]).read())
        assert states.shape == direct_s.shape  # NHWC after reader
        assert np.array_equal(states, direct_s)
        assert np.array_equal(actions, direct_a)
        assert n == len(actions)


class TestPipeline:
    @pytest.fixture(scope="class")
    def dataset(self, tmp_path_factory):
        conv = GameConverter(FEATURES, board_size=9)
        files = sorted(glob.glob(os.path.join(DATA, "game*.sgf")))
        prefix = str(tmp_path_factory.mktemp("ds") / "corpus")
        conv.sgfs_to_shards(files, prefix, shard_size=50)
        return pipeline.ShardedDataset(prefix)

    def test_gather_cross_shard(self, dataset):
        assert dataset.manifest["num_shards"] >= 2
        idx = np.array([0, 1, len(dataset) - 1, len(dataset) // 2])
        states, actions = dataset.gather(idx)
        assert states.shape[0] == 4 and actions.shape == (4,)
        # gather respects order: re-gather reversed
        s2, a2 = dataset.gather(idx[::-1])
        assert np.array_equal(a2, actions[::-1])
        assert np.array_equal(s2, states[::-1])

    def test_split_indices_persist(self, dataset, tmp_path):
        path = str(tmp_path / "shuffle.npz")
        tr, va, te = pipeline.split_indices(len(dataset), seed=1, path=path)
        assert len(tr) + len(va) + len(te) == len(dataset)
        assert len(np.intersect1d(tr, va)) == 0
        tr2, va2, te2 = pipeline.split_indices(len(dataset), seed=999,
                                               path=path)
        assert np.array_equal(tr, tr2)  # resumed from file, seed ignored

    def test_split_rejects_size_mismatch(self, dataset, tmp_path):
        path = str(tmp_path / "shuffle.npz")
        pipeline.split_indices(len(dataset), seed=1, path=path)
        with pytest.raises(ValueError, match="corpus changed"):
            pipeline.split_indices(len(dataset) + 5, seed=1, path=path)

    def test_prefetch_propagates_worker_error(self):
        def bad_iter():
            yield (np.zeros(1), np.zeros(1))
            raise OSError("shard vanished")
        it = pipeline.device_prefetch(bad_iter())
        next(it)
        with pytest.raises(OSError, match="shard vanished"):
            next(it)

    def test_prefetch_early_close_releases_worker(self, dataset):
        rng = np.random.default_rng(0)
        idx = np.arange(len(dataset))
        it = pipeline.device_prefetch(
            pipeline.batch_iterator(dataset, idx, 8, rng))  # infinite
        next(it)
        it.close()  # must not deadlock the worker
        import threading
        import time
        time.sleep(0.3)
        workers = [t for t in threading.enumerate()
                   if t.name.startswith("Thread-") and t.is_alive()]
        # the worker either exited or is about to (stop flag set);
        # closing again is a no-op
        it.close()

    def test_batch_iterator_and_prefetch(self, dataset):
        rng = np.random.default_rng(0)
        idx = np.arange(len(dataset))
        it = pipeline.batch_iterator(dataset, idx, 16, rng, epochs=1)
        batches = list(pipeline.device_prefetch(it))
        assert len(batches) == len(dataset) // 16
        s, a = batches[0]
        assert s.shape == (16, 9, 9, dataset.planes)
        import jax
        assert isinstance(s, jax.Array)
