"""Native C++ replayer: exact parity with the pygo oracle.

The rebuild's native component (SURVEY.md §2a): rules bookkeeping for
corpus conversion in C++, validated move-for-move against
``engine.pygo`` on random games — the same oracle strategy the
vectorized device engine is tested with.
"""

import numpy as np
import pytest

from rocalphago_tpu.data import native
from rocalphago_tpu.data.convert import GameConverter
from rocalphago_tpu.engine import pygo

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain")


def random_game(size, seed, plies=50):
    rng = np.random.default_rng(seed)
    st = pygo.GameState(size=size, komi=5.5)
    moves, colors, snaps = [], [], []
    for _ in range(plies):
        legal = st.get_legal_moves(include_eyes=True)
        snaps.append((
            np.asarray(st.board, np.int8).reshape(-1).copy(),
            st.current_player,
            -1 if st.ko is None else st.ko[0] * size + st.ko[1],
            st.turns_played,
            np.asarray(st.stone_ages, np.int32).reshape(-1).copy()))
        mv = None if not legal or rng.random() < 0.05 \
            else legal[rng.integers(len(legal))]
        moves.append(size * size if mv is None
                     else mv[0] * size + mv[1])
        colors.append(st.current_player)
        st.do_move(mv)
        if st.is_end_of_game:
            break
    return moves[:len(snaps)], colors[:len(snaps)], snaps


@pytest.mark.parametrize("size", [5, 9])
def test_exact_parity_with_pygo(size):
    for seed in range(10):
        moves, colors, snaps = random_game(size, seed)
        boards, to_move, kos, steps, ages = native.replay_arrays(
            size, [], [], moves, colors)
        for t, (b, p, ko, s, ag) in enumerate(snaps):
            assert (boards[t] == b).all()
            assert to_move[t] == p
            assert kos[t] == ko
            assert steps[t] == s
            assert (ages[t] == ag).all()


def test_illegal_move_reports_ply():
    with pytest.raises(native.IllegalReplay) as e:
        native.replay_arrays(5, [], [], [12, 12], [1, -1])
    assert e.value.ply == 1


def test_handicap_setup_matches_pygo():
    size = 9
    pts = [(2, 2), (6, 6)]
    st = pygo.GameState(size=size)
    st.place_handicaps(pts)
    st.do_move((4, 4))  # white (handicap passes turn to white)
    boards, to_move, _, steps, ages = native.replay_arrays(
        size, [p[0] * size + p[1] for p in pts], [],
        [4 * size + 4], [pygo.WHITE])
    assert to_move[0] == pygo.WHITE
    for p in pts:
        assert boards[0][p[0] * size + p[1]] == pygo.BLACK
        assert ages[0][p[0] * size + p[1]] == 0


def test_converter_native_path_matches_pure(monkeypatch, tmp_path):
    """convert_game must produce identical tensors with and without
    the native replayer."""
    from rocalphago_tpu.data import sgf as sgflib

    moves, colors, _ = random_game(9, seed=3, plies=40)
    game = sgflib.from_moves(
        9, 5.5, [(c, None if m == 81 else divmod(m, 9))
                 for c, m in zip(colors, moves)])
    text = sgflib.render(game)

    conv = GameConverter(("board", "ones", "turns_since", "liberties"),
                         board_size=9)
    s_native, a_native = conv.convert_game(text)
    monkeypatch.setattr(native, "available", lambda: False)
    s_pure, a_pure = conv.convert_game(text)
    np.testing.assert_array_equal(a_native, a_pure)
    np.testing.assert_array_equal(s_native, s_pure)
