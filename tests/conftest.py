"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §4 "multi-node
testing") so data-parallel training, collectives, and shardings are
exercised in CI without TPU hardware.

Note: env vars alone are not enough here — the machine's sitecustomize
registers a TPU PJRT plugin at interpreter start and pins
``jax_platforms``, so we also override the config after import (safe:
backends initialize lazily, at the first ``jax.devices()`` call, which
has not happened yet at conftest-import time).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_CHECKS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache (same knob bench.py uses): repeat suite
# runs skip recompiling the expensive trainer/self-play programs, which
# dominate suite wall-time (VERDICT r2 weak #4)
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.expanduser("~/.cache/jax_comp_cache_tests"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
except Exception:  # noqa: BLE001 — older jax without the knobs
    pass
