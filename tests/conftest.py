"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §4 "multi-node
testing") so data-parallel training, collectives, and shardings are
exercised in CI without TPU hardware.

Note: env vars alone are not enough here — the machine's sitecustomize
registers a TPU PJRT plugin at interpreter start and pins
``jax_platforms``, so we also override the config after import (safe:
backends initialize lazily, at the first ``jax.devices()`` call, which
has not happened yet at conftest-import time).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_CHECKS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache (same knob bench.py uses): repeat suite
# runs skip recompiling the expensive trainer/self-play programs, which
# dominate suite wall-time (VERDICT r2 weak #4).
#
# The cache directory is VERSIONED by the jax/jaxlib pair and the
# virtual-device topology: a legacy unversioned directory on this
# machine served a poisoned executable for the RL iteration program
# (deterministically zeroed updates — `test_rl_trainer_runs_and_saves`
# failed with the old directory and passes with a fresh one, same
# code), and suite runs here are routinely killed by driver timeouts,
# which can tear in-flight cache writes. Versioned directories never
# inherit entries written by another toolchain/topology, and
# `ROCALPHAGO_TEST_COMPILE_CACHE=0` disables the cache entirely when a
# poisoned entry is suspected (wipe the directory to recover).
if os.environ.get("ROCALPHAGO_TEST_COMPILE_CACHE", "1") != "0":
    try:
        import jaxlib

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser(
                "~/.cache/jax_comp_cache_tests/"
                f"jax{jax.__version__}-jaxlib{jaxlib.__version__}-d8"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # noqa: BLE001 — older jax without the knobs
        pass
