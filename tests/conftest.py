"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §4 "multi-node
testing") so data-parallel training, collectives, and shardings are
exercised in CI without TPU hardware.

Note: env vars alone are not enough here — the machine's sitecustomize
registers a TPU PJRT plugin at interpreter start and pins
``jax_platforms``, so we also override the config after import (safe:
backends initialize lazily, at the first ``jax.devices()`` call, which
has not happened yet at conftest-import time).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_CHECKS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
