"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §4 "multi-node
testing") so data-parallel training, collectives, and shardings are
exercised in CI without TPU hardware. Must run before ``import jax``,
hence the env mutation at module import time (pytest imports conftest
before test modules).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_CHECKS", "1")
