"""MCTS tree mechanics with injected fake backends.

The reference tests ``mcts.py`` entirely with plain Python lambdas as
the policy/value/rollout functions (SURVEY.md §4 "MCTS tests") — no NN
involved. Same here, for both the sequential ``MCTS`` and the batched
``ParallelMCTS``, plus an end-to-end ``MCTSPlayer`` smoke test over
tiny real nets.
"""

import numpy as np
import pytest

from rocalphago_tpu.engine import pygo
from rocalphago_tpu.models import CNNPolicy, CNNRollout, CNNValue
from rocalphago_tpu.search.mcts import (
    MCTS,
    MCTSPlayer,
    ParallelMCTS,
    TreeNode,
    device_rollout_fn,
    net_backends,
)

SIZE = 5


def uniform_priors(state):
    moves = state.get_legal_moves(include_eyes=False)
    return [(m, 1.0 / len(moves)) for m in moves] if moves else []


def constant_value(_state):
    return 0.2


def batch(fn):
    return lambda states: [fn(s) for s in states]


# ------------------------------------------------------------- TreeNode


class TestTreeNode:
    def test_expand_and_select(self):
        root = TreeNode(None, 1.0)
        root.expand([((0, 0), 0.7), ((1, 1), 0.3)])
        assert set(root._children) == {(0, 0), (1, 1)}
        move, child = root.select(c_puct=5.0)
        assert move == (0, 0)  # higher prior wins before any visits
        assert child._P == pytest.approx(0.7)

    def test_update_running_mean(self):
        node = TreeNode(None, 1.0)
        node.update(1.0)
        node.update(0.0)
        assert node._n_visits == 2
        assert node._Q == pytest.approx(0.5)

    def test_update_recursive_alternates_sign(self):
        root = TreeNode(None, 1.0)
        root.expand([((0, 0), 1.0)])
        child = root._children[(0, 0)]
        child.expand([((1, 1), 1.0)])
        leaf = child._children[(1, 1)]
        leaf.update_recursive(1.0)
        assert leaf._Q == pytest.approx(1.0)
        assert child._Q == pytest.approx(-1.0)
        assert root._Q == pytest.approx(1.0)

    def test_visits_shift_selection(self):
        root = TreeNode(None, 1.0)
        root.expand([((0, 0), 0.6), ((1, 1), 0.4)])
        a = root._children[(0, 0)]
        # punish the favourite; exploration term must eventually pick b
        for _ in range(50):
            root._n_visits += 1
            a.update(-1.0)
        move, _ = root.select(c_puct=5.0)
        assert move == (1, 1)

    def test_virtual_loss_revert_restores_stats(self):
        node = TreeNode(None, 0.5)
        node.update(0.8)
        q, n = node._Q, node._n_visits
        node.add_virtual_loss()
        assert node._n_visits == n + 1 and node._Q < q
        node.revert_virtual_loss()
        assert node._n_visits == n
        assert node._Q == pytest.approx(q)


# ----------------------------------------------------------------- MCTS


class TestMCTS:
    def make(self, lmbda=0.0, n_playout=40, cls=MCTS, **kw):
        if cls is ParallelMCTS:
            return ParallelMCTS(batch(constant_value),
                                batch(uniform_priors),
                                lambda states: [0.0] * len(states),
                                lmbda=lmbda, n_playout=n_playout,
                                playout_depth=4, **kw)
        return MCTS(constant_value, uniform_priors, uniform_priors,
                    lmbda=lmbda, n_playout=n_playout, playout_depth=4,
                    **kw)

    def test_returns_legal_move_and_counts_visits(self):
        mcts = self.make()
        state = pygo.GameState(size=SIZE)
        move = mcts.get_move(state)
        assert state.is_legal(move)
        # first playout expands the root itself; the other 39 descend
        assert sum(c._n_visits for c in mcts._root._children.values()) \
            == 39
        assert mcts._root._n_visits == 40

    def test_update_with_move_reuses_subtree(self):
        mcts = self.make()
        state = pygo.GameState(size=SIZE)
        move = mcts.get_move(state)
        subtree = mcts._root._children[move]
        mcts.update_with_move(move)
        assert mcts._root is subtree
        assert mcts._root._parent is None
        mcts.update_with_move((4, 4))  # unseen move → fresh root
        assert mcts._root.is_leaf()

    def test_rollout_mix_prefers_winning_line(self):
        # deterministic rollout that always ends the game by passing:
        # leaf values then come purely from area scoring
        def pass_rollout(state):
            return []
        mcts = MCTS(constant_value, uniform_priors, pass_rollout,
                    lmbda=1.0, n_playout=30, playout_depth=2,
                    rollout_limit=4)
        state = pygo.GameState(size=SIZE, komi=0.5)
        move = mcts.get_move(state)
        assert state.is_legal(move)

    def test_terminal_leaf_uses_game_winner(self):
        state = pygo.GameState(size=SIZE, komi=0.5)
        state.do_move((2, 2))
        state.do_move(pygo.PASS_MOVE, pygo.WHITE)
        state.do_move(pygo.PASS_MOVE, pygo.BLACK)
        assert state.is_end_of_game
        mcts = self.make(n_playout=5)
        mcts._playout(state.copy())
        # Black won the finished game; root edge belongs to the mover
        # into this position, so Q reflects a decided game, not 0.2
        assert abs(mcts._root._Q) == pytest.approx(1.0)


# --------------------------------------------------------- ParallelMCTS


class TestParallelMCTS:
    def test_matches_sequential_contract(self):
        mcts = TestMCTS().make(cls=ParallelMCTS, leaf_batch=8)
        state = pygo.GameState(size=SIZE)
        move = mcts.get_move(state)
        assert state.is_legal(move)
        assert mcts._root._n_visits == 40
        # all virtual losses reverted
        def no_vloss(node):
            assert node._vloss == 0
            for c in node._children.values():
                no_vloss(c)
        no_vloss(mcts._root)

    def test_batches_leaf_evaluations(self):
        calls = []

        def batch_policy(states):
            calls.append(len(states))
            return [uniform_priors(s) for s in states]

        mcts = ParallelMCTS(batch(constant_value), batch_policy,
                            lambda states: [0.0] * len(states),
                            lmbda=0.0, n_playout=24, leaf_batch=8,
                            playout_depth=4)
        mcts.get_move(pygo.GameState(size=SIZE))
        assert len(calls) == 3          # 24 playouts / 8 per wave
        assert max(calls) > 1           # genuinely batched

    def test_remainder_wave(self):
        mcts = TestMCTS().make(cls=ParallelMCTS, n_playout=13,
                               leaf_batch=5)
        mcts.get_move(pygo.GameState(size=SIZE))
        assert mcts._root._n_visits == 13


# ------------------------------------------------------------ MCTSPlayer


def test_mcts_player_end_to_end():
    policy = CNNPolicy(("board", "ones"), board=SIZE, layers=2,
                       filters_per_layer=4)
    value = CNNValue(("board", "ones"), board=SIZE, layers=2,
                     filters_per_layer=4, dense_units=8)
    player = MCTSPlayer(value, policy, lmbda=0.5, n_playout=8,
                        leaf_batch=4, rollout_limit=6, playout_depth=3,
                        seed=0)
    state = pygo.GameState(size=SIZE)
    move = player.get_move(state)
    assert state.is_legal(move)
    state.do_move(move)
    move2 = player.get_move(state)
    assert state.is_legal(move2)


class TestDeviceRollout:
    """device_rollout_fn: the on-device rollout-to-terminal leg."""

    def make_rollout_net(self):
        return CNNRollout(("board", "ones"), board=SIZE, filters=4)

    def test_outcomes_are_signed_and_padded_calls_work(self):
        br = device_rollout_fn(self.make_rollout_net(),
                               rollout_limit=40, min_batch=4, seed=0)
        states = [pygo.GameState(size=SIZE, komi=0.5),
                  pygo.GameState(size=SIZE, komi=0.5)]
        states[1].do_move((2, 2))
        outs = br(states)          # 2 states < min_batch 4 → padded
        assert len(outs) == 2
        assert all(o in (-1.0, 0.0, 1.0) for o in outs)

    def test_finished_game_scores_as_it_stands(self):
        st = pygo.GameState(size=SIZE, komi=0.5)
        st.do_move((2, 2))
        st.do_move(pygo.PASS_MOVE, pygo.WHITE)
        st.do_move(pygo.PASS_MOVE, pygo.BLACK)
        assert st.is_end_of_game     # Black wins by area + komi<1
        br = device_rollout_fn(self.make_rollout_net(),
                               rollout_limit=10, min_batch=2, seed=0)
        # entry player is White (after Black's pass); Black won → -1
        out = br([st])[0]
        expected = 1.0 if st.get_winner() == st.current_player else -1.0
        assert out == expected

    def test_mcts_player_with_device_rollouts(self):
        policy = CNNPolicy(("board", "ones"), board=SIZE, layers=2,
                           filters_per_layer=4)
        value = CNNValue(("board", "ones"), board=SIZE, layers=2,
                         filters_per_layer=4, dense_units=8)
        player = MCTSPlayer(value, policy,
                            rollout=self.make_rollout_net(),
                            lmbda=0.5, n_playout=8, leaf_batch=4,
                            rollout_limit=12, playout_depth=3, seed=0,
                            device_rollout=True)
        state = pygo.GameState(size=SIZE)
        move = player.get_move(state)
        assert state.is_legal(move)


def test_fused_policy_value_path_matches_separate():
    """With the canonical nested feature layout (value = policy +
    color) the wave evaluator shares one encode; the search must be
    identical to the separate-backends path (same nets, same seed)."""
    policy = CNNPolicy(("board", "ones"), board=SIZE, layers=2,
                       filters_per_layer=4)
    value = CNNValue(("board", "ones", "color"), board=SIZE, layers=2,
                     filters_per_layer=4, dense_units=8)

    def run(force_separate):
        rng = np.random.default_rng(0)
        bv, bp, br, bpv = net_backends(policy, value, rng=rng)
        if force_separate:
            bpv = None
        else:
            assert bpv is not None, "nested layout must fuse"
        mcts = ParallelMCTS(bv, bp, br, lmbda=0.0, n_playout=24,
                            leaf_batch=8, playout_depth=4,
                            rng=np.random.default_rng(1),
                            batch_policy_value_fn=bpv)
        state = pygo.GameState(size=SIZE)
        move = mcts.get_move(state)
        visits = {m: c._n_visits
                  for m, c in mcts._root._children.items()}
        return move, visits

    assert run(False) == run(True)


def test_mcts_player_alternating_game_stays_synced():
    """Regression: opponent moves between get_move calls must re-root
    or reset the reused subtree, never desync it (a desynced tree
    replays occupied points → IllegalMove)."""
    policy = CNNPolicy(("board", "ones"), board=SIZE, layers=2,
                       filters_per_layer=4)
    value = CNNValue(("board", "ones"), board=SIZE, layers=2,
                     filters_per_layer=4, dense_units=8)
    player = MCTSPlayer(value, policy, lmbda=0.0, n_playout=12,
                        leaf_batch=4, playout_depth=4, seed=0)
    opponent = np.random.default_rng(1)
    state = pygo.GameState(size=SIZE)
    for _ in range(5):
        move = player.get_move(state)
        assert move is None or state.is_legal(move)
        state.do_move(move)
        if state.is_end_of_game:
            break
        moves = state.get_legal_moves(include_eyes=False)
        state.do_move(moves[opponent.integers(len(moves))]
                      if moves else pygo.PASS_MOVE)
        if state.is_end_of_game:
            break


def test_mcts_player_time_shrinks_playouts():
    """Host-tree parity with DeviceMCTSPlayer's clock behavior
    (VERDICT r3 #10): under a short budget the player runs fewer
    playouts (leaf-wave multiples), and the first, compile-bearing
    search never feeds the rate estimate."""
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.mcts import MCTSPlayer

    pol = CNNPolicy(("board", "ones"), board=5, layers=1,
                    filters_per_layer=2)
    val = CNNValue(("board", "ones", "color"), board=5, layers=1,
                   filters_per_layer=2)
    player = MCTSPlayer(val, pol, lmbda=1.0, n_playout=16,
                        leaf_batch=4, seed=0)
    st = pygo.GameState(size=5)
    player.set_move_time(5.0)        # clock set, but no rate yet
    player.get_move(st)
    assert player.last_n_playout == 16   # full budget, seeds nothing
    assert player._clock.rate is None    # first search excluded
    player._clock.rate = 8.0             # pin: 8 playouts/sec
    player.set_move_time(1.0)            # → 8 playouts = 2 waves
    st.do_move((2, 2))
    player.get_move(st)
    assert player.last_n_playout == 8
    player.set_move_time(1000.0)         # generous → full budget
    st.do_move((1, 1))
    player.get_move(st)
    assert player.last_n_playout == 16


def test_move_clock_median_ignores_anomalous_sample():
    """VERDICT r4 weak #7: one anomalous wall time (GC pause,
    background load) must not halve or double the next move's budget
    — the rate is a median over recent samples, not a 50/50 EMA."""
    from rocalphago_tpu.search.clock import MoveClock

    clock = MoveClock()
    clock.note("k", 100, 1.0)            # warms the key (no sample)
    for _ in range(3):
        clock.note("k", 100, 1.0)        # steady 100 units/sec
    assert clock.rate == 100.0
    clock.note("k", 100, 10.0)           # 10x GC-pause outlier
    assert clock.rate == 100.0           # median shrugs it off
    clock.set_move_time(1.0)
    assert clock.allowed_units() == 100
    # a REAL sustained slowdown does move the estimate within WINDOW
    for _ in range(3):
        clock.note("k", 100, 10.0)
    assert clock.rate == 10.0
