"""The headline benchmark's adaptive TPU sizing path, exercised on CPU.

Round-1 postmortem: bench.py failures are invisible until the driver's
round-end run on real hardware, so the risky code path — the mid-game
probe that picks batch/chunk — must be covered off-chip. The
``_GRAFT_BENCH_FORCE_ADAPTIVE`` hook runs it on the CPU backend with
shrunken workloads.
"""

import io
import json
import os
import sys

import pytest


def test_honest_metric_suffixes(monkeypatch):
    """The headline honesty rules (VERDICT r5 #2) in one table: a
    truncated or contended run reports under a suffixed metric name,
    and NO compromised measurement (truncated, compile-included,
    contended) emits a vs_baseline ratio — the exact hole that let
    round 5 publish 1.81 games/min at vs_baseline 0.145 with
    includes_compile true."""
    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    m = bench.METRIC
    ok = bench._honest_metric(m, 10.0, 12.5, truncated=False,
                              includes_compile=False, contended=False)
    assert ok == (m, 0.8)
    name, vs = bench._honest_metric(m, 10.0, 12.5, truncated=True,
                                    includes_compile=False,
                                    contended=False)
    assert name == m + "_truncated" and vs is None
    name, vs = bench._honest_metric(m, 10.0, 12.5, truncated=False,
                                    includes_compile=False,
                                    contended=True)
    assert name == m + "_contended" and vs is None
    name, vs = bench._honest_metric(m, 10.0, 12.5, truncated=False,
                                    includes_compile=True,
                                    contended=False)
    # compile-polluted runs suffix too (the r5 leak published the
    # headline name with includes_compile true)
    assert name == m + "_compiled" and vs is None
    name, vs = bench._honest_metric(m, 10.0, 12.5, truncated=True,
                                    includes_compile=True,
                                    contended=True)
    assert name == m + "_truncated_compiled_contended" and vs is None


def test_host_contention_reading(monkeypatch):
    """_host_contention returns a usable (load, flag, pids) triple on
    this platform and never raises — a missing /proc reading must not
    fail the bench."""
    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    load1, contended, heavy = bench._host_contention(sample_s=0.05)
    assert load1 is None or load1 >= 0.0
    assert isinstance(contended, bool)
    assert isinstance(heavy, list)
    assert os.getpid() not in heavy     # never flags itself


def test_warmup_compiles_exactly_the_timed_programs():
    """run.warmup must leave a subsequent full rep with ZERO segment
    compiles — the exact-program warmup discipline that keeps the
    headline row at includes_compile: false (the r5 leak was a
    full-rep warmup starving the timed reps instead)."""
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.search.selfplay import make_selfplay_chunked

    cfg = GoConfig(size=5)
    net = CNNPolicy(("board", "ones"), board=5, layers=1,
                    filters_per_layer=2)
    # chunk deliberately not a divisor: the remainder segment is its
    # own compile and warmup must cover it too
    run = make_selfplay_chunked(
        cfg, net.feature_list, net.module.apply, net.module.apply,
        batch=4, max_moves=10, chunk=4, score_on_device=False)
    seg_s = run.warmup(net.params, net.params)
    assert seg_s is not None and seg_s > 0
    n0 = run.segment._cache_size()
    assert n0 == 2          # chunk-length + remainder programs
    res = run(net.params, net.params, jax.random.key(1),
              stop_when_done=True)
    jax.device_get(res.actions)
    assert run.segment._cache_size() == n0   # zero compile growth


@pytest.mark.slow
def test_adaptive_bench_measure_runs_and_reports(monkeypatch):
    monkeypatch.setenv("_GRAFT_BENCH_FORCE_ADAPTIVE", "1")
    monkeypatch.setenv("_GRAFT_BENCH_MAX_MOVES", "12")
    monkeypatch.setenv("_GRAFT_BENCH_SEED_PLIES", "12")
    monkeypatch.setenv("_GRAFT_BENCH_BATCHES", "16,8")
    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    # pin the contention sample: another process busy on the shared
    # CI box must not rename this run's metric under the test
    monkeypatch.setattr(bench, "_host_contention",
                        lambda sample_s=0.25: (0.1, False, []))
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench._measure()
    lines = [ln for ln in out.getvalue().splitlines() if ln.strip()]
    rec = json.loads(lines[-1])
    # 12-ply games are truncated: the record must carry its own
    # metric name — never the full-game headline's — and no ratio
    # against the full-game north star (VERDICT r2/r3)
    assert rec["metric"] == bench.METRIC + "_truncated"
    assert rec["load_1m"] == 0.1 and "contended" not in rec
    assert rec["unit"] == "games/min"
    assert rec["value"] > 0
    assert rec["batch"] in (16, 8)        # a probed candidate won
    assert 5 <= rec["chunk"] <= 100       # sized within the clamp
    assert rec["max_moves"] == 12
    assert rec["truncated"] is True
    assert rec["vs_baseline"] is None


@pytest.mark.slow
def test_fixed_override_ignored_off_tpu(monkeypatch):
    """_GRAFT_BENCH_FIXED must not leak into a CPU child: a TPU-sized
    batch on host would blow the liveness fallback's budget."""
    monkeypatch.setenv("_GRAFT_BENCH_FIXED", "1024,10")
    monkeypatch.setenv("_GRAFT_BENCH_MAX_MOVES", "4")
    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    monkeypatch.setattr(bench, "_host_contention",
                        lambda sample_s=0.25: (0.1, False, []))
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench._measure()
    rec = json.loads([ln for ln in out.getvalue().splitlines()
                      if ln.strip()][-1])
    assert rec["batch"] == 8          # CPU default, not the override
    assert rec["chunk"] == 40


def test_analyze_trace_summarizes_device_lane(tmp_path, monkeypatch):
    """scripts/analyze_trace.py: lane grouping, python-lane exclusion,
    per-op aggregation over a synthetic Perfetto trace."""
    import gzip

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 9, "tid": 3, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1",
         "ts": 0.0, "dur": 100.0},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1",
         "ts": 150.0, "dur": 50.0},
        {"ph": "X", "pid": 1, "tid": 2, "name": "dot.2",
         "ts": 300.0, "dur": 700.0},
        {"ph": "X", "pid": 9, "tid": 3, "name": "frame",
         "ts": 0.0, "dur": 9999.0},
    ]
    d = tmp_path / "plugins" / "profile" / "t1"
    d.mkdir(parents=True)
    with gzip.open(d / "m.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import analyze_trace

    lanes = analyze_trace.summarize(
        analyze_trace.load_events(analyze_trace.newest_trace(
            str(tmp_path))))
    assert list(lanes) == ["/device:TPU:0/XLA Ops"]   # python excluded
    lane = lanes["/device:TPU:0/XLA Ops"]
    assert lane["total_us"] == 850.0
    assert lane["span_us"] == 1000.0
    assert lane["ops"][0] == ("dot.2", 700.0, 1)
    assert lane["ops"][1] == ("fusion.1", 150.0, 2)


def test_self_size_from_results(tmp_path, monkeypatch):
    """bench.py self-sizes from today's on-chip self-play records
    (and ignores other metrics, other platforms, other days)."""
    import time as _time

    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    today = _time.strftime("%Y-%m-%d")
    log = tmp_path / "results.jsonl"
    log.write_text("\n".join([
        json.dumps({"metric": "selfplay_ply_program", "value": 80.0,
                    "batch": 64, "platform": "tpu",
                    "date": f"{today}T01:00:00"}),
        json.dumps({"metric": "selfplay_ply_program", "value": 120.0,
                    "batch": 256, "platform": "tpu",
                    "date": f"{today}T02:00:00"}),
        json.dumps({"metric": "selfplay_ply_program", "value": 999.0,
                    "batch": 16, "platform": "cpu",
                    "date": f"{today}T03:00:00"}),
        json.dumps({"metric": "selfplay_ply_program", "value": 999.0,
                    "batch": 16, "platform": "tpu",
                    "date": "2020-01-01T00:00:00"}),
        json.dumps({"metric": "engine_steps", "value": 9999.0,
                    "batch": 1024, "platform": "tpu",
                    "date": f"{today}T04:00:00"}),
        "{broken",
    ]) + "\n")
    monkeypatch.setenv("ROCALPHAGO_BENCH_LOG", str(log))
    got = bench._self_size_from_results()
    # best same-day TPU record: 120 plies/s at batch 256 ->
    # 2.13 s/ply -> chunk = int(20 / 2.13) = 9
    assert got == (256, 9)

    monkeypatch.setenv("ROCALPHAGO_BENCH_LOG", str(tmp_path / "no"))
    assert bench._self_size_from_results() is None


def test_bench_report_tables_and_probe_stats(tmp_path, monkeypatch):
    """scripts/bench_report.py: latest-record-per-config selection,
    date/platform filters, probe-window extraction."""
    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import bench_report

    log = tmp_path / "r.jsonl"
    log.write_text("\n".join([
        json.dumps({"metric": "m", "value": 1.0, "unit": "u",
                    "batch": 64, "platform": "tpu",
                    "date": "2026-07-31T01:00:00"}),
        json.dumps({"metric": "m", "value": 2.0, "unit": "u",
                    "batch": 64, "platform": "tpu",
                    "date": "2026-07-31T02:00:00"}),   # newer wins
        json.dumps({"metric": "m", "value": 9.0, "unit": "u",
                    "batch": 256, "platform": "tpu", "mfu": 0.1234,
                    "date": "2026-07-31T01:30:00"}),   # distinct cfg
        json.dumps({"metric": "m", "value": 3.0, "unit": "u",
                    "batch": 64, "platform": "tpu",
                    "pipeline_depth": 1, "host_gap_frac": 0.0421,
                    "date": "2026-07-31T02:00:00"}),   # dispatch A/B side
        json.dumps({"metric": "m", "value": 5.0, "unit": "u",
                    "batch": 64, "platform": "cpu",
                    "date": "2026-07-31T03:00:00"}),   # other platform
        json.dumps({"metric": "m", "value": 7.0, "unit": "u",
                    "batch": 64, "platform": "tpu",
                    "date": "2026-07-30T01:00:00"}),   # other day
        json.dumps({"metric": "encode_ab", "value": 100.0, "unit": "u",
                    "batch": 16, "platform": "tpu", "gating": "shared",
                    "phase1": 4, "chase_impl": "xla",
                    "us_per_pos": 123.4,
                    "date": "2026-07-31T01:00:00"}),   # encode A/B side
        json.dumps({"metric": "encode_ab", "value": 50.0, "unit": "u",
                    "batch": 16, "platform": "tpu", "gating": "split",
                    "phase1": 4, "chase_impl": "xla",
                    "us_per_pos": 246.8,
                    "date": "2026-07-31T01:05:00"}),   # distinct gating
        json.dumps({"metric": "serve_moves_per_s", "value": 88.0,
                    "unit": "moves/s", "platform": "tpu",
                    "sessions": 8, "mode": "batched",
                    "date": "2026-07-31T01:00:00"}),   # serving sweep
        json.dumps({"metric": "serve_moves_per_s", "value": 120.0,
                    "unit": "moves/s", "platform": "tpu",
                    "sessions": 64, "mode": "batched",
                    "date": "2026-07-31T01:00:00"}),   # distinct count
        json.dumps({"metric": "serve_moves_per_s", "value": 44.0,
                    "unit": "moves/s", "platform": "tpu",
                    "sessions": 16, "mode": "batched", "cache": "off",
                    "hit_rate": None,
                    "date": "2026-07-31T01:00:00"}),   # cache A/B off
        json.dumps({"metric": "serve_moves_per_s", "value": 175.0,
                    "unit": "moves/s", "platform": "tpu",
                    "sessions": 16, "mode": "batched", "cache": "on",
                    "hit_rate": 0.6491,
                    "date": "2026-07-31T01:00:00"}),   # cache A/B on
        json.dumps({"metric": "gateway_moves_per_s", "value": 95.0,
                    "unit": "moves/s", "platform": "tpu",
                    "conns": 4, "mode": "gateway", "p50_s": 0.01,
                    "date": "2026-07-31T01:00:00"}),   # gateway sweep
        json.dumps({"metric": "gateway_moves_per_s", "value": 90.0,
                    "unit": "moves/s", "platform": "tpu",
                    "conns": 16, "mode": "gateway", "p50_s": 0.02,
                    "date": "2026-07-31T01:00:00"}),   # distinct conns
        json.dumps({"metric": "zero_ingest_games_per_min", "value": 340.0,
                    "unit": "games/min", "platform": "tpu",
                    "actors": 2, "mesh_shape": "8x1",
                    "learner_idle_frac": 0.0714,
                    "date": "2026-07-31T01:00:00"}),   # actor sweep
        json.dumps({"metric": "zero_ingest_games_per_min", "value": 345.0,
                    "unit": "games/min", "platform": "tpu",
                    "actors": 4, "mesh_shape": "8x1",
                    "learner_idle_frac": 0.0574,
                    "date": "2026-07-31T01:00:00"}),   # distinct actors
        json.dumps({"metric": "multisize_moves_per_s", "value": 52.3,
                    "unit": "moves/s", "platform": "tpu",
                    "board": 13, "mode": "one_pool", "sessions": 4,
                    "date": "2026-07-31T01:00:00"}),   # size ladder row
        json.dumps({"metric": "selfplay_cap_games_per_min",
                    "value": 229.3, "unit": "games/min",
                    "platform": "tpu", "batch": 8, "board": 9,
                    "cap_p": 1.0, "fullsearch_frac": 1.0,
                    "date": "2026-07-31T01:00:00"}),   # cap A/B base
        json.dumps({"metric": "selfplay_cap_games_per_min",
                    "value": 582.5, "unit": "games/min",
                    "platform": "tpu", "batch": 8, "board": 9,
                    "cap_p": 0.25, "fullsearch_frac": 0.167,
                    "date": "2026-07-31T01:00:00"}),   # distinct cap_p
        json.dumps({"metric": "zero_ingest_games_per_min",
                    "value": 310.0, "unit": "games/min",
                    "platform": "tpu", "actors": 2,
                    "mesh_shape": "8x1", "learner_idle_frac": 0.09,
                    "kill_at": 2, "mttr_s": 2.442, "restarts": 1,
                    "date": "2026-07-31T01:00:00"}),   # recovery A/B
    ]) + "\n")
    recs = bench_report.load_records(str(log), "2026-07-31", "tpu")
    # pipeline_depth (and the encode gating/phase1/impl axes, the
    # serving sessions×mode axes, the actor/learner actors×mesh axes,
    # the cap-randomization cap_p axis and the recovery kill_at axis)
    # are part of the config key: each A/B side is a distinct row,
    # not a newer duplicate of its sibling
    assert sorted((r["value"], r.get("batch")) for r in recs) \
        == [(2.0, 64), (3.0, 64), (9.0, 256), (44.0, None),
            (50.0, 16), (52.3, None), (88.0, None), (90.0, None),
            (95.0, None), (100.0, 16), (120.0, None), (175.0, None),
            (229.3, 8), (310.0, None), (340.0, None), (345.0, None),
            (582.5, 8)]
    table = bench_report.render_table(recs)
    # board / MFU / host-gap / µs-per-pos / sessions / actors /
    # learner-idle columns: '—' when a record has none, the value
    # when it does
    assert ("| m | 2.0 | u | — | — | — | — | — | — | — | — | — | — | "
            "— | — | batch=64 |" in table)
    assert ("| m | 9.0 | u | — | 12.3% | — | — | — | — | — | — | — "
            "| — | — | — | batch=256 |" in table)
    assert ("| m | 3.0 | u | — | — | 4.21% | — | — | — | — | — | — "
            "| — | — | — | batch=64, pipeline_depth=1 |" in table)
    assert ("| encode_ab | 100.0 | u | — | — | — | 123.4 | — | — | — "
            "| — | — | — | — | — "
            "| batch=16, chase_impl=xla, gating=shared, phase1=4 |"
            in table)
    # the serving sweep keys by session count: both rows survive and
    # the sessions column carries the count (moves/sec-vs-sessions)
    assert ("| serve_moves_per_s | 88.0 | moves/s | — | — | — | — | 8 "
            "| — | — | — | — | — | — | — | mode=batched |" in table)
    assert ("| serve_moves_per_s | 120.0 | moves/s | — | — | — | — | "
            "64 | — | — | — | — | — | — | — | mode=batched |" in table)
    # the cache A/B (bench_serve.py --cache-ab) keys by the cache
    # on/off axis: both arms survive at ONE session count and the hit
    # rate column renders the on-arm's measured rate
    assert ("| serve_moves_per_s | 44.0 | moves/s | — | — | — | — | "
            "16 | — | — | — | — | — | — | — | cache=off, mode=batched |"
            in table)
    assert ("| serve_moves_per_s | 175.0 | moves/s | — | — | — | — | "
            "16 | — | — | — | — | — | — | 64.9% | cache=on, "
            "mode=batched |" in table)
    # the gateway sweep keys by connection count: both rows survive
    # and the conns column carries the count (bench_gateway.py's
    # wire-tax table; p50 stays in config)
    assert ("| gateway_moves_per_s | 95.0 | moves/s | — | — | — | — "
            "| — | 4 | — | — | — | — | — | — | mode=gateway, p50_s=0.01 |"
            in table)
    assert ("| gateway_moves_per_s | 90.0 | moves/s | — | — | — | — "
            "| — | 16 | — | — | — | — | — | — | mode=gateway, p50_s=0.02 |"
            in table)
    # the actor/learner sweep keys by actor count: both rows survive,
    # the actors column carries the count and learner idle renders as
    # a percentage (bench_zero_scale.py's scaling table)
    assert ("| zero_ingest_games_per_min | 340.0 | games/min | — | — "
            "| — | — | — | — | 2 | 7.1% | — | — | — | — | mesh_shape=8x1 |"
            in table)
    assert ("| zero_ingest_games_per_min | 345.0 | games/min | — | — "
            "| — | — | — | — | 4 | 5.7% | — | — | — | — | mesh_shape=8x1 |"
            in table)
    # the recovery A/B keys by kill_at: the killed-actor row survives
    # next to its fault-free sibling and the MTTR column carries the
    # kill-to-first-post-restart-game time (--kill-actor-at)
    assert ("| zero_ingest_games_per_min | 310.0 | games/min | — | — "
            "| — | — | — | — | 2 | 9.0% | — | — | 2.442s | — | "
            "kill_at=2, mesh_shape=8x1, restarts=1 |" in table)
    # the multi-size sweep keys by board: the board column carries it
    # (bench_multisize.py's size-scaling table)
    assert ("| multisize_moves_per_s | 52.3 | moves/s | 13 | — | — | "
            "— | 4 | — | — | — | — | — | — | — | mode=one_pool |" in table)
    # the cap-randomization A/B keys by cap_p: both rows survive, the
    # cap p / full frac columns carry them (bench_selfplay --cap-ab)
    assert ("| selfplay_cap_games_per_min | 229.3 | games/min | 9 | — "
            "| — | — | — | — | — | — | 1 | 100.0% | — | — | batch=8 |"
            in table)
    assert ("| selfplay_cap_games_per_min | 582.5 | games/min | 9 | — "
            "| — | — | — | — | — | — | 0.25 | 16.7% | — | — | batch=8 |"
            in table)

    probe = tmp_path / "probe.log"
    probe.write_text(
        "probe rc=124 [01:00:00]\n"
        "probe rc=0 [01:02:00]\nprobe rc=3 [01:04:00]\n"
        "probe rc=124 [01:06:00]\n"
        "probe rc=0 [01:10:00]\n")
    s = bench_report.probe_stats([str(probe)])
    assert s["probes"] == 5 and s["up"] == 3
    assert s["windows"] == 2
    assert s["window_spans_s"] == [120, 0]


def test_probe_stats_midnight_and_file_boundaries(tmp_path, monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import bench_report

    a = tmp_path / "a.probe.log"
    a.write_text("probe rc=0 [23:50:00]\nprobe rc=0 [00:20:00]\n")
    b = tmp_path / "b.probe.log"
    b.write_text("probe rc=0 [00:21:00]\n")
    s = bench_report.probe_stats([str(a), str(b)])
    # midnight wrap inside one file: one 30-min window, not clamped 0;
    # file boundary: b's window is separate, never stitched onto a's
    assert s["windows"] == 2
    assert s["window_spans_s"] == [1800, 0]
    assert s["probes"] == 3 and s["up"] == 3


def test_zero_curve_summary(tmp_path, monkeypatch):
    """scripts/zero_curve.py: curve extraction, config echo, and the
    flat-vs-learning verdict thresholds."""
    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import zero_curve

    run = tmp_path / "run"
    run.mkdir()
    (run / "metadata.json").write_text(json.dumps(
        {"config": {"game_batch": 4, "sims": 8}}))
    rows = [{"event": "iteration", "iteration": i,
             "value_acc": 0.5 + 0.04 * i, "value_mse": 1.0 - 0.05 * i,
             "policy_loss": 100.0 - i} for i in range(10)]
    (run / "metrics.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n")

    out = tmp_path / "s.json"
    zero_curve.main([str(run), "--window", "3", "--out", str(out)])
    s = json.loads(out.read_text())
    assert s["iterations"] == 10 and s["games"] == 40
    acc = s["curves"]["value_acc"]
    assert acc["first"] == 0.5 and acc["last"] == pytest.approx(0.86)
    assert s["value_head_verdict"] == "learning"

    # flat curve -> flat verdict
    flat = [dict(r, value_acc=0.5) for r in rows]
    (run / "metrics.jsonl").write_text(
        "\n".join(json.dumps(r) for r in flat) + "\n")
    zero_curve.main([str(run), "--out", str(out)])
    assert json.loads(out.read_text())["value_head_verdict"] == "flat"

    # rising but still ~chance (tail below the 0.55 floor) is NOT
    # "learning" — the verdict needs level, not just slope
    low = [dict(r, value_acc=0.30 + 0.02 * r["iteration"])
           for r in rows]
    (run / "metrics.jsonl").write_text(
        "\n".join(json.dumps(r) for r in low) + "\n")
    zero_curve.main([str(run), "--out", str(out)])
    assert json.loads(out.read_text())["value_head_verdict"] == "flat"
