"""The headline benchmark's adaptive TPU sizing path, exercised on CPU.

Round-1 postmortem: bench.py failures are invisible until the driver's
round-end run on real hardware, so the risky code path — the mid-game
probe that picks batch/chunk — must be covered off-chip. The
``_GRAFT_BENCH_FORCE_ADAPTIVE`` hook runs it on the CPU backend with
shrunken workloads.
"""

import io
import json
import os
import sys

import pytest


@pytest.mark.slow
def test_adaptive_bench_measure_runs_and_reports(monkeypatch):
    monkeypatch.setenv("_GRAFT_BENCH_FORCE_ADAPTIVE", "1")
    monkeypatch.setenv("_GRAFT_BENCH_MAX_MOVES", "12")
    monkeypatch.setenv("_GRAFT_BENCH_SEED_PLIES", "12")
    monkeypatch.setenv("_GRAFT_BENCH_BATCHES", "16,8")
    monkeypatch.syspath_prepend(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench._measure()
    lines = [ln for ln in out.getvalue().splitlines() if ln.strip()]
    rec = json.loads(lines[-1])
    assert rec["metric"] == bench.METRIC
    assert rec["unit"] == "games/min"
    assert rec["value"] > 0
    assert rec["batch"] in (16, 8)        # a probed candidate won
    assert 5 <= rec["chunk"] <= 100       # sized within the clamp
    assert rec["max_moves"] == 12
    # 12-ply games are truncated: the metric must say so and must not
    # claim a ratio against the full-game north star (VERDICT r2)
    assert rec["truncated"] is True
    assert rec["vs_baseline"] is None
