"""Pipelined chunk dispatch (``runtime.pipeline``) — ISSUE 4.

The contract under test: pipelining is a SCHEDULING change, not a
semantics change. Every chunked hot loop must produce bit-identical
results at ``depth=0`` (the old fully-sync pacing) and ``depth=1``
(one chunk in flight while the host decides) — PUCT search, gumbel
search, chunked self-play (including the lagged done-poll's
extra-chunk no-op) and a full zero iteration — while the sync path's
per-chunk host gap disappears (``host_gap_frac`` strictly lower
pipelined than sync, the bench A/B's tier-1 twin). Donation rides
along: the chunk programs donate their device-resident carries, and
``runtime.retries`` must refuse to wrap them.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.engine.jaxgo import GoConfig, new_states
from rocalphago_tpu.runtime import retries
from rocalphago_tpu.runtime.pipeline import (
    DEPTH_ENV,
    ChunkPipeline,
    default_depth,
)

SIZE = 5
N = SIZE * SIZE
FEATS = ("board", "ones")
VFEATS = FEATS + ("color",)
CFG = GoConfig(size=SIZE)


def fake_policy(params, planes):
    return jnp.zeros((planes.shape[0], N))


def fake_value(params, planes):
    mine = planes[..., 0].sum(axis=(1, 2))
    theirs = planes[..., 1].sum(axis=(1, 2))
    return (mine - theirs) / N


# ------------------------------------------------- ChunkPipeline unit


def test_depth_semantics_and_retire_order():
    """depth=0 retires every push immediately (sync); depth=1 keeps
    one chunk pending and retires in dispatch order."""
    sync = ChunkPipeline(depth=0)
    out = sync.push(jnp.int32(1), payload="a")
    assert [p for p, _ in out] == ["a"]
    assert sync.pending() == 0

    pipe = ChunkPipeline(depth=1)
    assert pipe.push(jnp.int32(1), payload="a") == []
    assert pipe.pending() == 1
    out = pipe.push(jnp.int32(2), payload="b")
    assert [p for p, _ in out] == ["a"]
    assert pipe.pending() == 1
    out = pipe.drain()
    assert [p for p, _ in out] == ["b"]
    assert pipe.pending() == 0
    # retired handles are materialized — device_get cannot block on
    # anything still in flight
    assert int(jax.device_get(out[0][1])) == 2


def test_gap_accounting_sync_counts_pipelined_does_not():
    """Every sync chunk boundary is a gap (the device idles while the
    host decides); a depth-1 window never empties mid-run, so its gap
    count is exactly zero — the invariant behind the bench A/B's
    'pipelined gap strictly lower'."""
    sync = ChunkPipeline(depth=0)
    for i in range(4):
        sync.push(jnp.int32(i))
        time.sleep(0.002)            # host "decision" time
    sync.drain()
    assert sync.gaps == 3            # one per inter-chunk boundary
    assert sync.gap_s > 0.0
    assert sync.host_gap_frac > 0.0

    pipe = ChunkPipeline(depth=1)
    for i in range(4):
        pipe.push(jnp.int32(i))
        time.sleep(0.002)
    pipe.drain()
    assert pipe.gaps == 0            # window never emptied mid-run
    assert pipe.host_gap_frac == 0.0
    assert pipe.host_gap_frac < sync.host_gap_frac


def test_env_default_depth(monkeypatch):
    monkeypatch.delenv(DEPTH_ENV, raising=False)
    assert default_depth() == 1
    monkeypatch.setenv(DEPTH_ENV, "0")
    assert default_depth() == 0
    assert ChunkPipeline().depth == 0
    monkeypatch.setenv(DEPTH_ENV, "3")
    assert ChunkPipeline().depth == 3
    monkeypatch.setenv(DEPTH_ENV, "-1")
    with pytest.raises(ValueError, match="must be >= 0"):
        default_depth()
    monkeypatch.setenv(DEPTH_ENV, "two")
    with pytest.raises(ValueError, match="non-negative integer"):
        default_depth()


def test_reset_stats_refuses_inflight():
    pipe = ChunkPipeline(depth=1)
    pipe.push(jnp.int32(0))
    with pytest.raises(RuntimeError, match="in flight"):
        pipe.reset_stats()
    pipe.drain()
    pipe.reset_stats()
    assert pipe.chunks == 0 and pipe.wall_s == 0.0


def test_windows_survive_finish_and_reuse():
    """A bench shares one pipeline across reps: finish() closes the
    accounting window; the idle time BETWEEN windows is not a gap."""
    pipe = ChunkPipeline(depth=0)
    pipe.push(jnp.int32(0))
    pipe.drain()
    wall1 = pipe.wall_s
    time.sleep(0.02)                 # inter-rep host time
    pipe.push(jnp.int32(1))
    pipe.drain()
    assert pipe.gaps == 0            # no INTRA-window boundary idled
    assert pipe.wall_s >= wall1
    assert pipe.wall_s < 0.02 + 0.5  # the sleep is not in any window


# ------------------------------------------- retries donation guard


def test_retry_refuses_donating_callable():
    def chunk_program(x):
        return x

    chunk_program.donates_buffers = True
    # the wraps below are the FIXTURE: they assert the runtime
    # refusal that jaxlint's retry-wraps-donating rule proves statically
    with pytest.raises(ValueError, match="DONATED"):
        retries.retry()(chunk_program)  # jaxlint: disable=retry-wraps-donating
    with pytest.raises(ValueError, match="DONATED"):
        retries.retry_call(chunk_program, 1)  # jaxlint: disable=retry-wraps-donating


def test_retry_refuses_real_donating_chunk_programs():
    """The actual chunk programs advertise donates_buffers (through
    the jaxobs.track wrapper's attribute surface) and are refused."""
    from rocalphago_tpu.search.device_mcts import make_device_mcts
    from rocalphago_tpu.search.selfplay import make_selfplay_chunked

    search = make_device_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=4, max_nodes=8)
    assert retries.donates(search.run_sims_donated)
    assert not retries.donates(search.run_sims)
    with pytest.raises(ValueError, match="DONATED"):
        # (grandfathered in .jaxlint-baseline.json: this wrap IS the fixture)
        retries.retry()(search.run_sims_donated)

    run = make_selfplay_chunked(CFG, FEATS, fake_policy, fake_policy,
                                batch=2, max_moves=4, chunk=2)
    assert retries.donates(run.segment)
    with pytest.raises(ValueError, match="DONATED"):
        # (grandfathered in .jaxlint-baseline.json: this wrap IS the fixture)
        retries.retry()(run.segment)
    # the RUNNER is retryable — it rebuilds its donated carries from
    # never-donated inputs on every invocation
    assert not retries.donates(run)


def test_transient_fault_on_donating_chunk_retries_via_runner():
    """ISSUE 4 satellite: a transient fault mid-loop (after chunks
    whose input slabs were already donated) must NOT be retried at
    the chunk — the runner level retry recomputes the identical
    result from the unchanged inputs."""
    from rocalphago_tpu.runtime import faults
    from rocalphago_tpu.search.selfplay import make_selfplay_chunked

    run = make_selfplay_chunked(CFG, FEATS, fake_policy, fake_policy,
                                batch=2, max_moves=12, chunk=4)
    key = jax.random.key(5)
    want = run(None, None, key)
    try:
        faults.install("io_error@selfplay.chunk:2")
        wrapped = retries.retry(max_attempts=2, base_delay=0.0,
                                sleep=lambda s: None)(run)
        got = wrapped(None, None, key)
    finally:
        faults.install(None)
    np.testing.assert_array_equal(np.asarray(want.actions),
                                  np.asarray(got.actions))
    np.testing.assert_array_equal(np.asarray(want.final.board),
                                  np.asarray(got.final.board))


# ------------------------------------------------ step-on-done no-op


def test_step_on_all_done_states_is_a_noop():
    """The lagged done-poll's safety lemma: a segment dispatched onto
    all-done states must change NOTHING (the engine freezes finished
    games) — so an extra in-flight chunk past the done point leaves
    ``final`` bit-identical."""
    states = new_states(CFG, 3)
    vstep = jax.vmap(lambda s, a: jaxgo.step(CFG, s, a))
    for _ in range(2):               # two passes end every game
        states = vstep(states, jnp.full((3,), N, jnp.int32))
    assert bool(jax.device_get(states.done.all()))
    before = jax.device_get(states)
    stepped = vstep(states, jnp.zeros((3,), jnp.int32))
    after = jax.device_get(stepped)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------- bit-identical depth sweeps


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


def test_puct_chunked_bit_identical_across_depths():
    """PUCT chunk loop: monolithic == depth 0 == depth 1 == depth 2,
    and the sync run's host gap is strictly above the pipelined
    run's (the A/B acceptance, in-process)."""
    from rocalphago_tpu.search.device_mcts import make_device_mcts

    search = make_device_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=24, max_nodes=48)
    roots = new_states(CFG, 2)
    v_mono, q_mono = jax.device_get(search(None, None, roots))
    pipes = {}
    for depth in (0, 1, 2):
        pipes[depth] = pipe = ChunkPipeline(depth=depth)
        visits, q = jax.device_get(search.run_chunked(
            None, None, roots, chunk=5, pipeline=pipe))
        np.testing.assert_array_equal(v_mono, visits), depth
        np.testing.assert_array_equal(q_mono, q), depth
        assert search.last_ran == 24
    assert pipes[0].host_gap_frac > pipes[1].host_gap_frac
    assert pipes[0].gaps > 0 and pipes[1].gaps == 0


def test_gumbel_chunked_bit_identical_across_depths():
    from rocalphago_tpu.search.device_mcts import make_gumbel_mcts

    search = make_gumbel_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=16, max_nodes=64,
                              m_root=4)
    roots = new_states(CFG, 2)
    rng = jax.random.key(11)
    ref = None
    gaps = {}
    for depth in (0, 1):
        pipe = ChunkPipeline(depth=depth)
        out = jax.device_get(search.run_chunked(
            None, None, roots, rng, chunk=3, pipeline=pipe))
        gaps[depth] = pipe
        if ref is None:
            ref = out
        else:
            for a, b in zip(ref, out):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
    assert gaps[0].host_gap_frac > gaps[1].host_gap_frac


def test_chunked_selfplay_bit_identical_across_depths():
    """Chunked self-play — plain, and with the lagged done-poll
    (games end well before max_moves, so depth>=1 dispatches a
    provably-no-op extra segment whose rows must come back as the
    sync path's zero padding)."""
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.search.selfplay import (
        make_selfplay,
        make_selfplay_chunked,
    )

    policy = CNNPolicy(FEATS, board=SIZE, layers=1,
                       filters_per_layer=2)
    key = jax.random.key(3)
    mono = make_selfplay(CFG, FEATS, policy.module.apply,
                         policy.module.apply, batch=4, max_moves=25)
    want_mono = mono(policy.params, policy.params, key)
    chunked = make_selfplay_chunked(
        CFG, FEATS, policy.module.apply, policy.module.apply,
        batch=4, max_moves=25, chunk=10)
    outs = {}
    for depth in (0, 1, 2):
        outs[depth] = chunked(policy.params, policy.params, key,
                              depth=depth)
        _assert_trees_equal(want_mono, outs[depth])

    # done-poll path: 5x5 games end far before 200 plies; every depth
    # must agree with depth 0 (which itself pads from the first
    # all-done segment, exactly like the pre-pipeline runner)
    long = make_selfplay_chunked(
        CFG, FEATS, policy.module.apply, policy.module.apply,
        batch=4, max_moves=200, chunk=10)
    ref = long(policy.params, policy.params, key, stop_when_done=True,
               depth=0)
    assert bool(np.asarray(ref.final.done).all())
    assert ref.actions.shape[0] == 200       # zero-padded full shape
    n_plies = int(np.asarray(ref.num_moves).max())
    assert n_plies < 150                     # the early-exit mattered
    # rows past the last live ply are the zero padding
    assert not np.asarray(ref.live)[n_plies:].any()
    for depth in (1, 2):
        got = long(policy.params, policy.params, key,
                   stop_when_done=True, depth=depth)
        _assert_trees_equal(ref, got)


def test_zero_iteration_bit_identical_across_depths(monkeypatch):
    """One full zero iteration (search self-play + replay + update)
    at env depth 0 vs 1: identical metrics and identical updated
    parameters — the whole trainer is pipelining-invariant."""
    import optax

    from rocalphago_tpu.training.zero import (
        init_zero_state,
        make_zero_iteration,
    )

    iteration = make_zero_iteration(
        CFG, FEATS, VFEATS, fake_policy, fake_value,
        optax.sgd(1e-2), optax.sgd(1e-2), batch=2, move_limit=6,
        n_sim=4, max_nodes=8, sim_chunk=2, replay_chunk=2)
    results = {}
    for depth in (0, 1):
        monkeypatch.setenv(DEPTH_ENV, str(depth))
        state = init_zero_state({"w": jnp.ones((2,))},
                                {"w": jnp.ones((2,))},
                                optax.sgd(1e-2), optax.sgd(1e-2),
                                seed=7)
        new_state, metrics = iteration(state)
        results[depth] = (jax.device_get(new_state),
                          jax.device_get(metrics))
    s0, m0 = results[0]
    s1, m1 = results[1]
    _assert_trees_equal(s0, s1)
    assert set(m0) == set(m1)
    for k in m0:
        np.testing.assert_array_equal(np.asarray(m0[k]),
                                      np.asarray(m1[k]))


def test_rl_chunked_iteration_bit_identical_across_depths(monkeypatch):
    """The chunked REINFORCE iteration (donating replay segments +
    pipelined selfplay) at env depth 0 vs 1."""
    import optax

    from rocalphago_tpu.io.checkpoint import pack_rng
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.training.rl import (
        RLState,
        make_rl_iteration_chunked,
    )

    policy = CNNPolicy(FEATS, board=SIZE, layers=1,
                       filters_per_layer=2)
    tx = optax.sgd(1e-3)
    iteration = make_rl_iteration_chunked(
        CFG, FEATS, policy.module.apply, tx, batch=2, move_limit=10,
        temperature=1.0, chunk=4)
    results = {}
    for depth in (0, 1):
        monkeypatch.setenv(DEPTH_ENV, str(depth))
        state = RLState(policy.params, tx.init(policy.params),
                        jnp.int32(0), pack_rng(jax.random.key(9)))
        new_state, metrics = iteration(state, policy.params)
        results[depth] = (jax.device_get(new_state),
                          jax.device_get(metrics))
    _assert_trees_equal(results[0][0], results[1][0])
    for k in results[0][1]:
        np.testing.assert_array_equal(
            np.asarray(results[0][1][k]),
            np.asarray(results[1][1][k]))


# -------------------------------------------- selfplay gap A/B


def test_selfplay_pipelined_gap_strictly_lower():
    """The bench A/B's tier-1 twin on the self-play runner: the sync
    done-poll pays a host gap per segment; the pipelined runner's
    window never empties."""
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.search.selfplay import make_selfplay_chunked

    policy = CNNPolicy(FEATS, board=SIZE, layers=1,
                       filters_per_layer=2)
    run = make_selfplay_chunked(
        CFG, FEATS, policy.module.apply, policy.module.apply,
        batch=4, max_moves=24, chunk=4)
    key = jax.random.key(1)
    run(policy.params, policy.params, key)   # compile
    pipes = {d: ChunkPipeline(depth=d) for d in (0, 1)}
    for d, pipe in pipes.items():
        run(policy.params, policy.params, key, stop_when_done=True,
            pipeline=pipe)
    assert pipes[0].gaps > 0
    assert pipes[1].gaps == 0
    assert pipes[1].host_gap_frac < pipes[0].host_gap_frac


# ------------------------------------------ donation memory contract


def test_chunk_loop_donates_but_callers_keep_their_trees():
    """run_sims_chunked donates the slab it loops on, yet a caller's
    tree (owned=False, the default) survives — the loop's defensive
    copy eats the first donation. With owned=True the caller's
    buffers are consumed (donated away on this backend)."""
    from rocalphago_tpu.search.device_mcts import make_device_mcts

    search = make_device_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=8, max_nodes=16)
    roots = new_states(CFG, 2)
    tree = search.init(None, None, roots)
    out, ran = search.run_sims_chunked(None, None, tree, chunk=4)
    assert ran == 8
    # the input tree is still alive and reusable
    out2, _ = search.run_sims_chunked(None, None, tree, chunk=4)
    _assert_trees_equal(out, out2)

    owned_tree = search.init(None, None, roots)
    search.run_sims_chunked(None, None, owned_tree, chunk=4,
                            owned=True)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.device_get(owned_tree.visits))
