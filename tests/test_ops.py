"""Pallas kernel differential tests (interpret mode on CPU CI).

The kernels must be drop-in exact against their XLA twins; adversarial
shapes (the serpentine worst case that maximizes label-propagation
distance) are included so the static sweep bound is exercised, not
just typical sparse boards.
"""

import jax
import numpy as np
import pytest

from rocalphago_tpu.engine import pygo
from rocalphago_tpu.engine.jaxgo import GoConfig, compute_labels
from rocalphago_tpu.ops import pallas_labels

SIZE = 9
N = SIZE * SIZE


def xla_labels(boards):
    cfg = GoConfig(size=SIZE)
    return jax.vmap(lambda b: compute_labels(cfg, b))(boards)


def random_boards(batch, moves, seed):
    rng = np.random.default_rng(seed)
    out = np.zeros((batch, N), np.int8)
    for i in range(batch):
        st = pygo.GameState(size=SIZE, komi=5.5)
        for _ in range(moves):
            legal = st.get_legal_moves(include_eyes=False)
            if not legal or st.is_end_of_game:
                break
            st.do_move(legal[rng.integers(len(legal))])
        out[i] = np.asarray(st.board, np.int8).reshape(-1)
    return out


def single_file_snake(size: int):
    """A 1-wide boustrophedon snake: even rows full, odd rows a single
    connector stone at alternating ends — ONE group whose label must
    propagate along the whole path (the longest chain constructible on
    a board), the stress case for the kernel's static sweep bound."""
    b = np.zeros((size, size), np.int8)
    for x in range(size):
        if x % 2 == 0:
            b[x, :] = 1
        else:
            b[x, size - 1 if (x // 2) % 2 == 0 else 0] = 1
    return b.reshape(-1)


@pytest.mark.parametrize("moves", [0, 10, 30, 60])
def test_pallas_labels_match_xla_on_random_boards(moves):
    boards = random_boards(6, moves, seed=moves)
    got = np.asarray(pallas_labels(boards, SIZE, interpret=True))
    want = np.asarray(xla_labels(boards))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("size", [SIZE, 19])
def test_pallas_labels_serpentine_worst_case(size):
    """Longest-chain snakes (on 19×19 the path is ~190 stones) plus a
    solid board must label exactly — these exercise the static sweep
    bound far beyond typical sparse positions."""
    solid = np.ones((size * size,), np.int8)
    boards = np.stack([single_file_snake(size), solid,
                       -single_file_snake(size)]).astype(np.int8)
    got = np.asarray(pallas_labels(boards, size, interpret=True))
    cfg = GoConfig(size=size)
    want = np.asarray(
        jax.vmap(lambda b: compute_labels(cfg, b))(boards))
    np.testing.assert_array_equal(got, want)
    # each snake really is one group rooted at its min index
    for row in (0, 2):
        snake = got[row]
        stones = boards[row] != 0
        assert (snake[stones] == snake[stones].min()).all()
