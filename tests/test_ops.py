"""Pallas kernel differential tests (interpret mode on CPU CI).

The kernels must be drop-in exact against their XLA twins; adversarial
shapes (the serpentine worst case that maximizes label-propagation
distance) are included so the static sweep bound is exercised, not
just typical sparse boards.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import pygo
from rocalphago_tpu.engine.jaxgo import GoConfig, compute_labels
from rocalphago_tpu.ops import pallas_chase, pallas_labels

SIZE = 9
N = SIZE * SIZE


def xla_labels(boards):
    cfg = GoConfig(size=SIZE)
    return jax.vmap(lambda b: compute_labels(cfg, b))(boards)


def random_boards(batch, moves, seed):
    rng = np.random.default_rng(seed)
    out = np.zeros((batch, N), np.int8)
    for i in range(batch):
        st = pygo.GameState(size=SIZE, komi=5.5)
        for _ in range(moves):
            legal = st.get_legal_moves(include_eyes=False)
            if not legal or st.is_end_of_game:
                break
            st.do_move(legal[rng.integers(len(legal))])
        out[i] = np.asarray(st.board, np.int8).reshape(-1)
    return out


def single_file_snake(size: int):
    """A 1-wide boustrophedon snake: even rows full, odd rows a single
    connector stone at alternating ends — ONE group whose label must
    propagate along the whole path (the longest chain constructible on
    a board), the stress case for the kernel's static sweep bound."""
    b = np.zeros((size, size), np.int8)
    for x in range(size):
        if x % 2 == 0:
            b[x, :] = 1
        else:
            b[x, size - 1 if (x // 2) % 2 == 0 else 0] = 1
    return b.reshape(-1)


@pytest.mark.parametrize("moves", [0, 10, 30, 60])
def test_pallas_labels_match_xla_on_random_boards(moves):
    boards = random_boards(6, moves, seed=moves)
    got = np.asarray(pallas_labels(boards, SIZE, interpret=True))
    want = np.asarray(xla_labels(boards))
    np.testing.assert_array_equal(got, want)


def chase_lanes(seed, positions=24, moves_lo=8, moves_hi=40):
    """Chase entries via the SAME harvest the chase benchmark uses
    (``benchmarks/_harness.py``) so test and bench always exercise the
    exact entry contract the ladder planes hand to the chase."""
    import os
    import sys

    # repo root derived from this file, not cwd, so the import works
    # from any pytest invocation directory
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from benchmarks._harness import harvest_chase_lanes

    return harvest_chase_lanes(SIZE, lanes=None, seed=seed,
                               moves_lo=moves_lo, moves_hi=moves_hi,
                               positions=positions)


@pytest.mark.slow
def test_pallas_chase_matches_xla_on_random_entries():
    from rocalphago_tpu.features.ladders import _chase

    cfg = GoConfig(size=SIZE)
    boards, labels, preys = chase_lanes(seed=3)
    assert len(preys) >= 20
    xla = jax.jit(jax.vmap(functools.partial(
        _chase, cfg, depth=40, enabled=True)))
    want = np.asarray(xla(jnp.asarray(boards), jnp.asarray(labels),
                          jnp.asarray(preys)))
    prey_oh = (np.arange(N)[None, :] == preys[:, None])
    got = np.asarray(pallas_chase(
        jnp.asarray(boards), jnp.asarray(labels),
        jnp.asarray(prey_oh), SIZE, depth=40, interpret=True))
    np.testing.assert_array_equal(got, want)
    # the harvest must include both outcomes or the test proves little
    assert want.any() and not want.all()


@pytest.mark.slow
def test_pallas_chase_under_vmap_matches_unbatched():
    """Every production call site reaches the kernel through the
    encoder's jax.vmap over games (the pallas_call batching rule
    prepends a grid dim) — pin that path, not just the flat one."""
    from rocalphago_tpu.features.ladders import _chase

    cfg = GoConfig(size=SIZE)
    boards, labels, preys = chase_lanes(seed=9, positions=30)
    g = 3                                 # games × lanes
    lanes = (len(preys) // g) * g
    assert lanes >= 2 * g
    shape_b = (g, lanes // g, N)
    vb = jnp.asarray(boards[:lanes]).reshape(shape_b)
    vl = jnp.asarray(labels[:lanes]).reshape(shape_b)
    oh = (np.arange(N)[None, :] == preys[:lanes, None]).reshape(shape_b)

    batched = jax.vmap(lambda b, l, p: pallas_chase(
        b, l, p, SIZE, depth=40, interpret=True))(vb, vl,
                                                  jnp.asarray(oh))
    xla = jax.jit(jax.vmap(functools.partial(
        _chase, cfg, depth=40, enabled=True)))
    want = np.asarray(xla(jnp.asarray(boards[:lanes]),
                          jnp.asarray(labels[:lanes]),
                          jnp.asarray(preys[:lanes])))
    np.testing.assert_array_equal(
        np.asarray(batched).reshape(-1), want)


@pytest.mark.slow
def test_pallas_chase_collect_core_matches_xla():
    """The kernel's read-core accumulation (the incremental encoder's
    footprint seed) must match the XLA chase's ``collect_core`` cell
    for cell — captured verdicts too, since the tuple return shares
    one while loop."""
    from rocalphago_tpu.features.ladders import _chase

    cfg = GoConfig(size=SIZE)
    boards, labels, preys = chase_lanes(seed=7, positions=30)
    xla = jax.jit(jax.vmap(functools.partial(
        _chase, cfg, depth=40, enabled=True, collect_core=True)))
    want_cap, want_core = xla(jnp.asarray(boards),
                              jnp.asarray(labels),
                              jnp.asarray(preys))
    prey_oh = (np.arange(N)[None, :] == preys[:, None])
    got_cap, got_core = pallas_chase(
        jnp.asarray(boards), jnp.asarray(labels), jnp.asarray(prey_oh),
        SIZE, depth=40, interpret=True, collect_core=True)
    np.testing.assert_array_equal(np.asarray(got_cap),
                                  np.asarray(want_cap))
    np.testing.assert_array_equal(np.asarray(got_core),
                                  np.asarray(want_core))
    assert np.asarray(want_core).any()


@pytest.mark.slow
def test_pallas_chase_disabled_lane_is_false():
    boards, labels, preys = chase_lanes(seed=5, positions=4)
    zeros = np.zeros((len(preys), N), bool)
    got = np.asarray(pallas_chase(
        jnp.asarray(boards), jnp.asarray(labels), jnp.asarray(zeros),
        SIZE, interpret=True))
    assert not got.any()


@pytest.mark.slow
def test_chase_impl_flag_produces_identical_planes(monkeypatch):
    """The ROCALPHAGO_PALLAS_CHASE=interpret path must yield the exact
    same ladder planes as the default XLA chase (plane-level wiring of
    the kernel, not just the raw chase)."""
    from rocalphago_tpu.engine.jaxgo import (
        from_pygo,
        group_data,
        legal_mask,
    )
    from rocalphago_tpu.features import ladders

    cfg = GoConfig(size=SIZE)
    rng = np.random.default_rng(11)
    st = pygo.GameState(size=SIZE, komi=5.5)
    for _ in range(30):
        legal = st.get_legal_moves(include_eyes=False)
        if not legal or st.is_end_of_game:
            break
        st.do_move(legal[rng.integers(len(legal))])
    jst = from_pygo(cfg, st)
    gd = group_data(cfg, jst.board, with_zxor=False)
    legal = legal_mask(cfg, jst, gd)[:-1]

    def planes():
        return (np.asarray(ladders.ladder_capture_plane(
                    cfg, jst, gd, legal)),
                np.asarray(ladders.ladder_escape_plane(
                    cfg, jst, gd, legal)))

    monkeypatch.delenv("ROCALPHAGO_PALLAS_CHASE", raising=False)
    cap_xla, esc_xla = planes()
    monkeypatch.setenv("ROCALPHAGO_PALLAS_CHASE", "interpret")
    cap_pal, esc_pal = planes()
    np.testing.assert_array_equal(cap_xla, cap_pal)
    np.testing.assert_array_equal(esc_xla, esc_pal)


@pytest.mark.parametrize("size", [SIZE, 19])
def test_pallas_labels_serpentine_worst_case(size):
    """Longest-chain snakes (on 19×19 the path is ~190 stones) plus a
    solid board must label exactly — these exercise the static sweep
    bound far beyond typical sparse positions."""
    solid = np.ones((size * size,), np.int8)
    boards = np.stack([single_file_snake(size), solid,
                       -single_file_snake(size)]).astype(np.int8)
    got = np.asarray(pallas_labels(boards, size, interpret=True))
    cfg = GoConfig(size=size)
    want = np.asarray(
        jax.vmap(lambda b: compute_labels(cfg, b))(boards))
    np.testing.assert_array_equal(got, want)
    # each snake really is one group rooted at its min index
    for row in (0, 2):
        snake = got[row]
        stones = boards[row] != 0
        assert (snake[stones] == snake[stones].min()).all()
