"""SL trainer smoke + resume tests on the 8-fake-device CPU mesh.

Mirrors the reference's ``tests/test_supervised_policy_trainer.py``
(SURVEY.md §4 "Trainer smoke tests"): tiny model + tiny dataset, run a
few minibatches, assert weights/metadata land on disk; plus the resume
path, and — beyond the reference — that training is genuinely
data-parallel across the virtual mesh (conftest forces 8 CPU devices).
"""

import json
import os

import numpy as np
import pytest

from rocalphago_tpu.models import CNNPolicy
from rocalphago_tpu.parallel import mesh as meshlib
from rocalphago_tpu.training.sl import SLConfig, SLTrainer

SIZE = 7
FEATURES = ("board", "ones")
PLANES = 4
N_POS = 192


def write_dataset(prefix: str, n: int = N_POS, seed: int = 0) -> None:
    """Synthesize a small learnable corpus: the 'expert' move is a fixed
    function of the position so accuracy can rise above chance."""
    rng = np.random.default_rng(seed)
    states = rng.integers(0, 2, (n, SIZE, SIZE, PLANES)).astype(np.uint8)
    actions = (states[:, :, :, 0].sum((1, 2)) % (SIZE * SIZE)).astype(
        np.int32)
    half = n // 2
    for i, sl in enumerate((slice(0, half), slice(half, n))):
        np.savez(f"{prefix}-{i:05d}.npz", states=states[sl],
                 actions=actions[sl])
    with open(f"{prefix}-manifest.json", "w") as f:
        json.dump({"board_size": SIZE, "planes": PLANES,
                   "shard_counts": [half, n - half],
                   "features": list(FEATURES)}, f)


@pytest.fixture()
def corpus(tmp_path):
    prefix = str(tmp_path / "data" / "corpus")
    os.makedirs(tmp_path / "data")
    write_dataset(prefix)
    return prefix


def small_cfg(corpus, out_dir, **kw):
    defaults = dict(
        train_data=corpus, out_dir=str(out_dir), minibatch=16, epochs=2,
        learning_rate=0.05, train_val_test=(0.8, 0.1, 0.1),
        symmetries=True, seed=1, max_validation_batches=2)
    defaults.update(kw)
    return SLConfig(**defaults)


def small_net():
    return CNNPolicy(FEATURES, board=SIZE, layers=2, filters_per_layer=4)


def test_mesh_spans_all_virtual_devices():
    mesh = meshlib.make_mesh()
    assert mesh.shape[meshlib.DATA_AXIS] == 8


def test_sl_smoke_and_artifacts(corpus, tmp_path):
    out = tmp_path / "out"
    trainer = SLTrainer(small_cfg(corpus, out), net=small_net())
    result = trainer.run()
    assert np.isfinite(result["train_loss"])
    assert np.isfinite(result["val_loss"])
    assert result["step"] > 0
    meta = json.loads((out / "metadata.json").read_text())
    assert len(meta["epochs"]) == 2
    assert (out / "weights.00001.flax.msgpack").exists()
    assert (out / "shuffle.npz").exists()
    assert (out / "metrics.jsonl").exists()


def test_sl_learns_synthetic_rule(corpus, tmp_path):
    cfg = small_cfg(corpus, tmp_path / "out", epochs=6, learning_rate=0.2,
                    symmetries=False)
    trainer = SLTrainer(cfg, net=small_net())
    result = trainer.run()
    meta = json.loads((tmp_path / "out" / "metadata.json").read_text())
    first = meta["epochs"][0]["train_loss"]
    assert result["train_loss"] < first, "loss did not decrease"


def test_sl_resume_continues_from_checkpoint(corpus, tmp_path):
    out = tmp_path / "out"
    t1 = SLTrainer(small_cfg(corpus, out, epochs=1), net=small_net())
    t1.run()
    step1 = int(np.asarray(t1.state.step))
    assert step1 > 0
    # same out_dir, more epochs → resumes, does not restart from 0
    t2 = SLTrainer(small_cfg(corpus, out, epochs=2), net=small_net())
    assert t2.start_epoch == 1
    assert int(np.asarray(t2.state.step)) == step1
    result = t2.run()
    assert result["step"] > step1
    meta = json.loads((out / "metadata.json").read_text())
    assert meta["epochs"][-1]["epoch"] == 1


def test_sl_rejects_plane_mismatch(corpus, tmp_path):
    bad = CNNPolicy(("board",), board=SIZE, layers=2, filters_per_layer=4)
    with pytest.raises(ValueError, match="planes"):
        SLTrainer(small_cfg(corpus, tmp_path / "out"), net=bad)


def test_split_is_persisted_and_stable(corpus, tmp_path):
    out = tmp_path / "out"
    t1 = SLTrainer(small_cfg(corpus, out, epochs=1), net=small_net())
    a = np.sort(t1.train_idx)
    t2 = SLTrainer(small_cfg(corpus, out, epochs=1), net=small_net())
    np.testing.assert_array_equal(a, np.sort(t2.train_idx))


def test_kill_and_resume_is_bit_identical(corpus, tmp_path):
    """Fault-injection (SURVEY.md §5 "failure detection"): a run killed
    after epoch 0 and resumed must produce exactly the same final
    params as an uninterrupted run — the checkpoint carries everything
    (params, opt state, PRNG bits) and batch order is derived
    per-epoch, so preemption recovery is lossless."""
    import jax
    import jax.flatten_util  # noqa: F401 — used as jax.flatten_util

    straight = SLTrainer(small_cfg(corpus, tmp_path / "a", epochs=2),
                         net=small_net())
    straight.run()
    straight.ckpt.close()

    interrupted = SLTrainer(small_cfg(corpus, tmp_path / "b", epochs=1),
                            net=small_net())
    interrupted.run()
    interrupted.ckpt.close()          # simulated preemption point
    resumed = SLTrainer(small_cfg(corpus, tmp_path / "b", epochs=2),
                        net=small_net())
    assert resumed.start_epoch == 1
    resumed.run()
    resumed.ckpt.close()

    a = jax.device_get(straight.state.params)
    b = jax.device_get(resumed.state.params)
    flat_a, _ = jax.flatten_util.ravel_pytree(a)
    flat_b, _ = jax.flatten_util.ravel_pytree(b)
    np.testing.assert_array_equal(np.asarray(flat_a), np.asarray(flat_b))


def test_mid_epoch_kill_and_resume_is_bit_identical(corpus, tmp_path):
    """Preemption INSIDE an epoch: with ``save_every`` the trainer
    checkpoints mid-epoch, and resume derives the data cursor
    (step % steps_per_epoch) to skip consumed batches — so killing
    after any step still reproduces the uninterrupted run bit-for-bit
    (round-1 weakness: resume used to replay the whole epoch)."""
    import jax
    import jax.flatten_util  # noqa: F401

    straight = SLTrainer(small_cfg(corpus, tmp_path / "a", epochs=1),
                         net=small_net())
    straight.run()
    straight.ckpt.close()
    steps_per_epoch = straight._steps_per_epoch()
    assert steps_per_epoch >= 6, "corpus too small for a mid-epoch kill"

    interrupted = SLTrainer(
        small_cfg(corpus, tmp_path / "b", epochs=1, save_every=2),
        net=small_net())
    orig_step = interrupted._train_step
    calls = {"n": 0}

    def killing_step(state, planes, actions):
        if calls["n"] == 5:
            raise KeyboardInterrupt("simulated preemption")
        calls["n"] += 1
        return orig_step(state, planes, actions)

    interrupted._train_step = killing_step
    with pytest.raises(KeyboardInterrupt):
        interrupted.run()
    interrupted.ckpt.close()

    resumed = SLTrainer(
        small_cfg(corpus, tmp_path / "b", epochs=1, save_every=2),
        net=small_net())
    assert resumed.start_epoch == 0
    assert resumed._resume_skip == 4     # last save landed at step 4
    resumed.run()
    resumed.ckpt.close()

    a = jax.device_get(straight.state.params)
    b = jax.device_get(resumed.state.params)
    flat_a, _ = jax.flatten_util.ravel_pytree(a)
    flat_b, _ = jax.flatten_util.ravel_pytree(b)
    np.testing.assert_array_equal(np.asarray(flat_a), np.asarray(flat_b))


def test_final_test_metric_and_standalone_eval_agree(corpus, tmp_path):
    """BASELINE.md metric 1 plumbing: the trainer records a held-out
    test top-1 in metadata.json, and the standalone eval CLI reproduces
    it from the exported model.json + persisted split."""
    from rocalphago_tpu.training import evaluate as ev

    out = tmp_path / "out"
    cfg = small_cfg(corpus, out, epochs=1, max_validation_batches=50)
    trainer = SLTrainer(cfg, net=small_net())
    result = trainer.run()
    assert "test_accuracy" in result
    meta = json.loads((out / "metadata.json").read_text())
    assert meta["test_accuracy"] == pytest.approx(
        result["test_accuracy"])

    res = ev.main([str(out / "model.json"), corpus, "--split", "test",
                   "--shuffle-npz", str(out / "shuffle.npz"),
                   "--minibatch", "16"])
    assert res["positions"] > 0
    assert res["top1"] == pytest.approx(result["test_accuracy"],
                                        abs=1e-5)
