"""On-device MCTS (``search.device_mcts``) — fake-backend tests.

Same strategy as the host-tree MCTS tests (and the reference's
``tests/test_mcts.py``): the policy/value evaluators are injected
callables, so tree mechanics are tested with no trained nets — here
the fakes are shape-compatible jittable functions of the encoded
planes (uniform priors; a stone-count value), which lets the whole
searcher run as the single compiled program it is in production.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.engine.jaxgo import GoConfig, new_states
from rocalphago_tpu.search.device_mcts import make_device_mcts

SIZE = 5
N = SIZE * SIZE
FEATS = ("board", "ones")
VFEATS = FEATS + ("color",)
CFG = GoConfig(size=SIZE)


def fake_policy(params, planes):
    """Uniform logits — priors become uniform over sensible moves."""
    return jnp.zeros((planes.shape[0], N))


def fake_value(params, planes):
    """(my stones − their stones) / N from the board planes — favors
    captures, enough signal to steer the search measurably."""
    mine = planes[..., 0].sum(axis=(1, 2))
    theirs = planes[..., 1].sum(axis=(1, 2))
    return (mine - theirs) / N


@pytest.fixture(scope="module")
def searcher():
    return make_device_mcts(CFG, FEATS, VFEATS, fake_policy, fake_value,
                            n_sim=32, max_nodes=64, c_puct=5.0)


def test_visits_sum_and_sensible_support(searcher):
    roots = new_states(CFG, 4)
    visits, q = jax.device_get(searcher(None, None, roots))
    assert visits.shape == (4, N + 1)
    np.testing.assert_array_equal(visits.sum(axis=1), 32)
    # empty-board roots: every move is sensible, pass never visited
    # (its prior is 0 while sensible moves exist)
    assert (visits[:, N] == 0).all()
    assert (np.abs(q) <= 1.0 + 1e-5).all()


def test_search_is_deterministic(searcher):
    roots = new_states(CFG, 2)
    v1, q1 = jax.device_get(searcher(None, None, roots))
    v2, q2 = jax.device_get(searcher(None, None, roots))
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(q1, q2)


def test_capture_move_dominates_visits(searcher):
    """B to move with W(0,0) in atari: the capture at (0,1) swings the
    stone-count value net the most, so it must collect the most root
    visits."""
    st = pygo.GameState(size=SIZE)
    st.do_move((1, 0), pygo.BLACK)
    st.do_move((0, 0), pygo.WHITE)
    st.current_player = pygo.BLACK
    root = jaxgo.from_pygo(CFG, st)
    roots = jax.tree.map(lambda x: x[None], root)
    visits, q = jax.device_get(searcher(None, None, roots))
    capture = 0 * SIZE + 1                       # flat index of (0, 1)
    board_visits = visits[0, :N]
    assert board_visits.argmax() == capture, (
        f"capture got {board_visits[capture]} visits, max is "
        f"{board_visits.max()} at {board_visits.argmax()}")
    # and its backed-up value is positive for the capturing player
    assert q[0, capture] > 0


def test_chunked_sims_equal_monolithic(searcher):
    """init + repeated run_sims(k) must equal the one-program search
    exactly — the search is deterministic and the tree carry is the
    entire state, so chunking is pure program-splitting."""
    roots = new_states(CFG, 2)
    v_mono, q_mono = jax.device_get(searcher(None, None, roots))
    tree = searcher.init(None, None, roots)
    for k in (5, 5, 5, 5, 5, 5, 2):      # 32 sims, uneven chunks
        tree = searcher.run_sims(None, None, tree, k=k)
    v_chunk, q_chunk = jax.device_get(searcher.root_stats(tree))
    np.testing.assert_array_equal(v_mono, v_chunk)
    np.testing.assert_array_equal(q_mono, q_chunk)


def test_split_sim_path_matches_fused(searcher):
    """The serving seam (prepare_sim → eval_batch → apply_sim, the
    cross-game-batching drive in ``rocalphago_tpu/serve``) must be
    the fused search exactly: same halves, same eval program, so a
    pooled session's visits are bit-identical to run_sims."""
    roots = new_states(CFG, 2)
    tree_f = searcher.init(None, None, roots)
    tree_f = searcher.run_sims(None, None, tree_f, k=12)
    v_f, q_f = jax.device_get(searcher.root_stats(tree_f))

    priors0, _ = searcher.eval_batch(None, None, roots)
    tree_s = searcher.assemble_tree(roots, priors0)
    free = jnp.full((2,), -1, jnp.int32)
    for _ in range(12):
        ctx = searcher.prepare_sim(tree_s, free)
        priors, values = searcher.eval_batch(None, None,
                                             ctx.eval_states)
        tree_s = searcher.apply_sim(tree_s, ctx, priors, values)
    v_s, q_s = jax.device_get(searcher.root_stats(tree_s))
    np.testing.assert_array_equal(v_f, v_s)
    np.testing.assert_array_equal(q_f, q_s)


def test_capacity_bound_keeps_searching():
    """A full slab must stop allocating but keep evaluating — visit
    counts still total n_sim and nothing crashes."""
    searcher = make_device_mcts(CFG, FEATS, VFEATS, fake_policy,
                                fake_value, n_sim=24, max_nodes=4)
    roots = new_states(CFG, 2)
    visits, _ = jax.device_get(searcher(None, None, roots))
    np.testing.assert_array_equal(visits.sum(axis=1), 24)


@pytest.mark.slow
def test_mcts_selfplay_plays_full_games():
    """Search-driven self-play on 5×5: games end by two passes within
    the move budget, recorded actions are within range, and the live
    mask is monotonically non-increasing per game."""
    from rocalphago_tpu.search.device_mcts import make_mcts_selfplay

    run = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value, batch=2, max_moves=150,
                             n_sim=12, max_nodes=24, sim_chunk=5)
    final, actions, live = run(None, None, jax.random.key(0))
    assert bool(np.asarray(final.done).all()), "games did not finish"
    acts = np.asarray(actions)
    assert ((acts >= 0) & (acts <= N)).all()
    lv = np.asarray(live).astype(int)
    assert (np.diff(lv, axis=0) <= 0).all(), "live mask regressed"
    # scoring works on the finals
    winners = np.asarray(jax.device_get(
        jax.vmap(lambda s: jaxgo.winner(CFG, s))(final)))
    assert set(winners) <= {-1, 0, 1}


def test_search_sharded_over_mesh_matches_unsharded(searcher):
    """Environment parallelism by placement alone: sharding the root
    batch over the virtual mesh's data axis shards the whole search
    (tree slabs are per-game), and results stay bit-identical — XLA
    propagates the sharding through init/simulate with no search-code
    changes."""
    from rocalphago_tpu.parallel import mesh as meshlib

    roots = new_states(CFG, 4)
    v1, q1 = jax.device_get(searcher(None, None, roots))
    mesh = meshlib.make_mesh(2)
    roots_sh = meshlib.shard_batch(mesh, roots)
    v2, q2 = jax.device_get(searcher(None, None, roots_sh))
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(q1, q2)


def test_device_mcts_player_plays_gtp_game():
    """The serving wrapper: DeviceMCTSPlayer drives a GTP genmove on a
    real (tiny) policy/value pair — host state bridged in, device
    search, vertex back out."""
    from rocalphago_tpu.interface.gtp import GTPEngine
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import DeviceMCTSPlayer

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    player = DeviceMCTSPlayer(val, pol, n_sim=8, max_nodes=16,
                              sim_chunk=4)
    engine = GTPEngine(player)
    for cmd, ok_prefix in ((f"boardsize {SIZE}", "="),
                           ("clear_board", "="),
                           ("genmove b", "= ")):
        reply, _ = engine.handle(cmd + "\n")
        assert reply.startswith(ok_prefix), (cmd, reply)
    vertex = reply.split()[-1]
    assert vertex.upper() != "RESIGN"


def test_terminal_root_backs_up_nothing():
    """A game already ended by two passes: the search must not crash
    and the root (its parent edge is -1) accumulates no edge visits."""
    st = new_states(CFG, 2)
    vstep = jax.vmap(lambda s, a: jaxgo.step(CFG, s, a))
    st = vstep(st, jnp.full((2,), N, jnp.int32))
    st = vstep(st, jnp.full((2,), N, jnp.int32))
    assert bool(st.done.all())
    searcher = make_device_mcts(CFG, FEATS, VFEATS, fake_policy,
                                fake_value, n_sim=8, max_nodes=8)
    visits, q = jax.device_get(searcher(None, None, st))
    np.testing.assert_array_equal(visits, 0)
    np.testing.assert_array_equal(q, 0.0)


# ---------------------------------------------------------------------------
# Gumbel sequential-halving root search


def fake_value_zero(params, planes):
    return jnp.zeros((planes.shape[0],))


def test_halving_schedule_shapes():
    from rocalphago_tpu.search.device_mcts import _halving_schedule

    # budget divides exactly: 128 sims over 16 candidates
    sched = _halving_schedule(128, 16)
    assert sched == [(16, 2), (8, 4), (4, 8), (2, 16)]
    assert sum(k * v for k, v in sched) == 128
    # tiny budget: every phase still visits each survivor once
    sched = _halving_schedule(32, 16)
    assert [k for k, _ in sched] == [16, 8, 4, 2]
    assert all(v >= 1 for _, v in sched)
    # leftover lands on the final 2-candidate phase
    sched = _halving_schedule(100, 4)
    assert sched[-1][0] == 2
    assert sum(k * v for k, v in sched) <= 100


def test_gumbel_player_slab_fits_halving_plan():
    """Advisor r3 repro: a small-budget gumbel player must size its
    slab from the halving plan's REAL simulation count (30 for
    n_sim=8/m_root=16), not nominal n_sim — 2*8=16 nodes would
    silently saturate mid-search."""
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import (
        DeviceMCTSPlayer,
        gumbel_plan_sims,
    )

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    plan = gumbel_plan_sims(8, 16, SIZE * SIZE + 1)
    assert plan > 8
    player = DeviceMCTSPlayer(val, pol, n_sim=8, gumbel=True,
                              m_root=16, sim_chunk=4)
    assert player._max_nodes == 2 * plan
    # PUCT sizing is unchanged
    puct = DeviceMCTSPlayer(val, pol, n_sim=8, sim_chunk=4)
    assert puct._max_nodes == 16


def test_gumbel_visits_follow_schedule():
    """Constant value net => candidate ranking is fixed by the gumbel
    draw alone, so the visit pattern must equal the halving schedule:
    the top candidate attends every phase, total visits = plan total,
    and best is the global gumbel argmax."""
    from rocalphago_tpu.search.device_mcts import make_gumbel_mcts

    search = make_gumbel_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value_zero, n_sim=32, max_nodes=64,
                              m_root=8)
    roots = new_states(CFG, 3)
    rng = jax.random.key(7)
    visits, q, best, pi = jax.device_get(
        search(None, None, roots, rng))
    plan_total = sum(k * v for k, v in search.schedule)
    top_total = sum(v for _, v in search.schedule)
    np.testing.assert_array_equal(visits.sum(axis=1), plan_total)
    np.testing.assert_array_equal(visits.max(axis=1), top_total)
    # with constant values, best == argmax of the gumbel-perturbed
    # logits (recover them via init with the same rng)
    _, g, cand, _ = search.init(None, None, roots, rng)
    np.testing.assert_array_equal(best, np.asarray(g).argmax(axis=1))
    np.testing.assert_array_equal(best, np.asarray(cand)[:, 0])


@pytest.mark.slow
def test_gumbel_chunked_equals_monolithic():
    from rocalphago_tpu.search.device_mcts import make_gumbel_mcts

    search = make_gumbel_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=24, max_nodes=48,
                              m_root=8)
    roots = new_states(CFG, 2)
    rng = jax.random.key(3)
    v1, q1, b1, p1 = jax.device_get(search(None, None, roots, rng))
    v2, q2, b2, p2 = jax.device_get(
        search.run_chunked(None, None, roots, rng, chunk=5))
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(b1, b2)
    np.testing.assert_allclose(q1, q2, rtol=1e-6)
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


@pytest.mark.slow
def test_gumbel_finds_capture():
    """Same oracle as the PUCT capture test: with all actions as
    candidates, sequential halving must keep and pick the capture (the
    biggest stone-count swing) as best."""
    from rocalphago_tpu.search.device_mcts import make_gumbel_mcts

    # c_scale=4: the stone-count net's value gaps are ~0.04-0.08, so
    # at the default scale a lucky gumbel draw on a quiet move can
    # legitimately outweigh sigma(q) — weighting value up makes the
    # oracle decisive (exactly the knob's purpose)
    search = make_gumbel_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, n_sim=64, max_nodes=128,
                              m_root=N + 1, c_scale=4.0)
    st = pygo.GameState(size=SIZE)
    st.do_move((1, 0), pygo.BLACK)
    st.do_move((0, 0), pygo.WHITE)
    st.current_player = pygo.BLACK
    root = jaxgo.from_pygo(CFG, st)
    roots = jax.tree.map(lambda x: x[None], root)
    capture = 0 * SIZE + 1
    for seed in (0, 1, 2):
        _, _, best, _ = jax.device_get(
            search(None, None, roots, jax.random.key(seed)))
        assert int(best[0]) == capture, (seed, int(best[0]))


def test_gumbel_player_plays_gtp_game():
    from rocalphago_tpu.interface.gtp import GTPEngine
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import DeviceMCTSPlayer

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    player = DeviceMCTSPlayer(val, pol, n_sim=8, max_nodes=16,
                              sim_chunk=4, gumbel=True, m_root=4)
    engine = GTPEngine(player)
    for cmd, ok_prefix in ((f"boardsize {SIZE}", "="),
                           ("clear_board", "="),
                           ("genmove b", "= ")):
        reply, _ = engine.handle(cmd + "\n")
        assert reply.startswith(ok_prefix), (cmd, reply)
    assert reply.split()[-1].upper() != "RESIGN"


def test_improved_policy_reduces_to_priors_on_constant_value():
    """With a constant value net, completed q is constant, so sigma
    adds the same offset everywhere and pi' must equal the root
    priors (softmax of the unmodified masked logits)."""
    from rocalphago_tpu.search.device_mcts import make_gumbel_mcts

    search = make_gumbel_mcts(CFG, FEATS, VFEATS, fake_policy,
                              fake_value_zero, n_sim=16, max_nodes=32,
                              m_root=8)
    roots = new_states(CFG, 2)
    _, _, _, pi = jax.device_get(
        search(None, None, roots, jax.random.key(0)))
    np.testing.assert_allclose(pi.sum(axis=-1), 1.0, rtol=1e-5)
    # uniform logits over 25 sensible moves, pass masked out
    np.testing.assert_allclose(pi[:, :N], 1.0 / N, rtol=1e-4)
    np.testing.assert_allclose(pi[:, N], 0.0, atol=1e-6)


def test_gumbel_selfplay_records_improved_policy():
    from rocalphago_tpu.search.device_mcts import make_mcts_selfplay

    run = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value, batch=2, max_moves=6,
                             n_sim=8, max_nodes=16, sim_chunk=4,
                             record_visits=True, gumbel=True,
                             m_root=4)
    final, actions, live, targets = run(None, None, jax.random.key(1))
    t = np.asarray(targets)
    assert t.dtype == np.float32
    assert t.shape == (actions.shape[0], 2, N + 1)
    np.testing.assert_allclose(t.sum(axis=-1), 1.0, rtol=1e-4)
    acts = np.asarray(actions)
    assert ((acts >= 0) & (acts <= N)).all()


@pytest.mark.slow
def test_dirichlet_root_noise_perturbs_search():
    """PUCT self-play with root noise: different rng seeds must yield
    different visit patterns (the noiseless searcher is fully
    deterministic), and gumbel+noise is rejected up front."""
    from rocalphago_tpu.search.device_mcts import make_mcts_selfplay

    runs = {}
    for seed in (0, 1):
        run = make_mcts_selfplay(
            CFG, FEATS, VFEATS, fake_policy, fake_value, batch=2,
            max_moves=1, n_sim=12, max_nodes=24, sim_chunk=6,
            record_visits=True, dirichlet_alpha=1.0, noise_frac=0.5,
            temperature=0)
        _, _, _, targets = run(None, None, jax.random.key(seed))
        runs[seed] = np.asarray(targets)
    assert not np.array_equal(runs[0], runs[1]), (
        "root noise had no effect on the search")

    with pytest.raises(ValueError, match="gumbel"):
        make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                           fake_value, batch=2, max_moves=1, n_sim=8,
                           max_nodes=16, gumbel=True,
                           dirichlet_alpha=0.03)


def test_advance_root_follows_child_edges(searcher):
    """advance_root moves the root down an expanded edge: the shifted
    root's stats must equal the child node's rows, and searching from
    it must keep accumulating there."""
    roots = new_states(CFG, 1)
    tree = searcher.init(None, None, roots)
    tree = searcher.run_sims(None, None, tree, k=16)
    visits0, _ = jax.device_get(searcher.root_stats(tree))
    a = int(visits0[0].argmax())
    child_idx = int(jax.device_get(tree.child)[0, 0, a])
    assert child_idx >= 0
    tree2, ok = searcher.advance_root(tree, jnp.array([a]))
    assert bool(jax.device_get(ok)[0])
    assert int(jax.device_get(tree2.root)[0]) == child_idx
    v_child = jax.device_get(tree.visits)[0, child_idx]
    v_root2, _ = jax.device_get(searcher.root_stats(tree2))
    np.testing.assert_array_equal(v_root2[0], v_child)
    # resumed search allocates/visits below the NEW root
    tree3 = searcher.run_sims(None, None, tree2, k=8)
    v_root3, _ = jax.device_get(searcher.root_stats(tree3))
    assert v_root3.sum() == v_child.sum() + 8
    # unexpanded edge: ok=False, root unchanged
    unvisited = int(np.argmin(jax.device_get(
        tree.child)[0, 0] >= 0))
    _, ok2 = searcher.advance_root(tree, jnp.array([unvisited]))
    assert not bool(jax.device_get(ok2)[0])


def test_player_subtree_reuse_across_moves():
    """A two-player scripted exchange: the second get_move must engage
    the carried subtree (reuses == 1) and still return a legal move;
    clear-board reset forgets it."""
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import DeviceMCTSPlayer
    from rocalphago_tpu.search.players import reset_player

    from rocalphago_tpu.utils.coords import flatten_idx, unflatten_idx

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    player = DeviceMCTSPlayer(val, pol, n_sim=32, max_nodes=128,
                              sim_chunk=8)
    st = pygo.GameState(size=SIZE)
    mv = player.get_move(st)
    assert player.reuses == 0
    st.do_move(mv)
    # pick an opponent reply the search actually EXPANDED (reuse can
    # only follow explored edges): walk the carried tree to our
    # move's child, take any grandchild edge
    _, _, _, tree = player._carry
    child = np.asarray(jax.device_get(tree.child))[0]
    our_child = child[0, flatten_idx(mv, SIZE)]
    assert our_child >= 0
    replies = np.nonzero(child[our_child][:N] >= 0)[0]
    assert replies.size, "no grandchildren expanded at 32 sims"
    st.do_move(unflatten_idx(int(replies[0]), SIZE))
    mv2 = player.get_move(st)
    assert player.reuses == 1
    assert mv2 is None or st.is_legal(mv2)
    # an opponent move the search never expanded (pass) -> rebuild
    st.do_move(mv2)
    st.do_move(None)
    player.get_move(st)
    assert player.reuses == 1
    reset_player(player)
    st2 = pygo.GameState(size=SIZE)
    player.get_move(st2)
    assert player.reuses == 1             # fresh game -> fresh tree
