"""Crash-safe runtime layer (``rocalphago_tpu.runtime``) unit tests:
atomic artifact writes, retry classification/backoff, the fault-plan
grammar and barrier semantics, the watchdog, the line-buffered
``MetricsLogger`` crash contract with its tolerant reader, metadata
resume-overwrite semantics, and the ladder-script satellite fixes."""

import json
import os
import time

import pytest

from rocalphago_tpu.runtime import atomic, faults, retries
from rocalphago_tpu.runtime.jsonl import read_jsonl
from rocalphago_tpu.runtime.watchdog import Watchdog


@pytest.fixture(autouse=True)
def _no_fault_plan():
    """Isolate every test from the env plan and reset fired specs."""
    faults.install("")
    yield
    faults.install(None)


# ---------------------------------------------------------- atomic

def test_atomic_write_roundtrip(tmp_path):
    p = str(tmp_path / "a" / "b.json")
    atomic.atomic_write_json(p, {"x": 1})
    with open(p) as f:
        assert json.load(f) == {"x": 1}
    atomic.atomic_write_bytes(p, b"v2")
    with open(p, "rb") as f:
        assert f.read() == b"v2"


def test_atomic_write_failure_preserves_old(tmp_path, monkeypatch):
    """A failure at the rename leaves the previous complete file and
    no temp litter — the whole point of the dance."""
    p = str(tmp_path / "f.bin")
    atomic.atomic_write_bytes(p, b"old")

    def boom(*a, **k):
        raise OSError("injected replace failure")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        atomic.atomic_write_bytes(p, b"new")
    monkeypatch.undo()
    with open(p, "rb") as f:
        assert f.read() == b"old"
    assert os.listdir(tmp_path) == ["f.bin"]   # tmp cleaned up


# --------------------------------------------------------- retries

def test_retry_transient_then_success():
    calls = []

    @retries.retry(max_attempts=3, base_delay=0.0, sleep=lambda s: None)
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert flaky() == "ok"
    assert len(calls) == 3


def test_retry_gives_up_after_max_attempts():
    calls = []

    @retries.retry(max_attempts=2, base_delay=0.0, sleep=lambda s: None)
    def always():
        calls.append(1)
        raise OSError("still down")

    with pytest.raises(OSError):
        always()
    assert len(calls) == 2


def test_retry_programming_error_not_retried():
    calls = []

    @retries.retry(max_attempts=5, base_delay=0.0, sleep=lambda s: None)
    def broken():
        calls.append(1)
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        broken()
    assert len(calls) == 1


def test_transient_classification():
    class XlaRuntimeError(Exception):
        pass

    assert retries.is_transient(OSError("disk"))
    assert retries.is_transient(faults.InjectedFault("io"))
    assert retries.is_transient(
        XlaRuntimeError("UNAVAILABLE: socket closed"))
    assert retries.is_transient(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    # an XlaRuntimeError wrapping a programming error is NOT transient
    assert not retries.is_transient(
        XlaRuntimeError("INVALID_ARGUMENT: dimension mismatch"))
    assert not retries.is_transient(TypeError("bad arg"))
    assert not retries.is_transient(KeyboardInterrupt())


def test_backoff_deterministic_and_bounded():
    a = [retries.backoff_delay(i, 0.5, 8.0, seed=7, key="f")
         for i in range(6)]
    b = [retries.backoff_delay(i, 0.5, 8.0, seed=7, key="f")
         for i in range(6)]
    assert a == b                       # same seed → same schedule
    assert a != [retries.backoff_delay(i, 0.5, 8.0, seed=8, key="f")
                 for i in range(6)]
    for i, d in enumerate(a):
        envelope = min(8.0, 0.5 * 2 ** i)
        assert envelope * 0.5 <= d <= envelope


# ---------------------------------------------------------- faults

def test_fault_plan_grammar():
    specs = faults.parse_plan(
        "crash@iter3.post_save, io_error@promote:2, sleep@chunk=0.25")
    assert [s.kind for s in specs] == ["crash", "io_error", "sleep"]
    assert specs[0].iteration == 3 and specs[0].barrier == "post_save"
    assert specs[1].hit == 2
    assert specs[2].arg == 0.25
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.parse_plan("crash")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_plan("explode@save")
    with pytest.raises(ValueError, match="needs a duration"):
        faults.parse_plan("sleep@save")


def test_fault_barrier_iteration_and_hit_count():
    faults.install("io_error@iter2.zero.post_save:2")
    faults.barrier("zero.post_save", 0)      # wrong iteration
    faults.barrier("zero.post_save", 2)      # hit 1 of 2
    with pytest.raises(faults.InjectedFault):
        faults.barrier("zero.post_save", 2)  # hit 2 → fires
    faults.barrier("zero.post_save", 2)      # fired → spent


def test_fault_barrier_suffix_match():
    faults.install("io_error@post_save")
    with pytest.raises(faults.InjectedFault):
        faults.barrier("sl.post_save", 0)
    faults.install("io_error@zero.post_save")
    faults.barrier("sl.post_save", 0)        # qualified: no match
    with pytest.raises(faults.InjectedFault):
        faults.barrier("zero.post_save", 0)


def test_fault_sleep_kind():
    faults.install("sleep@tick=0.05")
    t0 = time.monotonic()
    faults.barrier("loop.tick")
    assert time.monotonic() - t0 >= 0.05


def test_injected_fault_is_retryable_and_one_shot():
    """The designed interplay: one injected io_error costs one retry
    attempt, then the run proceeds — fault plans exercise the backoff
    path without killing the run."""
    faults.install("io_error@write:1")

    @retries.retry(max_attempts=3, base_delay=0.0, sleep=lambda s: None)
    def write():
        faults.barrier("artifact.write")
        return "written"

    assert write() == "written"


# -------------------------------------------------------- watchdog

def test_watchdog_beat_keeps_quiet():
    events = []

    class Log:
        def log(self, event, **kw):
            events.append((event, kw))

    with Watchdog(0.2, metrics=Log(), poll_s=0.02) as wd:
        for _ in range(10):
            wd.beat()
            time.sleep(0.02)
    assert events == []


def test_watchdog_stall_logs_and_aborts():
    events, aborted = [], []

    class Log:
        def log(self, event, **kw):
            events.append((event, kw))

    wd = Watchdog(0.05, metrics=Log(), poll_s=0.01,
                  abort_fn=lambda: aborted.append(1), exit=False,
                  name="t")
    wd.start()
    time.sleep(0.3)                      # no beats → stall
    wd.stop()
    assert aborted == [1]
    assert events and events[0][0] == "stall"
    assert events[0][1]["watchdog"] == "t"
    assert events[0][1]["elapsed_s"] >= 0.05


# ------------------------------------- MetricsLogger crash contract

def test_metrics_logger_line_buffered_no_close(tmp_path):
    """Every log() is durably a whole line immediately (buffering=1):
    a kill between events loses nothing, a kill mid-write loses at
    most the in-flight line. Read WITHOUT closing the logger — a
    crashed process never calls close()."""
    from rocalphago_tpu.io.metrics import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    log = MetricsLogger(path, echo=False)
    for i in range(5):
        log.log("iteration", iteration=i)
    recs = read_jsonl(path)
    assert [r["iteration"] for r in recs] == list(range(5))


def test_read_jsonl_skips_torn_final_line(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "a", "i": 0}) + "\n")
        f.write(json.dumps({"event": "a", "i": 1}) + "\n")
        f.write('{"event": "a", "i": 2, "tru')   # torn mid-record
    recs = read_jsonl(path)
    assert [r["i"] for r in recs] == [0, 1]
    with pytest.raises(ValueError):
        read_jsonl(path, on_error="raise")


# -------------------------------------- MetadataWriter resume paths

def test_metadata_resume_overwrites_reran_epoch(tmp_path):
    from rocalphago_tpu.io.checkpoint import MetadataWriter

    path = str(tmp_path / "metadata.json")
    meta = MetadataWriter(path, header={"cmd": "x"})
    meta.record_epoch({"iteration": 0, "loss": 1.0})
    meta.record_epoch({"iteration": 1, "loss": 0.9})
    # crashed-and-resumed run re-records iteration 1
    meta2 = MetadataWriter(path)
    meta2.record_epoch({"iteration": 1, "loss": 0.9})
    with open(path) as f:
        epochs = json.load(f)["epochs"]
    assert [e["iteration"] for e in epochs] == [0, 1]


def test_metadata_corrupt_file_starts_fresh(tmp_path):
    from rocalphago_tpu.io.checkpoint import MetadataWriter

    path = str(tmp_path / "metadata.json")
    with open(path, "w") as f:
        f.write('{"epochs": [{"iteration":')    # legacy torn write
    meta = MetadataWriter(path, header={"cmd": "x"})
    meta.record_epoch({"iteration": 0})
    with open(path) as f:
        data = json.load(f)
    assert data["cmd"] == "x" and len(data["epochs"]) == 1


# ------------------------------------------- ladder script (ADVICE)

def _load_ladder():
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "zero_ladder_matches.py")
    spec = importlib.util.spec_from_file_location("zero_ladder", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ladder_pool_snapshots_missing_dir_is_usage_error(tmp_path):
    mod = _load_ladder()
    with pytest.raises(SystemExit, match="does not exist"):
        mod.pool_snapshots(str(tmp_path / "no_such_run"))


def test_ladder_pool_snapshots_numeric_sort(tmp_path):
    mod = _load_ladder()
    pool = tmp_path / "run" / "pool"
    pool.mkdir(parents=True)
    # zero-padding narrower than the largest iteration: lexicographic
    # order would yield 10 < 5
    for it in (5, 10, 100):
        (pool / f"best.{it}.policy.msgpack").write_bytes(b"")
    snaps = mod.pool_snapshots(str(tmp_path / "run"))
    assert [it for it, _ in snaps] == [5, 10, 100]


def test_ladder_write_spec_never_clobbers_pool(tmp_path):
    mod = _load_ladder()
    pool = tmp_path / "run" / "pool"
    pool.mkdir(parents=True)
    weights = pool / "best.00005.policy.msgpack"
    weights.write_bytes(b"w")
    tracked = pool / "best.00005.policy.json"
    tracked.write_text('{"tracked": true}')     # git-tracked artifact
    spec_src = tmp_path / "spec.json"
    spec_src.write_text(json.dumps({"class": "CNNPolicy"}))
    out_dir = tmp_path / "specs"
    out_dir.mkdir()
    out = mod.write_spec(str(spec_src), str(weights), str(out_dir))
    assert os.path.dirname(out) == str(out_dir)
    assert tracked.read_text() == '{"tracked": true}'   # untouched
    with open(out) as f:
        spec = json.load(f)
    assert spec["weights_file"] == os.path.abspath(str(weights))


# -------------------------------------------------- compile cache

def test_compile_cache_env_off_and_first_config_wins(monkeypatch):
    """runtime/compilecache.py: the shared persistent-cache helper
    every CLI entry point calls is env-disableable
    (``ROCALPHAGO_COMPILE_CACHE=off``) and NEVER re-points an
    already-configured cache — the suite's conftest pins one, which
    is exactly the first-config-wins case the helper must respect
    (re-pointing mid-process would split one run's compiles across
    two caches)."""
    import jax

    from rocalphago_tpu.runtime.compilecache import enable_compile_cache

    for off in ("0", "off", "NONE", "disabled", " Off "):
        monkeypatch.setenv("ROCALPHAGO_COMPILE_CACHE", off)
        assert enable_compile_cache() is None
    pinned = jax.config.jax_compilation_cache_dir
    assert pinned                   # conftest configured the suite's
    monkeypatch.setenv("ROCALPHAGO_COMPILE_CACHE", "/tmp/elsewhere")
    assert enable_compile_cache() == pinned
    assert jax.config.jax_compilation_cache_dir == pinned


# -------------------------------------------------------- deadline

def test_deadline_semantics():
    from rocalphago_tpu.runtime.deadline import Deadline

    d = Deadline.after(None)
    assert d.unlimited
    assert not d.expired()
    assert d.remaining() is None
    d0 = Deadline.after(0)
    assert d0.expired()
    assert d0.remaining() == 0.0
    assert Deadline.after(-5).expired()      # negative budgets clamp
    d1 = Deadline.after(60)
    assert not d1.expired()
    assert 0 < d1.remaining() <= 60
    assert "unlimited" in repr(d)


def test_deadline_expires_with_wall_clock():
    from rocalphago_tpu.runtime.deadline import Deadline

    d = Deadline.after(0.05)
    assert not d.expired()
    time.sleep(0.08)
    assert d.expired()
    assert d.remaining() == 0.0


# ---------------------------------------- checkpoint restore fallback

def test_checkpoint_restore_falls_back_past_torn_step(tmp_path,
                                                      capsys):
    """Satellite (ISSUE 2): a finalized-then-damaged newest Orbax
    step must not kill the resume — restore warns and falls back to
    the next-older retained step. An EXPLICITLY requested step still
    raises."""
    import shutil

    import numpy as np

    from rocalphago_tpu.io.checkpoint import TrainCheckpointer

    d = str(tmp_path / "ckpt")
    ckpt = TrainCheckpointer(d, max_to_keep=3)
    template = {"w": np.zeros(4, np.float32), "step": 0}
    for s in (1, 2):
        ckpt.save(s, {"w": np.full(4, float(s), np.float32),
                      "step": s}, wait=True)
    ckpt.wait()
    assert ckpt.latest_step() == 2
    # tear the newest step AFTER finalize: rip out its item payload
    # (the torn-directory model — rename already happened, contents
    # later damaged by the flaky filesystem)
    item_dir = os.path.join(d, "2", "default")
    assert os.path.isdir(item_dir)
    shutil.rmtree(item_dir)

    restored, step = ckpt.restore(template)
    assert step == 1
    assert restored["step"] == 1
    assert restored["w"][0] == 1.0
    err = capsys.readouterr().err
    assert "falling back to step 1" in err

    with pytest.raises(Exception):
        ckpt.restore(template, step=2)       # asked-for step: honest
    ckpt.close()
