"""Self-play economics (docs/PERFORMANCE.md "Self-play economics"):
playout-cap randomization, forced-playout policy-target pruning, and
the auxiliary ownership/score labels — plus the flags-OFF bit-identity
guarantees the whole layer is gated behind.

Same fake-backend strategy as tests/test_device_mcts.py: injected
jittable policy/value callables, tiny boards, so every path runs as
the compiled programs it is in production with no trained nets.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import jaxgo
from rocalphago_tpu.engine.jaxgo import GoConfig, new_states
from rocalphago_tpu.search.device_mcts import (
    make_device_mcts,
    make_mcts_selfplay,
)

SIZE = 5
N = SIZE * SIZE
FEATS = ("board", "ones")
VFEATS = FEATS + ("color",)
CFG = GoConfig(size=SIZE)


def fake_policy(params, planes):
    return jnp.zeros((planes.shape[0], N))


def fake_value(params, planes):
    mine = planes[..., 0].sum(axis=(1, 2))
    theirs = planes[..., 1].sum(axis=(1, 2))
    return (mine - theirs) / N


# ------------------------------------------------ masked budget runs


def test_full_budget_matches_plain_run():
    """A budget of n_sim on every row must be the plain chunked run
    bit-for-bit — the masked program is the SAME search with rows
    switched off, so all-on is the identity."""
    s = make_device_mcts(CFG, FEATS, VFEATS, fake_policy, fake_value,
                         n_sim=16, max_nodes=32)
    roots = new_states(CFG, 2)
    t1 = s.init(None, None, roots)
    t1, ran1 = s.run_sims_chunked(None, None, t1, 4, owned=True)
    v1, q1 = jax.device_get(s.root_stats(t1))
    t2 = s.init(None, None, roots)
    t2, ran2 = s.run_sims_chunked(None, None, t2, 4, owned=True,
                                  budget=jnp.full((2,), 16, jnp.int32))
    v2, q2 = jax.device_get(s.root_stats(t2))
    assert ran1 == ran2 == 16
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(q1, q2)


def test_mixed_budget_rows_stop_at_cap():
    """Mixed per-row budgets in ONE slab program: each cheap row's
    root visits total exactly its budget, and a full-budget row is
    bit-identical to the same row of an unmasked run (rows are
    independent per-game trees — masking a neighbor must not leak)."""
    s = make_device_mcts(CFG, FEATS, VFEATS, fake_policy, fake_value,
                         n_sim=16, max_nodes=32)
    roots = new_states(CFG, 3)
    budget = jnp.array([4, 16, 9], jnp.int32)
    tree = s.init(None, None, roots)
    tree, _ = s.run_sims_chunked(None, None, tree, 5, owned=True,
                                 budget=budget)
    v, q = jax.device_get(s.root_stats(tree))
    np.testing.assert_array_equal(v.sum(axis=1), np.asarray(budget))
    plain = s.init(None, None, roots)
    plain, _ = s.run_sims_chunked(None, None, plain, 5, owned=True)
    vp, qp = jax.device_get(s.root_stats(plain))
    np.testing.assert_array_equal(v[1], vp[1])
    np.testing.assert_array_equal(q[1], qp[1])


# ------------------------------------- forced playouts + pruning


def test_pruned_targets_sum_to_one_and_zero_forced_only():
    """KataGo target pruning: the recorded distribution sums to 1,
    keeps the most-visited child whole, and zeroes children whose
    visits don't clear the forced floor — forced-only exploration
    must not teach the policy."""
    s = make_device_mcts(CFG, FEATS, VFEATS, fake_policy, fake_value,
                         n_sim=32, max_nodes=64, forced_k=2.0)
    roots = new_states(CFG, 2)
    tree = s.init(None, None, roots)
    tree = s.run_sims(None, None, tree, k=32)
    visits, _ = jax.device_get(s.root_stats(tree))
    target, pruned = jax.device_get(s.pruned_targets(tree))
    np.testing.assert_allclose(target.sum(axis=-1), 1.0, rtol=1e-5)
    assert (target >= 0).all()
    assert ((target > 0) <= (visits > 0)).all(), (
        "target puts mass on an unvisited child")
    # uniform priors at 32 sims: floor = sqrt(2·32/25) ≈ 1.6, so
    # 1-visit children are forced-only and must be zeroed
    assert ((visits > 0) & (target == 0)).any()
    assert (pruned > 0).all()
    best = visits.argmax(axis=-1)
    assert (target[np.arange(2), best] > 0).all()
    np.testing.assert_array_equal(target.argmax(axis=-1), best)


def test_pruned_targets_reduce_to_visits_without_forcing():
    """forced_k=0: the floor is 0 and the target is exactly the
    normalized visit distribution with nothing pruned."""
    s = make_device_mcts(CFG, FEATS, VFEATS, fake_policy, fake_value,
                         n_sim=16, max_nodes=32)
    roots = new_states(CFG, 2)
    tree = s.init(None, None, roots)
    tree = s.run_sims(None, None, tree, k=16)
    visits, _ = jax.device_get(s.root_stats(tree))
    target, pruned = jax.device_get(s.pruned_targets(tree))
    np.testing.assert_array_equal(pruned, 0)
    np.testing.assert_allclose(
        target, visits / visits.sum(axis=-1, keepdims=True), rtol=1e-6)


# ------------------------------------------------ self-play gating


def _selfplay_kwargs(**over):
    kw = dict(batch=2, max_moves=6, n_sim=8, max_nodes=16, sim_chunk=4,
              record_visits=True)
    kw.update(over)
    return kw


def test_selfplay_flags_off_identity():
    """Explicitly-disabled economics kwargs must be the default path
    bit-for-bit — actions, live mask, targets, and the rng chain all
    untouched (the OFF path never splits the game rng)."""
    base = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                              fake_value, **_selfplay_kwargs())
    off = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value,
                             **_selfplay_kwargs(cap_p=0.0, cap_cheap=2,
                                                forced_k=0.0))
    out_b = jax.device_get(base(None, None, jax.random.key(5)))
    out_o = jax.device_get(off(None, None, jax.random.key(5)))
    assert len(out_b) == len(out_o) == 4       # no full mask when OFF
    for a, b in zip(jax.tree.leaves(out_b), jax.tree.leaves(out_o)):
        np.testing.assert_array_equal(a, b)
    assert np.asarray(out_b[3]).dtype == np.int32


def test_selfplay_cap_correlated_draw_and_budget_sums():
    """Correlated (default) cap draw: every row of a ply shares one
    Bernoulli, the returned full mask matches, and each ply's target
    visit total is exactly the drawn budget — cheap plies stop at the
    cap, full plies run the whole n_sim."""
    run = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value,
                             **_selfplay_kwargs(batch=4, cap_p=0.5,
                                                cap_cheap=2))
    final, actions, live, targets, full = run(None, None,
                                              jax.random.key(0))
    f = np.asarray(full)
    lv = np.asarray(live)
    t = np.asarray(targets)
    assert f.dtype == np.bool_ and f.shape == lv.shape
    assert t.dtype == np.int32
    assert (f == f[:, :1]).all(), "correlated draw differs in-batch"
    sums = t.sum(axis=-1)
    np.testing.assert_array_equal(
        sums, np.where(lv, np.where(f, 8, 2), 0))


def test_selfplay_cap_per_row_budgets():
    """Per-row (iid) draw: rows of one ply may differ, and each row's
    visit total still matches its own draw."""
    run = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value,
                             **_selfplay_kwargs(batch=4, cap_p=0.5,
                                                cap_cheap=2,
                                                cap_per_row=True))
    _, _, live, targets, full = run(None, None, jax.random.key(2))
    f = np.asarray(full)
    lv = np.asarray(live)
    sums = np.asarray(targets).sum(axis=-1)
    np.testing.assert_array_equal(
        sums, np.where(lv, np.where(f, 8, 2), 0))


def test_selfplay_forced_k_records_pruned_distribution():
    """forced_k on its own: moves still come from RAW visits, but the
    recorded target is the pruned float distribution."""
    run = make_mcts_selfplay(CFG, FEATS, VFEATS, fake_policy,
                             fake_value,
                             **_selfplay_kwargs(forced_k=1.0))
    _, actions, live, targets = run(None, None, jax.random.key(1))
    t = np.asarray(targets)
    assert t.dtype == np.float32
    lv = np.asarray(live)
    np.testing.assert_allclose(t.sum(axis=-1)[lv], 1.0, rtol=1e-5)
    acts = np.asarray(actions)
    assert ((acts >= 0) & (acts <= N)).all()


# ------------------------------------------------ terminal labels


def test_terminal_labels_parity_with_engine_scoring():
    """ops.labels.terminal_labels must agree with the engine's area
    scoring exactly: score == black − white_plus_komi, sign(score) ==
    jaxgo.winner, and the per-point ownership counts reproduce the
    score (ownership IS the area verdict per point)."""
    from benchmarks._harness import random_game_states
    from rocalphago_tpu.ops.labels import terminal_labels

    states = random_game_states(CFG, 8, 40, jax.random.key(2))
    own, score = jax.device_get(
        jax.vmap(lambda s: terminal_labels(CFG, s))(states))
    b, w = jax.device_get(
        jax.vmap(lambda s: jaxgo.area_scores(CFG, s))(states))
    np.testing.assert_allclose(
        score, np.asarray(b, np.float32) - np.asarray(w, np.float32))
    winners = jax.device_get(
        jax.vmap(lambda s: jaxgo.winner(CFG, s))(states))
    np.testing.assert_array_equal(
        np.sign(score).astype(np.int32), winners)
    assert own.dtype == np.int8
    assert set(np.unique(own)) <= {-1, 0, 1}
    np.testing.assert_allclose(
        (own == 1).sum(axis=-1) - (own == -1).sum(axis=-1) - CFG.komi,
        score)


# ------------------------------------------------ aux value heads


def test_aux_heads_graft_keeps_value_bit_identical():
    """with_aux_heads: the grown net's value output is the trained
    net's bit-for-bit (trunk + value head copied by value); the new
    heads predict with the right shapes."""
    from rocalphago_tpu.models import CNNValue
    from rocalphago_tpu.models.value import with_aux_heads

    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    grown = with_aux_heads(val, seed=3)
    assert grown.module.aux_heads == ("ownership", "score")
    from rocalphago_tpu.engine import pygo

    st = pygo.GameState(size=SIZE)
    st.do_move((1, 1), pygo.BLACK)
    v0 = val.batch_eval_state([st])
    v1 = grown.batch_eval_state([st])
    np.testing.assert_array_equal(v0, v1)
    planes = grown._states_to_planes([st])
    v, aux = jax.device_get(grown.forward_aux(planes))
    np.testing.assert_array_equal(np.asarray(v), v1)
    assert aux["ownership"].shape == (1, N)
    assert (np.abs(aux["ownership"]) <= 1.0).all()
    assert aux["score"].shape == (1,)
    # unknown head names rejected up front
    with pytest.raises(ValueError, match="aux heads"):
        CNNValue.create_network(board=SIZE, aux_heads=("bogus",))


# ------------------------------------------------ zero iteration


def _make_iteration(pol, val, **over):
    import optax
    from rocalphago_tpu.training.zero import make_zero_iteration

    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    kw = dict(batch=2, move_limit=6, n_sim=4, max_nodes=8, sim_chunk=2,
              replay_chunk=6)
    kw.update(over)
    return (make_zero_iteration(
        CFG, FEATS, VFEATS, pol.module.apply, val.module.apply,
        tx_p, tx_v, **kw), tx_p, tx_v)


def _state_fingerprint(state):
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def test_zero_iteration_flags_off_identity():
    """One full zero iteration with every economics kwarg explicitly
    disabled must produce the SAME state (params, opt state, rng) as
    the default build — the gate is trace-time, so OFF means the
    pre-economics programs run unchanged."""
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.training.zero import init_zero_state

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4)
    it0, tx_p, tx_v = _make_iteration(pol, val)
    it1, _, _ = _make_iteration(pol, val, cap_p=0.0, cap_cheap=1,
                                forced_k=0.0, aux_weight=0.0)
    s0 = init_zero_state(pol.params, val.params, tx_p, tx_v, seed=0)
    new0, _ = it0(s0)
    s1 = init_zero_state(pol.params, val.params, tx_p, tx_v, seed=0)
    new1, _ = it1(s1)
    assert _state_fingerprint(new0) == _state_fingerprint(new1)


@pytest.mark.slow
def test_zero_iteration_econ_aux_end_to_end():
    """Everything ON at once (cap + forcing + aux heads): the
    iteration runs end-to-end, aux losses are finite, the record
    carries the full mask and labels, and a v1-shaped record (full
    stripped) still learns — the learner synthesizes all-full."""
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.models.value import with_aux_heads
    from rocalphago_tpu.training.zero import init_zero_state

    pol = CNNPolicy(FEATS, board=SIZE, layers=1, filters_per_layer=4)
    val = with_aux_heads(
        CNNValue(VFEATS, board=SIZE, layers=1, filters_per_layer=4))
    import functools

    it, tx_p, tx_v = _make_iteration(
        pol, val, move_limit=8, cap_p=0.5, cap_cheap=2, forced_k=1.0,
        aux_weight=0.5,
        value_apply_aux=functools.partial(val.module.apply,
                                          with_aux=True))
    state = init_zero_state(pol.params, val.params, tx_p, tx_v, seed=1)
    import jax.random as jrandom

    from rocalphago_tpu.io.checkpoint import unpack_rng

    _, game_key = jrandom.split(unpack_rng(state.rng))
    games = jax.device_get(it.play(state.policy_params,
                                   state.value_params, game_key))
    assert games.full is not None and games.full.dtype == np.bool_
    assert games.ownership is not None and games.score is not None
    new, m = it.learn(state, games)
    for key in ("policy_loss", "value_loss", "aux_loss_ownership",
                "aux_loss_score"):
        assert np.isfinite(float(jax.device_get(m[key]))), key
    # v1-shaped record: the full mask absent -> treated as all-full
    v1_games = games._replace(full=None)
    new2, m2 = it.learn(state, v1_games)
    assert np.isfinite(float(jax.device_get(m2["policy_loss"])))
