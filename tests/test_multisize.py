"""Multi-size subsystem: FCN heads applying one checkpoint at every
board size, the MultiSizePool serving ladder + GTP boardsize
re-routing, per-session komi as data, and the progressive-size
curriculum driver.

Tiny nets and small boards throughout; the board-size PARAMETRIZATION
is the point — the same param pytree must apply and stay
symmetry-honest at every size.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.models import CNNPolicy, CNNValue

SIZE = 5
FEATS = ("board", "ones")
VFEATS = FEATS + ("color",)


@pytest.fixture(scope="module")
def fcn_nets():
    pol = CNNPolicy(FEATS, board=SIZE, layers=2, filters_per_layer=4)
    val = CNNValue(VFEATS, board=SIZE, layers=2, filters_per_layer=4)
    return pol, val


def _dense_value():
    os.environ["ROCALPHAGO_VALUE_HEAD"] = "dense"
    try:
        return CNNValue(VFEATS, board=SIZE, layers=2,
                        filters_per_layer=4)
    finally:
        del os.environ["ROCALPHAGO_VALUE_HEAD"]


# ------------------------------------------------------ FCN heads


def test_policy_fcn_vs_bias_head_ab_fixed_seed(fcn_nets):
    """A fresh net is bit-identical under either policy head: the
    legacy per-position bias initializes to zeros, so head='fcn'
    (which omits it) changes nothing until training moves it."""
    pol, _ = fcn_nets
    legacy = CNNPolicy(FEATS, board=SIZE, layers=2,
                       filters_per_layer=4, head="bias")
    planes = jnp.zeros((2, SIZE, SIZE, pol.preprocess.output_dim))
    planes = planes.at[0, 2, 2, 0].set(1.0)
    a = np.asarray(pol.forward(planes))
    b = np.asarray(legacy.forward(planes))
    np.testing.assert_array_equal(a, b)
    assert pol.size_generic() and not legacy.size_generic()


def test_value_head_env_knob_and_size_lock(fcn_nets):
    _, val = fcn_nets
    dense = _dense_value()
    assert val.size_generic() and not dense.size_generic()
    with pytest.raises(ValueError, match="MULTISIZE"):
        dense.at_board(9)
    # the facade at the native size is the net itself
    assert val.at_board(SIZE) is val


@pytest.mark.parametrize("size", [7, 9, 13])
def test_one_checkpoint_applies_at_every_size(tmp_path, fcn_nets,
                                              size):
    """Save at 5, load, apply at 7/9/13: same param pytree (shared by
    reference), right output shapes, finite values."""
    pol, val = fcn_nets
    pj = os.path.join(tmp_path, "policy.json")
    vj = os.path.join(tmp_path, "value.json")
    pol.save_model(pj)
    val.save_model(vj)
    from rocalphago_tpu.models.nn_util import NeuralNetBase

    for src, loaded in ((pol, NeuralNetBase.load_model(pj)),
                        (val, NeuralNetBase.load_model(vj))):
        facade = loaded.at_board(size)
        assert facade.board == size
        assert facade.params is loaded.params
        planes = jnp.zeros(
            (1, size, size, facade.preprocess.output_dim))
        out = np.asarray(facade.forward(planes))
        want = (1, size * size) if src is pol else (1,)
        assert out.shape == want
        assert np.isfinite(out).all()
    # loaded weights match the saved net bit-for-bit
    for a, b in zip(jax.tree.leaves(pol.params),
                    jax.tree.leaves(
                        NeuralNetBase.load_model(pj).params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("size", [5, 9, 13])
def test_value_symmetric_invariant_across_sizes(fcn_nets, size):
    """The dihedral-ensembled value is invariant under any board
    transform AT EVERY SIZE the facade serves — the invariance audit
    the multi-size pool leans on."""
    from rocalphago_tpu.training.symmetries import transform_planes

    _, val = fcn_nets
    net = val.at_board(size)
    rng = np.random.default_rng(size)
    planes = jnp.asarray(rng.standard_normal(
        (1, size, size, net.preprocess.output_dim)), jnp.float32)
    base = np.asarray(net.forward_symmetric(planes))
    for t in range(8):
        tp = jax.vmap(lambda x: transform_planes(x, t))(planes)
        np.testing.assert_allclose(
            np.asarray(net.forward_symmetric(tp)), base,
            rtol=0, atol=1e-5)


@pytest.mark.parametrize("size", [5, 9, 13, 19])
def test_symmetry_transforms_round_trip(size):
    """transform/inverse_transform are exact inverses and the action
    map agrees with the plane map, at every supported size (pass maps
    to itself)."""
    from rocalphago_tpu.training.symmetries import (
        inverse_transform_planes,
        transform_action,
        transform_planes,
    )

    rng = np.random.default_rng(size)
    x = jnp.asarray(rng.standard_normal((size, size, 2)), jnp.float32)
    n = size * size
    action = jnp.int32(1 * size + 2)       # an off-axis point
    onehot = jnp.zeros((size, size, 1)).at[1, 2, 0].set(1.0)
    for t in range(8):
        rt = inverse_transform_planes(transform_planes(x, t), t)
        np.testing.assert_array_equal(np.asarray(rt), np.asarray(x))
        moved = int(transform_action(action, t, size))
        grid = np.asarray(transform_planes(onehot, t))[:, :, 0]
        assert moved == int(np.flatnonzero(grid.reshape(n))[0])
        assert int(transform_action(jnp.int32(n), t, size)) == n


# ------------------------------------------------- per-session komi


@pytest.fixture(scope="module")
def komi_search(fcn_nets):
    from rocalphago_tpu.search.device_mcts import make_device_mcts

    pol, val = fcn_nets
    return make_device_mcts(pol.cfg, pol.feature_list,
                            val.feature_list, pol.module.apply,
                            val.module.apply, n_sim=6)


def _done_pair(cfg):
    """[live, done-by-two-passes] batch of empty-board states."""
    live = jaxgo.from_pygo(cfg, pygo.GameState(size=cfg.size,
                                               komi=cfg.komi))
    g = pygo.GameState(size=cfg.size, komi=cfg.komi)
    g.do_move(None)
    g.do_move(None)
    done = jaxgo.from_pygo(cfg, g)
    return jax.tree.map(lambda a, b: jnp.stack([a, b]), live, done)


def test_eval_batch_komi_default_is_bit_compat(fcn_nets, komi_search):
    pol, val = fcn_nets
    states = _done_pair(pol.cfg)
    p0, v0 = komi_search.eval_batch(pol.params, val.params, states)
    p1, v1 = komi_search.eval_batch_komi(
        pol.params, val.params, states,
        jnp.full((2,), pol.cfg.komi, jnp.float32))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


def test_eval_batch_komi_flips_terminal_sign(fcn_nets, komi_search):
    """Empty board, two passes: white wins by komi at the default;
    at komi=-25 the margin flips, so the terminal value flips."""
    pol, val = fcn_nets
    states = _done_pair(pol.cfg)
    _, v0 = komi_search.eval_batch(pol.params, val.params, states)
    _, v2 = komi_search.eval_batch_komi(
        pol.params, val.params, states,
        jnp.array([pol.cfg.komi, -25.0], jnp.float32))
    assert float(v2[1]) == -float(v0[1]) != 0.0


def test_pool_komi_session_and_pinned_default_path(fcn_nets):
    from rocalphago_tpu.serve.sessions import ServePool

    pol, val = fcn_nets
    pool = ServePool(val, pol, n_sim=4, batch_sizes=(1, 2, 4))
    try:
        sess = pool.open_session(resilient=False, komi=0.5)
        mv = sess.get_move(pygo.GameState(size=SIZE, komi=0.5))
        assert mv is None or isinstance(mv, tuple)
        st = pool.stats()
        assert st["evaluator"]["komi_batches"] >= 1
        assert st["board"] == SIZE
        assert st["komi_default"] == float(pol.cfg.komi)
        # a default-komi session stays on the pinned program
        before = pool.evaluator.komi_batches
        s2 = pool.open_session(resilient=False)
        s2.get_move(pygo.GameState(size=SIZE, komi=pol.cfg.komi))
        assert pool.evaluator.komi_batches == before
        # komi re-threads live (the GTP komi command's path)
        s2.set_komi(0.5)
        assert s2.komi == 0.5
    finally:
        pool.close()


# --------------------------------------------------- MultiSizePool


@pytest.fixture(scope="module")
def msize_pool(fcn_nets):
    from rocalphago_tpu.multisize import MultiSizePool

    pol, val = fcn_nets
    pool = MultiSizePool(val, pol, sizes=(5, 7), n_sim=4,
                         batch_sizes=(1, 2, 4))
    yield pool
    pool.close()


def test_multisize_routing_shares_one_checkpoint(fcn_nets,
                                                 msize_pool):
    pol, val = fcn_nets
    assert msize_pool.sizes == (5, 7)
    assert msize_pool.default_size == 5
    p7 = msize_pool.pool_for(7)
    assert p7.policy.params is pol.params
    assert p7.value.params is val.params
    s5 = msize_pool.open_session(resilient=False)
    s7 = msize_pool.open_session(size=7, resilient=False)
    try:
        assert s5.raw.board == 5 and s7.raw.board == 7
        s5.get_move(pygo.GameState(size=5))
        s7.get_move(pygo.GameState(size=7))
        with pytest.raises(ValueError, match="one board size"):
            msize_pool.driver([s5, s7])
    finally:
        s5.close()
        s7.close()


def test_multisize_probe_schema_and_add_size(msize_pool):
    st = msize_pool.stats()
    assert st["multisize"] is True
    assert st["default_board"] == 5
    assert set(st["boards"]) == {str(s) for s in msize_pool.sizes}
    for size, row in st["boards"].items():
        assert row["board"] == int(size)
        assert "komi_batches" in row["evaluator"]
    assert st["sessions_live"] == sum(
        b["sessions"]["live"] for b in st["boards"].values())
    with pytest.raises(KeyError, match="add_size"):
        msize_pool.pool_for(11)
    msize_pool.add_size(11)
    assert 11 in msize_pool.sizes


def test_multisize_refuses_size_locked_heads(fcn_nets):
    from rocalphago_tpu.multisize import MultiSizePool

    pol, _ = fcn_nets
    with pytest.raises(ValueError, match="MULTISIZE"):
        MultiSizePool(_dense_value(), pol, sizes=(5, 7))


def test_gtp_boardsize_reroutes_and_carries_komi(msize_pool):
    from rocalphago_tpu.interface.gtp import GTPEngine

    sess = msize_pool.open_session(resilient=True)
    eng = GTPEngine(sess.player, serve_pool=msize_pool,
                    serve_session=sess)
    assert eng.size == 5
    r, _ = eng.handle("1 komi 6.5\n")
    assert r.startswith("=1")
    r, _ = eng.handle("2 boardsize 7\n")
    assert r.startswith("=2"), r
    assert eng.size == 7
    assert eng._serve_session is not sess
    assert eng._serve_session.raw.board == 7
    assert eng._serve_session.komi == 6.5
    r, _ = eng.handle("3 genmove b\n")
    assert r.startswith("=3"), r
    # a size the ladder does not serve is still refused
    r, _ = eng.handle("4 boardsize 17\n")
    assert r.startswith("?4"), r
    eng._serve_session.close()


# ------------------------------------------------------ curriculum


def _save_pair(tmp_path, pol, val):
    pj = os.path.join(tmp_path, "policy.json")
    vj = os.path.join(tmp_path, "value.json")
    pol.save_model(pj)
    val.save_model(vj)
    return pj, vj


def test_curriculum_stages_hand_off_checkpoints(tmp_path, fcn_nets,
                                                monkeypatch):
    """Fast plumbing test: run_training stubbed out — proves the
    stage sequencing, at_board checkpoint handoff, per-stage argv
    (iterations/seed appended last so they win), span + event
    emission into the CURRICULUM stream."""
    from rocalphago_tpu.models.nn_util import NeuralNetBase
    from rocalphago_tpu.training import curriculum, zero

    calls = []

    def fake_run_training(argv):
        calls.append(list(argv))
        p_json, v_json, out_dir = argv[0], argv[1], argv[2]
        os.makedirs(out_dir, exist_ok=True)
        for name, src in (("policy", p_json), ("value", v_json)):
            net = NeuralNetBase.load_model(src)
            net.save_model(os.path.join(out_dir, f"{name}.json"))
        return {"iteration": 0, "policy_loss": 1.0}

    monkeypatch.setattr(zero, "run_training", fake_run_training)
    pol, val = fcn_nets
    pj, vj = _save_pair(tmp_path, pol, val)
    out = os.path.join(tmp_path, "run")
    summary = curriculum.run_curriculum(
        [pj, vj, out, "--stages", "5:1,7:2", "--seed", "3",
         "--sims", "4"])

    assert [s["board"] for s in summary["stages"]] == [5, 7]
    assert len(calls) == 2
    for argv, iters, seed in zip(calls, ("1", "2"), ("3", "4")):
        assert argv[argv.index("--iterations") + 1] == iters
        assert argv[argv.index("--seed") + 1] == seed
        assert "--sims" in argv          # passthrough forwarded
    # stage 1 trained on stage 0's export re-boarded to 7
    s1_in = NeuralNetBase.load_model(calls[1][0])
    assert s1_in.board == 7
    s0_out = NeuralNetBase.load_model(
        os.path.join(out, "stage00_b5", "policy.json"))
    for a, b in zip(jax.tree.leaves(s0_out.params),
                    jax.tree.leaves(s1_in.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert summary["final_policy"].endswith(
        os.path.join("stage01_b7", "policy.json"))

    events = [json.loads(line)
              for line in open(os.path.join(out, "metrics.jsonl"))]
    kinds = [e["event"] for e in events]
    assert kinds.count("curriculum_stage") == 2
    spans = [e for e in events if e["event"] == "span"
             and e.get("name") == "curriculum.stage"]
    assert {s["board"] for s in spans} == {5, 7}


def test_parse_stages_rejects_malformed():
    from rocalphago_tpu.training.curriculum import parse_stages

    assert parse_stages("9:30,13:20") == [(9, 30), (13, 20)]
    for bad in ("9x30", "9:", "", "1:5", "9:0"):
        with pytest.raises(ValueError):
            parse_stages(bad)


@pytest.mark.slow
def test_curriculum_two_stage_real(tmp_path, fcn_nets):
    """The real thing, tiny: two zero stages 5x5 -> 7x7 plus the
    Wilson-gated transferred-vs-fresh match at 7x7."""
    from rocalphago_tpu.training.curriculum import run_curriculum

    pol, val = fcn_nets
    pj, vj = _save_pair(tmp_path, pol, val)
    out = os.path.join(tmp_path, "run")
    summary = run_curriculum(
        [pj, vj, out, "--stages", "5:1,7:1", "--game-batch", "2",
         "--sims", "4", "--move-limit", "12", "--save-every", "1",
         "--no-gating", "--transfer-games", "4",
         "--transfer-move-limit", "20"])
    assert os.path.exists(
        os.path.join(out, "stage01_b7", "policy.json"))
    tr = summary["transfer"]
    assert tr["board"] == 7 and isinstance(tr["transfer"], bool)
    assert 0.0 <= tr["wilson_lb"] <= 1.0
